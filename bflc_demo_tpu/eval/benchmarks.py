"""Benchmark harnesses on the BASELINE.md axes:
FL round time (s), global test-acc, samples/sec/chip.

Config 1 is the reference-equivalence run (SURVEY.md §6): softmax regression
on occupancy data, 20 clients / committee 4 / top-6, target ≈0.92 test-acc by
round ~10.  The reference's wall-clock per round is dominated by 10-30 s
polling sleeps (main.py:231-233); ours is actual compute + coordination, so
round time is the headline win.
"""

from __future__ import annotations

import json
import os
import struct
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

from bflc_demo_tpu.protocol.constants import (DEFAULT_PROTOCOL,
                                              ProtocolConfig)

# NOTE: the FL-runtime imports (jax-heavy) are deliberately lazy — the
# control-plane benchmarks below are spawned into light subprocesses for
# their legacy-mode baseline leg, and those children must not pay a full
# jax initialisation to time some Ed25519 and socket code.


def bench_config1(rounds: int = 10, ledger_backend: str = "auto",
                  seed: int = 0, verbose: bool = False,
                  runtime: str = "host",
                  rounds_per_dispatch: int = 1,
                  estimate_flops: bool = False) -> Dict:
    """runtime: 'host' (per-client dispatches, reference-shaped) or 'mesh'
    (one XLA program per round — the TPU-first data plane).
    rounds_per_dispatch > 1 (mesh only) batches R rounds per dispatch with
    post-hoc ledger audit.
    estimate_flops (mesh, rounds_per_dispatch=1 only): record XLA
    cost-analysis FLOPs/round and MFU against the chip peak (eval.mfu)."""
    from bflc_demo_tpu.client.mesh_runtime import run_federated_mesh
    from bflc_demo_tpu.client.simulation import run_federated
    from bflc_demo_tpu.data import load_occupancy, iid_shards
    from bflc_demo_tpu.models import make_softmax_regression

    if runtime not in ("host", "mesh"):
        raise ValueError(f"runtime must be 'host' or 'mesh', got {runtime!r}")
    if runtime == "host" and rounds_per_dispatch > 1:
        raise ValueError("rounds_per_dispatch applies to runtime='mesh' only")
    cfg = DEFAULT_PROTOCOL
    xtr, ytr, xte, yte = load_occupancy()
    shards = iid_shards(xtr, ytr, cfg.client_num)
    model = make_softmax_regression()
    if runtime == "host":
        res = run_federated(model, shards, (xte, yte), cfg, rounds=rounds,
                            ledger_backend=ledger_backend, seed=seed,
                            verbose=verbose)
    else:
        res = run_federated_mesh(model, shards, (xte, yte), cfg,
                                 rounds=rounds,
                                 ledger_backend=ledger_backend, seed=seed,
                                 rounds_per_dispatch=rounds_per_dispatch,
                                 estimate_flops=estimate_flops,
                                 verbose=verbose)
    # samples/sec/chip — count the work each runtime actually does:
    # host: the K uploaders train their own shards, one chip;
    # mesh: ALL clients train max-padded shards (cyclic repetition for
    # static shapes), spread over n_chips
    n_chips = res.n_devices     # what the runtime actually used
    if runtime == "host":
        samples_per_round = sum(
            (len(sx) // cfg.batch_size) * cfg.batch_size * cfg.local_epochs
            for sx, _ in shards[:cfg.needed_update_count])
    else:
        s_pad = max(len(sx) for sx, _ in shards)
        samples_per_round = (cfg.client_num *
                             (s_pad // cfg.batch_size) * cfg.batch_size *
                             cfg.local_epochs)
    mean_round = (sum(res.round_times_s) / len(res.round_times_s)
                  if res.round_times_s else float("inf"))
    # warm mean: drop the compile-bearing first dispatch (the first
    # rounds_per_dispatch entries share that dispatch's cost) — the
    # steady-state per-round price a user actually pays
    warm = res.round_times_s[rounds_per_dispatch:]
    warm_mean = sum(warm) / len(warm) if warm else mean_round
    # run-to-run honesty (VERDICT r4 weak #4: a mean with no spread is
    # untrendable on a contended shared-CPU host): std + CV over the warm
    # rounds, and the warm median as the outlier-robust central value
    if warm:
        import statistics
        warm_std = statistics.pstdev(warm)
        warm_median = statistics.median(warm)
    else:
        warm_std, warm_median = 0.0, mean_round
    out = {
        "rounds": res.rounds_completed,
        "final_acc": res.final_accuracy,
        "best_acc": res.best_accuracy(),
        "mean_round_time_s": mean_round,
        "warm_mean_round_time_s": warm_mean,
        "warm_median_round_time_s": warm_median,
        "warm_std_round_time_s": warm_std,
        "warm_cv": (warm_std / warm_mean) if warm_mean else 0.0,
        "min_round_time_s": min(res.round_times_s, default=float("inf")),
        "wall_time_s": res.wall_time_s,
        "train_samples_per_sec_per_chip": (samples_per_round / n_chips
                                           / warm_mean),
        "accuracy_history": res.accuracy_history,
        "loss_history": res.loss_history,
        "ledger_log_size": res.ledger_log_size,
    }
    if estimate_flops and res.flops_per_round:
        from bflc_demo_tpu.eval.mfu import chip_peak_flops
        out["flops_per_round"] = res.flops_per_round
        peak = chip_peak_flops()
        if peak:
            out["mfu"] = res.mfu(peak * n_chips)
    return out


def endurance_config1(rounds: int = 50, ledger_backend: str = "auto",
                      seed: int = 0, rounds_per_dispatch: int = 5,
                      snapshot_interval: int = 0,
                      wal_rounds: int = 240) -> Dict:
    """The DECLARED metric axis, finally measured (VERDICT r5 missing #2):
    BASELINE.json's metric is "test-acc @ round 50", yet no artifact ever
    ran 50 rounds.  This does — config 1 end to end on whatever platform
    is present (CPU needs no tunnel) — and audits the property the
    architecture exists for: epoch progress is strictly monotone across
    the whole campaign (every sponsor observation advances the epoch; no
    round is lost or replayed).

    snapshot_interval > 0 additionally runs the SNAPSHOT-ARMED
    endurance leg (the ROADMAP "endurance at snapshot scale" item):
    `wal_rounds` scripted config-1-geometry rounds on a WAL-attached
    ledger, once with a certified snapshot + prefix GC every
    `snapshot_interval` rounds and once unarmed — returned under
    ``wal`` with the per-round journal-size trajectory evidence that
    the armed journal is BOUNDED (sawtooth) while the legacy one grows
    linearly.  tests/test_endurance.py asserts the bound at 240 rounds.

    Returns {rounds_completed, test_acc_at_round_50 (or at `rounds`),
    best_test_acc, epochs_monotone, wall_time_s[, wal]}.
    """
    from bflc_demo_tpu.client.mesh_runtime import run_federated_mesh
    from bflc_demo_tpu.data import load_occupancy, iid_shards
    from bflc_demo_tpu.models import make_softmax_regression

    cfg = DEFAULT_PROTOCOL
    xtr, ytr, xte, yte = load_occupancy()
    shards = iid_shards(xtr, ytr, cfg.client_num)
    model = make_softmax_regression()
    res = run_federated_mesh(model, shards, (xte, yte), cfg,
                             rounds=rounds, ledger_backend=ledger_backend,
                             seed=seed,
                             rounds_per_dispatch=rounds_per_dispatch)
    epochs = [e for e, _ in res.accuracy_history]
    accs = [a for _, a in res.accuracy_history]
    tail = accs[-10:] if len(accs) >= 10 else accs
    out = {
        "rounds_completed": res.rounds_completed,
        f"test_acc_at_round_{rounds}": round(res.final_accuracy, 4),
        # the oscillation-robust plateau estimate: a single round's acc on
        # an ill-conditioned trajectory is a lottery draw; the last-10
        # mean is what the campaign actually converged around
        "tail10_mean_test_acc": round(float(sum(tail) / len(tail)), 4)
        if tail else 0.0,
        "best_test_acc": round(res.best_accuracy(), 4),
        "epochs_monotone": bool(
            all(b > a for a, b in zip(epochs, epochs[1:]))
            and len(epochs) == rounds),
        "wall_time_s": round(res.wall_time_s, 3),
    }
    if snapshot_interval > 0:
        out["wal"] = _endurance_wal_leg(wal_rounds, snapshot_interval)
    return out


def _endurance_wal_leg(rounds: int = 240,
                       snapshot_interval: int = 16) -> Dict:
    """Bounded-journal evidence at endurance scale: `rounds` scripted
    config-1-geometry rounds driven directly on a WAL-attached python
    ledger (op application is the work both variants share; no sockets,
    so hundreds of rounds take seconds), run twice —

    - **armed**: every `snapshot_interval` epochs the writer-shaped
      sequence runs (encode state, snapshot op, `gc_prefix` → WAL2
      compaction, exactly `comm.ledger_service._emit_snapshot` /
      `_maybe_finalize_snapshot` order);
    - **legacy**: the same chain with no snapshots (the pre-PR-7
      unbounded journal).

    Samples the on-disk journal size after every round.  The armed
    journal must sawtooth within ~one interval of ops while the legacy
    one grows linearly with the chain.
    """
    import os as _os
    import tempfile

    import hashlib as _hl

    from bflc_demo_tpu.ledger import LedgerStatus, make_ledger
    from bflc_demo_tpu.ledger.snapshot import make_snapshot_op

    cfg = DEFAULT_PROTOCOL

    def leg(armed: bool):
        with tempfile.TemporaryDirectory(prefix="bflc-endur-wal-") as td:
            path = _os.path.join(td, "run.wal")
            led = make_ledger(cfg, backend="python")
            addrs = [f"0x{i:040x}" for i in range(cfg.client_num)]
            for a in addrs:
                assert led.register_node(a) == LedgerStatus.OK
            assert led.attach_wal(path)
            sizes = []
            for _ in range(rounds):
                ep = led.epoch
                committee = set(led.committee())
                got = 0
                for a in addrs:
                    if a in committee:
                        continue
                    h = _hl.sha256(f"{ep}|{a}".encode()).digest()
                    if led.upload_local_update(
                            a, h, 10, 1.0, ep) == LedgerStatus.OK:
                        got += 1
                    if got >= cfg.needed_update_count:
                        break
                row = [0.5 + 0.01 * u
                       for u in range(cfg.needed_update_count)]
                for a in committee:
                    assert led.upload_scores(a, ep,
                                             row) == LedgerStatus.OK
                mh = _hl.sha256(f"model|{ep}".encode()).digest()
                assert led.commit_model(mh, ep) == LedgerStatus.OK
                if armed and led.epoch % snapshot_interval == 0:
                    # the writer's emission order (_emit_snapshot →
                    # _maybe_finalize_snapshot): state BEFORE the op,
                    # GC to the position after it
                    state = led.encode_state()
                    pos = led.log_size()
                    op = make_snapshot_op(led)
                    assert led.apply_op(op) == LedgerStatus.OK
                    led.gc_prefix(pos + 1, state)
                sizes.append(_os.path.getsize(path))
            led.detach_wal()
            # ops still HELD (journaled): chain length minus the GC'd
            # prefix — the armed leg's bounded-state evidence
            return sizes, led.log_size() - getattr(led, "log_base", 0)

    armed_sizes, armed_ops = leg(True)
    legacy_sizes, legacy_ops = leg(False)
    half = len(armed_sizes) // 2
    return {
        "rounds": rounds, "snapshot_interval": snapshot_interval,
        "armed_max_wal_bytes": max(armed_sizes),
        "armed_final_wal_bytes": armed_sizes[-1],
        # the bounded-growth claim in one number: the armed journal's
        # ceiling over the SECOND half is no higher than over the first
        # (a sawtooth, not a ramp)
        "armed_first_half_max_wal_bytes": max(armed_sizes[:half]),
        "armed_second_half_max_wal_bytes": max(armed_sizes[half:]),
        "legacy_max_wal_bytes": max(legacy_sizes),
        "legacy_final_wal_bytes": legacy_sizes[-1],
        "armed_held_ops": armed_ops,
        "legacy_held_ops": legacy_ops,
        "bounded_ratio": round(
            legacy_sizes[-1] / max(max(armed_sizes), 1), 2),
    }


def endurance_async_config1(rounds: int = 2000, *,
                            reseat_every: int = 25,
                            snapshot_interval: int = 64,
                            churn_every: int = 40,
                            slo_warmup: int = 50,
                            seed: int = 0) -> Dict:
    """The multi-thousand-round ASYNC campaign (production endurance):
    `rounds` scripted buffered-aggregation drains driven directly on a
    snapshot-armed, WAL-attached python ledger under composed
    heavytail + churn semantics — stale base epochs in the admission
    mix, senders permanently retiring and fresh ones registering
    mid-campaign — with deterministic committee reseats every
    `reseat_every` drains (ProtocolConfig.async_reseat_every).

    Scripted like `_endurance_wal_leg` (op application is the work;
    no sockets), so thousands of rounds take seconds, while every
    durability claim is measured on the REAL protocol state machine:

    - a full replica replays every certified op concurrently (the
      validator re-derivation analog) and must agree on head, state
      digest and seated committee at the end;
    - a third ledger state-syncs from a snapshot taken mid-run INSIDE
      a reseat window and replays the tail to the same head;
    - the WAL and the held-op window must sawtooth (second-half
      ceiling <= first-half), not ramp, across churn and reseats;
    - a departed sender's in-flight delta must leave the buffer within
      two drains of its retirement (never wedge);
    - an SLO engine with adaptive baselining judges every round's
      measured wall + admitted staleness + (zero) rederive skips: the
      healthy campaign must page ZERO alerts — the false-page test.

    Returns the evidence dict tests/test_endurance.py and
    ``bench.py`` (BFLC_BENCH_ENDURANCE_ASYNC=1) assert and record."""
    import os as _os
    import random as _random
    import tempfile
    import hashlib as _hl

    from bflc_demo_tpu.ledger import LedgerStatus, make_ledger
    from bflc_demo_tpu.ledger.snapshot import (make_snapshot_op,
                                               restore_snapshot)
    from bflc_demo_tpu.obs.slo import SLOEngine, SLOSpec
    from bflc_demo_tpu.protocol.constants import ProtocolConfig

    cfg = ProtocolConfig(
        client_num=12, comm_count=3, aggregate_count=3,
        needed_update_count=5, learning_rate=0.05, batch_size=16,
        async_buffer=4, max_staleness=8,
        async_reseat_every=reseat_every).validate()
    rng = _random.Random(seed)
    engine = SLOEngine([
        SLOSpec("round_latency", "round_wall_s", 30.0,
                warmup=slo_warmup, adapt_floor=0.25),
        SLOSpec("async_staleness", "staleness_p95",
                float(cfg.max_staleness)),
        SLOSpec("rederive_skip", "rederive_skipped_delta", 0.0,
                budget=0.05)])

    with tempfile.TemporaryDirectory(prefix="bflc-endur-async-") as td:
        path = _os.path.join(td, "run.wal")
        led = make_ledger(cfg, backend="python")
        replica = make_ledger(cfg, backend="python")
        addrs = [f"addr-{i:04d}" for i in range(cfg.client_num)]
        for a in addrs:
            assert led.register_node(a) == LedgerStatus.OK
        assert led.attach_wal(path)
        live = list(addrs)              # senders still participating
        next_idx = cfg.client_num
        join_cap = 2 * cfg.client_num   # total admissions ever (churn)
        departed: dict = {}             # addr -> drain it retired at
        replayed = 0                    # replica's chain position
        synced = None                   # the mid-run state-sync ledger

        def _replay_to_tip():
            nonlocal replayed
            while replayed < led.log_size():
                op = led.log_op(replayed)
                assert replica.apply_op(op) == LedgerStatus.OK
                if synced is not None:
                    assert synced.apply_op(op) == LedgerStatus.OK
                replayed += 1

        wal_sizes, held_ops, state_sizes = [], [], []
        reseats = 0
        stale_admitted: List[int] = []
        stale_refused = 0
        wedged = 0
        false_pages = 0
        t_start = time.monotonic()
        t_prev = t_start
        for r in range(rounds):
            ep = led.epoch
            committee = set(led.committee())
            # --- churn: one retirement + one fresh admission per window
            if churn_every and r and r % churn_every == 0:
                pool = [a for a in live if a not in committee]
                if len(pool) > cfg.async_buffer + 2:
                    gone = pool[rng.randrange(len(pool))]
                    live.remove(gone)
                    departed[gone] = r
                if next_idx < join_cap:
                    fresh = f"addr-{next_idx:04d}"
                    next_idx += 1
                    assert led.register_node(fresh) == LedgerStatus.OK
                    live.append(fresh)
            # --- heavytail admissions: fill the buffer from live
            # trainers; ~1/8 arrive on a stale base epoch, and a few
            # outright too-stale (the refusal path is part of the run)
            trainers = [a for a in live if a not in committee]
            rng.shuffle(trainers)
            for a in trainers:
                if led.async_buffer_depth >= cfg.async_buffer:
                    break
                base = ep
                if ep > 0 and rng.random() < 0.125:
                    base = max(0, ep - rng.randint(1, cfg.max_staleness))
                if ep > cfg.max_staleness and rng.random() < 0.02:
                    st = led.async_upload(
                        a, _hl.sha256(f"x|{r}|{a}".encode()).digest(),
                        10, 1.0, ep - cfg.max_staleness - 1)
                    assert st == LedgerStatus.WRONG_EPOCH
                    stale_refused += 1
                    continue
                h = _hl.sha256(f"{r}|{a}".encode()).digest()
                st = led.async_upload(a, h, 10 + (r % 5), 1.0, base)
                if st == LedgerStatus.OK:
                    stale_admitted.append(ep - base)
            k = led.async_buffer_depth
            assert k == cfg.async_buffer
            # --- committee scoring (live members only; a retired seat
            # simply falls silent — unscored entries median to 0.0)
            aseqs = [e.aseq for e in led.async_buffer_view()]
            for a in committee:
                if a in live:
                    led.async_scores(
                        a, [(q, rng.random()) for q in aseqs])
            due = led.async_reseat_due()
            mh = _hl.sha256(f"model|{r}".encode()).digest()
            assert led.async_commit(mh, ep, k) == LedgerStatus.OK
            if due:
                reseats += 1
            # --- departed-sender wedge check: a retiree's delta must
            # leave the buffer within two drains of its retirement
            buffered = {e.sender for e in led.async_buffer_view()}
            for a, at in departed.items():
                if a in buffered and r - at >= 2:
                    wedged += 1
            _replay_to_tip()
            # --- mid-run state-sync INSIDE a reseat window: adopt the
            # writer's state exactly as a late validator would
            if synced is None and r == rounds // 2 \
                    and reseat_every > 0 \
                    and (led._acommit_count % reseat_every) \
                    not in (0, reseat_every - 1):
                synced = restore_snapshot(led.encode_state(), cfg,
                                          led.log_size(),
                                          led.log_head())
            # --- snapshot arm: certified checkpoint + prefix GC (the
            # writer's _emit_snapshot order), the WAL's sawtooth
            if snapshot_interval and led.epoch % snapshot_interval == 0:
                state = led.encode_state()
                pos = led.log_size()
                op = make_snapshot_op(led)
                assert led.apply_op(op) == LedgerStatus.OK
                _replay_to_tip()
                led.gc_prefix(pos + 1, state)
            wal_sizes.append(_os.path.getsize(path))
            held_ops.append(led.log_size() - getattr(led, "log_base", 0))
            state_sizes.append(len(led.encode_state()))
            # --- SLO judging on the measured round
            t_now = time.monotonic()
            window = stale_admitted[-k:] or [0]
            false_pages += len(engine.observe_round({
                "epoch": r, "round_wall_s": t_now - t_prev,
                "staleness_p95": float(sorted(window)[
                    max(int(0.95 * len(window)) - 1, 0)]),
                "rederive_skipped_delta": 0.0}))
            t_prev = t_now
        led.detach_wal()
        # --- final re-derivation agreement: replica (full replay) and
        # the mid-run state-sync ledger both land on the writer's head,
        # state and seated committee
        _replay_to_tip()
        agree = (replica.log_head() == led.log_head()
                 and replica.state_digest() == led.state_digest()
                 and replica.committee() == led.committee())
        if synced is not None:
            agree = agree and (synced.log_head() == led.log_head()
                               and synced.state_digest()
                               == led.state_digest()
                               and synced.committee() == led.committee())
        half = len(wal_sizes) // 2
        return {
            "rounds": rounds, "reseat_every": reseat_every,
            "snapshot_interval": snapshot_interval,
            "final_epoch": led.epoch,
            "epochs_monotone": led.epoch == rounds,
            "reseats": reseats,
            "final_committee": led.committee(),
            "clients_retired": len(departed),
            "clients_joined": next_idx - cfg.client_num,
            "stale_admitted": sum(1 for s in stale_admitted if s > 0),
            "stale_refused": stale_refused,
            "departed_wedged": wedged,
            "replica_agrees": bool(agree),
            "state_synced_mid_reseat_window": synced is not None,
            "max_wal_bytes": max(wal_sizes),
            "first_half_max_wal_bytes": max(wal_sizes[:half]),
            "second_half_max_wal_bytes": max(wal_sizes[half:]),
            "max_held_ops": max(held_ops),
            "first_half_max_held_ops": max(held_ops[:half]),
            "second_half_max_held_ops": max(held_ops[half:]),
            "max_state_bytes": max(state_sizes),
            "second_half_max_state_bytes": max(state_sizes[half:]),
            "slo_false_pages": false_pages,
            "slo": engine.report(),
            "wall_time_s": round(time.monotonic() - t_start, 3),
        }


# --------------------------------------------------- control plane (PR 3)
def _cert_throughput_inproc(n_ops: int = 24, validators: int = 4,
                            modes=("sequential", "batched")) -> Dict:
    """Certification-machinery throughput, measured in-process: a writer-
    side CertificateAssembler against a live (thread-served) validator
    fleet, certifying the same n_ops-deep backlog of signed register ops.

    'sequential' = one `certify` round-trip per op (the pre-PR shape);
    'batched' = one `certify_range` call (PR 3).  A fresh fleet per mode
    (replicas are stateful).  Runs under whatever crypto mode the process
    imported with — BFLC_CONTROL_PLANE_LEGACY=1 in the environment gives
    the pre-PR naive-Ed25519 numbers, which is how `certification_
    throughput` obtains its baseline leg.  Every certificate produced is
    checked under the unchanged `verify_certificate`."""
    from bflc_demo_tpu.comm.bft import (CertificateAssembler,
                                        ValidatorNode, next_head,
                                        provision_validators,
                                        verify_certificate)
    from bflc_demo_tpu.comm.identity import (ED25519_BACKEND, _op_bytes,
                                             provision_wallets)
    from bflc_demo_tpu.ledger.base import encode_register_op
    from bflc_demo_tpu.protocol.constants import ProtocolConfig, bft_quorum

    cfg = ProtocolConfig(client_num=max(n_ops, 5), comm_count=4,
                         aggregate_count=6, needed_update_count=10,
                         learning_rate=0.05, batch_size=16)
    wallets, _ = provision_wallets(n_ops, b"cert-bench-seed-01")
    entries = []
    for w in wallets:
        op = encode_register_op(w.address)
        tag = w.sign(_op_bytes("register", w.address, 0, b"")).hex()
        entries.append((op, {"tag": tag, "pubkey": w.public_bytes.hex()}))
    quorum = bft_quorum(validators)
    out: Dict = {"n_ops": n_ops, "validators": validators,
                 "crypto_backend": ED25519_BACKEND,
                 "legacy_mode": bool(
                     os.environ.get("BFLC_CONTROL_PLANE_LEGACY"))}
    for mode in modes:
        vwallets, vkeys = provision_validators(
            validators, b"cert-bench-fleet|" + mode.encode())
        nodes = [ValidatorNode(cfg, w, i, validator_keys=vkeys)
                 for i, w in enumerate(vwallets)]
        for v in nodes:
            v.start()
        asm = CertificateAssembler([(v.host, v.port) for v in nodes],
                                   vkeys, quorum)
        try:
            t0 = time.perf_counter()
            if mode == "sequential":
                prev = b"\0" * 32
                certs = []
                for i, (op, auth) in enumerate(entries):
                    certs.append(asm.certify(i, op, auth, prev))
                    prev = next_head(prev, op)
            else:
                certs = asm.certify_range(0, entries, b"\0" * 32)
            dt = time.perf_counter() - t0
        finally:
            asm.close()
            for v in nodes:
                v.close()
        prev = b"\0" * 32
        for i, ((op, _), cert) in enumerate(zip(entries, certs)):
            if cert is None or not verify_certificate(
                    cert, index=i, prev_head=prev, op=op, quorum=quorum,
                    validator_keys=vkeys):
                raise RuntimeError(
                    f"{mode}: op {i} failed certification — a throughput "
                    f"number over broken certificates would be fiction")
            prev = next_head(prev, op)
        out[f"{mode}_ops_per_sec"] = round(n_ops / dt, 2)
        out[f"{mode}_ms_per_op"] = round(dt * 1e3 / n_ops, 3)
    if {"sequential", "batched"} <= set(modes):
        out["batched_vs_sequential"] = round(
            out["batched_ops_per_sec"] / out["sequential_ops_per_sec"], 2)
    return out


def _rederive_scripted_rounds(mode: str, rounds: int, validators: int,
                              lie: bool = False) -> Dict:
    """One in-process fleet (thread-served writer + validator quorum)
    driving `rounds` scripted config-1-shaped rounds with the rederive
    plane at `mode` — the benchmark's measurement core and the
    refusal-drill harness (`lie=True` corrupts the writer's committed
    model bytes).  Returns wall/round, per-validator rederive cost,
    the committed epoch and the validator stats."""
    import hashlib as _hl
    from unittest import mock

    import numpy as np

    import bflc_demo_tpu.comm.ledger_service as _ls
    from bflc_demo_tpu.comm.bft import ValidatorNode, provision_validators
    from bflc_demo_tpu.comm.identity import _op_bytes, provision_wallets
    from bflc_demo_tpu.protocol.constants import ProtocolConfig
    from bflc_demo_tpu.utils.serialization import pack_entries, pack_pytree

    cfg = ProtocolConfig(client_num=8, comm_count=2, aggregate_count=3,
                         needed_update_count=4, learning_rate=0.05,
                         batch_size=16)
    init = pack_pytree({"W": np.zeros((64, 8), np.float32),
                        "b": np.zeros((8,), np.float32)})
    saved = os.environ.get("BFLC_REDERIVE")
    os.environ["BFLC_REDERIVE"] = mode
    nodes, srv = [], None
    try:
        vwallets, vkeys = provision_validators(
            validators, b"rederive-bench|" + mode.encode())
        nodes = [ValidatorNode(cfg, w, i, validator_keys=vkeys,
                               initial_model_blob=init)
                 for i, w in enumerate(vwallets)]
        for v in nodes:
            v.start()
        srv = _ls.LedgerServer(
            cfg, init, bft_validators=[(v.host, v.port) for v in nodes],
            bft_keys=vkeys, bft_timeout_s=2.0)
        srv.start()
        cl = _ls.CoordinatorClient(srv.host, srv.port)
        wallets, _ = provision_wallets(cfg.client_num,
                                       b"rederive-bench-clients")

        def sign(w, kind, ep, payload):
            return w.sign(_op_bytes(kind, w.address, ep, payload)).hex()

        for w in wallets:
            cl.request("register", addr=w.address,
                       pubkey=w.public_bytes.hex(),
                       tag=sign(w, "register", 0, b""))

        def corrupting_pack(entries):
            e = dict(entries)
            k = sorted(e)[0]
            a = np.array(e[k], np.float32).copy()
            a.flat[0] += np.float32(0.25)
            return pack_entries(dict(e, **{k: a}))

        ctx = (mock.patch.object(_ls, "pack_entries", corrupting_pack)
               if lie else _null_ctx())
        walls = []
        last = {}
        with ctx:
            for ep in range(rounds):
                t0 = time.perf_counter()
                committee = set(cl.request("committee")["committee"])
                trainers = [w for w in wallets
                            if w.address not in committee]
                for i, w in enumerate(
                        trainers[:cfg.needed_update_count]):
                    blob = pack_pytree(
                        {"W": np.full((64, 8), 0.01 * (i + 1 + ep),
                                      np.float32),
                         "b": np.full((8,), 0.001 * (i + 1),
                                      np.float32)})
                    d = _hl.sha256(blob).digest()
                    payload = d + struct.pack("<qd", 10 + i, 1.0)
                    cl.request("upload", addr=w.address, blob=blob,
                               hash=d.hex(), n=10 + i, cost=1.0,
                               epoch=ep,
                               tag=sign(w, "upload", ep, payload))
                nu = cfg.needed_update_count
                for j, w in enumerate([w for w in wallets
                                       if w.address in committee]):
                    row = [0.5 + 0.01 * (j + u) for u in range(nu)]
                    payload = struct.pack(f"<{nu}d", *row)
                    last = cl.request("scores", addr=w.address,
                                      epoch=ep, scores=row,
                                      tag=sign(w, "scores", ep,
                                               payload))
                walls.append(time.perf_counter() - t0)
                if lie:
                    break           # one refused round is the drill
        info = cl.request("info")
        stats = [dict(v._rederiver.stats) if v._rederiver is not None
                 else None for v in nodes]
        per_validator_s = [s["seconds"] for s in stats if s]
        return {
            "mode": mode, "rounds_driven": len(walls),
            "committed_epoch": info["epoch"],
            "last_status": last.get("status"),
            "wall_per_round_s": round(
                sum(walls) / max(len(walls), 1), 4),
            "rederive_s_per_validator_round": round(
                sum(per_validator_s)
                / max(len(per_validator_s) * len(walls), 1), 5)
            if per_validator_s else 0.0,
            "refusals": sum(s["refused"] for s in stats if s),
            "skips": sum(s["skipped"] for s in stats if s),
            "oks": sum(s["ok"] for s in stats if s),
        }
    finally:
        if srv is not None:
            srv.close()
        for v in nodes:
            v.close()
        if saved is None:
            os.environ.pop("BFLC_REDERIVE", None)
        else:
            os.environ["BFLC_REDERIVE"] = saved


def _null_ctx():
    import contextlib
    return contextlib.nullcontext()


def rederive_config1(rounds: int = 3, validators: int = 4) -> Dict:
    """The validator re-derivation plane's cost + enforcement axis
    (bflc_demo_tpu.rederive): off / shard / full legs over the same
    scripted fleet geometry — round wall overhead vs the off leg and
    the per-validator re-derivation cost (shard must be cheaper than
    full) — plus the refusal drill: a writer committing a corrupted
    (self-consistent) model under shard mode must FAIL certification
    with the committed epoch unmoved.  Rides bench.py `extra.rederive`."""
    legs = {m: _rederive_scripted_rounds(m, rounds, validators)
            for m in ("off", "shard", "full")}
    drill = _rederive_scripted_rounds("shard", 1, validators, lie=True)
    off_wall = max(legs["off"]["wall_per_round_s"], 1e-9)
    return {
        "rounds": rounds, "validators": validators,
        "legs": legs,
        "round_wall_overhead_shard_x": round(
            legs["shard"]["wall_per_round_s"] / off_wall, 3),
        "round_wall_overhead_full_x": round(
            legs["full"]["wall_per_round_s"] / off_wall, 3),
        "refusal_drill": {
            "certified": drill["last_status"] not in ("CERT_TIMEOUT",),
            "last_status": drill["last_status"],
            "refusals": drill["refusals"],
            "committed_epoch": drill["committed_epoch"],
        },
    }


def certification_throughput(n_ops: int = 24, validators: int = 4,
                             include_legacy: bool = True) -> Dict:
    """The ops-certified/sec axis with its own baseline: the in-process
    measurement above under THIS process's (fast) crypto, plus — in a
    child interpreter with BFLC_CONTROL_PLANE_LEGACY=1 — the pre-PR path
    (sequential certification, naive Ed25519, no verify memo, hex-JSON
    frames).  `speedup_vs_pre_pr` is batched-fast vs sequential-legacy:
    the number the PR's acceptance bar is stated in."""
    out = _cert_throughput_inproc(n_ops, validators)
    if include_legacy:
        code = ("import json; "
                "from bflc_demo_tpu.eval.benchmarks import "
                "_cert_throughput_inproc as f; "
                f"print(json.dumps(f({n_ops}, {validators}, "
                "modes=('sequential',))))")
        env = dict(os.environ, BFLC_CONTROL_PLANE_LEGACY="1",
                   JAX_PLATFORMS="cpu")
        try:
            r = subprocess.run([sys.executable, "-c", code], env=env,
                               capture_output=True, text=True, timeout=600)
            lines = [ln for ln in r.stdout.splitlines()
                     if ln.startswith("{")]
            if r.returncode == 0 and lines:
                legacy = json.loads(lines[-1])
                out["pre_pr_sequential_ops_per_sec"] = \
                    legacy["sequential_ops_per_sec"]
                out["speedup_vs_pre_pr"] = round(
                    out["batched_ops_per_sec"]
                    / legacy["sequential_ops_per_sec"], 2)
            else:
                out["pre_pr_error"] = r.stderr.strip()[-300:]
        except subprocess.TimeoutExpired:
            out["pre_pr_error"] = "legacy child timed out"
    return out


def federation_config1(rounds: int = 3, *, standbys: int = 2,
                       validators: int = 4, quorum: int = 1,
                       compare_sequential: bool = False,
                       telemetry: bool = True,
                       trace_sample: float = 0.0,
                       timeout_s: float = 420.0) -> Dict:
    """Process-federation benchmark at the paper's config-1 BFT geometry —
    the topology that actually reproduces the reference's deployment (20
    client processes + 2 hot standbys + 4 commit validators + quorum-1
    acks + WAL; the same fleet the chaos-soak headline runs) — measuring
    what no other bench axis sees: round wall time THROUGH the certified
    socket path, ops-certified/sec, and the crypto-time share of the
    writer process (attributed by utils.tracing spans, not asserted).

    compare_sequential=True re-runs the identical federation with
    BFLC_CONTROL_PLANE_LEGACY=1 in the children's environment — the
    pre-PR control plane (sequential certification, naive Ed25519,
    hex-JSON blob frames) — and reports the round-time and
    ops-certified/sec ratios.

    telemetry=True (default) arms the fleet telemetry plane (obs/): the
    driver scrapes every role each committed round and the result
    carries `telemetry` scrape coverage (roles answering / expected) —
    bench.py surfaces it as extra.telemetry.  telemetry=False is the
    overhead baseline leg (TPU_RESULTS.md telemetry-overhead axis).

    trace_sample > 0 additionally arms causal op tracing (obs.trace;
    implies telemetry) and the leg result carries a `trace` summary —
    reassembled trace count, role coverage per trace, and the critical-
    path attribution fraction — computed from the run's span files
    before the tempdir goes away."""
    from bflc_demo_tpu.data import load_occupancy, iid_shards

    cfg = DEFAULT_PROTOCOL
    xtr, ytr, xte, yte = load_occupancy()
    shards = iid_shards(xtr, ytr, cfg.client_num)

    def _run(legacy: bool) -> Dict:
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        saved = {k: os.environ.get(k)
                 for k in ("BFLC_CONTROL_PLANE_LEGACY", "BFLC_PROC_TRACE")}
        if legacy:
            os.environ["BFLC_CONTROL_PLANE_LEGACY"] = "1"
        else:
            os.environ.pop("BFLC_CONTROL_PLANE_LEGACY", None)
        os.environ["BFLC_PROC_TRACE"] = "1"
        trace_summary = None
        device_summary = None
        try:
            with tempfile.TemporaryDirectory(prefix="bflc-fed-bench-") \
                    as td:
                res = run_federated_processes(
                    "make_softmax_regression", shards, (xte, yte), cfg,
                    rounds=rounds, standbys=standbys, quorum=quorum,
                    bft_validators=validators,
                    wal_path=os.path.join(td, "writer.wal"),
                    telemetry_dir=(os.path.join(td, "telemetry")
                                   if telemetry or trace_sample else ""),
                    trace_sample=trace_sample,
                    timeout_s=timeout_s)
                if trace_sample:
                    # summarize the causal traces BEFORE the tempdir is
                    # reclaimed: the artifact of record is the summary,
                    # not the span files
                    trace_summary = _trace_summary(
                        os.path.join(td, "telemetry"))
                if telemetry or trace_sample:
                    # device-plane evidence, same before-the-tempdir-
                    # dies rule: post-warmup per-round fresh-compile
                    # deltas (the steady-state gate's data) + storm
                    # verdicts + the worst memory watermark
                    device_summary = _device_summary(
                        os.path.join(td, "telemetry"))
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        info = res.final_info or {}
        wall = max(res.wall_time_s, 1e-9)
        certified = int(info.get("certified_size")
                        or info.get("log_size") or 0)
        perf = info.get("perf") or {}
        costs = perf.get("costs", {})
        crypto_s = (costs.get("crypto.sign_s", 0.0)
                    + costs.get("crypto.verify_s", 0.0))
        wire_s = (costs.get("wire.send_s", 0.0)
                  + costs.get("wire.recv_s", 0.0))
        rounds_done = max(res.rounds_completed, 1)
        # steady-state round time: commit-to-commit intervals from the
        # sponsor's own observations.  Fleet spawn (20 jax child imports)
        # and the registration burst live before the FIRST commit;
        # dividing total wall by rounds would let that startup noise
        # drown exactly the per-round control-plane cost this benchmark
        # exists to measure.
        ts = [t for _, t in res.epoch_times]
        if len(ts) >= 2:
            round_wall = (ts[-1] - ts[0]) / (len(ts) - 1)
        else:
            round_wall = wall / rounds_done
        return {
            "rounds": res.rounds_completed,
            "round_wall_time_s": round(round_wall, 4),
            "time_to_first_round_s": round(ts[0], 3) if ts else None,
            "wall_time_s": round(wall, 3),
            "ops_certified": certified,
            # fleet-level rate (includes spawn/idle — trend, not truth)
            # and the writer's actual certification throughput (ops over
            # the time the certify path really ran)
            "ops_certified_per_sec": round(certified / wall, 2),
            "cert_throughput_ops_per_sec": round(
                certified / costs["bft.certify_s"], 2)
            if costs.get("bft.certify_s") else None,
            "best_acc": round(res.best_accuracy(), 4),
            "writer_crypto_time_s": round(crypto_s, 3),
            "writer_crypto_share": round(crypto_s / wall, 4),
            "writer_wire_time_s": round(wire_s, 3),
            "writer_certify_time_s": round(
                costs.get("bft.certify_s", 0.0), 3),
            "writer_aggregate_time_s": round(
                costs.get("aggregate_s", 0.0), 3),
            "ops_certified_batched": int(
                costs.get("bft.certify_batched_ops", 0)),
            "ops_certified_single": int(
                costs.get("bft.certify_single_ops", 0)),
            # scrape coverage: roles answering / roles expected — the
            # telemetry plane's own health axis (None when disabled)
            "telemetry": ({k: res.telemetry_report[k]
                           for k in ("scrapes", "roles_expected",
                                     "answered_total", "expected_total",
                                     "coverage")}
                          if res.telemetry_report else None),
            # causal-trace summary (None when untraced): how many op
            # journeys reassembled and how completely the critical path
            # attributes round wall time (obs.trace)
            "trace": trace_summary,
            # device-plane summary (None when telemetry was dark or the
            # device plane pinned): post-warmup recompile deltas, storm
            # verdicts, memory watermark (obs.device / obs.timeline)
            "device": device_summary,
        }

    out: Dict = {
        "geometry": {"clients": cfg.client_num, "standbys": standbys,
                     "validators": validators, "quorum": quorum,
                     "wal": True, "rounds": rounds,
                     "telemetry": telemetry},
        "fast": _run(legacy=False),
    }
    if compare_sequential:
        out["pre_pr_sequential"] = _run(legacy=True)
        fast, seq = out["fast"], out["pre_pr_sequential"]
        if fast["round_wall_time_s"] > 0:
            out["round_time_speedup"] = round(
                seq["round_wall_time_s"] / fast["round_wall_time_s"], 2)
        if fast.get("cert_throughput_ops_per_sec") \
                and seq.get("cert_throughput_ops_per_sec"):
            out["cert_throughput_speedup"] = round(
                fast["cert_throughput_ops_per_sec"]
                / seq["cert_throughput_ops_per_sec"], 2)
    return out


# ------------------------------------------------------ data plane (PR 5)
def _wire_transparency_check() -> bool:
    """Prove the compressed wire is content-transparent: the same
    message decodes bit-identically whether it rides a compressed, BIN1
    or legacy hex-JSON frame — so certified history (hashes over payload
    BYTES) cannot depend on the frame encoding."""
    import json as _json
    import socket
    import struct as _struct

    from bflc_demo_tpu.comm import wire

    blob = bytes(range(256)) * 64 + b"\x00" * 30000      # compressible
    msg = {"method": "upload", "blob": blob, "hash": "ab" * 32, "n": 3}
    a, b = socket.socketpair()
    try:
        wire.send_msg(a, msg)                            # compressed
        legacy_body = _json.dumps(
            {**{k: v for k, v in msg.items() if k != "blob"},
             "blob": blob.hex()}, separators=(",", ":")).encode()
        a.sendall(_struct.pack(">I", len(legacy_body)) + legacy_body)
        m1, m2 = wire.recv_msg(b), wire.recv_msg(b)
        return (wire.blob_bytes(m1["blob"]) == blob
                and wire.blob_bytes(m2["blob"]) == blob
                and m1["hash"] == m2["hash"] == msg["hash"])
    finally:
        a.close()
        b.close()


def _scrape_series(timeline, role_prefix: str, metric: str,
                   **want) -> float:
    """Max cumulative value of counter `metric` across all scraped
    snapshots of roles starting with `role_prefix`, summed over roles
    (counters are cumulative: each role's final snapshot carries its
    total; a killed role keeps its last observed value)."""
    best: Dict[str, float] = {}
    for rec in timeline:
        if rec.get("type") != "scrape":
            continue
        for role, snap in rec.get("roles", {}).items():
            if not role.startswith(role_prefix):
                continue
            total = 0.0
            samples = ((snap.get("metrics") or {}).get(metric)
                       or {}).get("samples", [])
            for s in samples:
                lab = s.get("labels", {})
                if all(lab.get(k) == v for k, v in want.items()):
                    total += s.get("value", 0.0)
            best[role] = max(best.get(role, 0.0), total)
    return sum(best.values())


def _scrape_hist(timeline, role_prefix: str, metric: str, **want):
    """(count, mean) of histogram `metric` merged across roles, from
    each role's last snapshot."""
    last: Dict[str, tuple] = {}
    for rec in timeline:
        if rec.get("type") != "scrape":
            continue
        for role, snap in rec.get("roles", {}).items():
            if not role.startswith(role_prefix):
                continue
            count, tot = 0, 0.0
            samples = ((snap.get("metrics") or {}).get(metric)
                       or {}).get("samples", [])
            for s in samples:
                lab = s.get("labels", {})
                if all(lab.get(k) == v for k, v in want.items()):
                    count += s.get("count", 0)
                    tot += s.get("sum", 0.0)
            if count:
                last[role] = (count, tot)
    n = sum(c for c, _ in last.values())
    t = sum(s for _, s in last.values())
    return n, (t / n if n else 0.0)


def _writer_egress_per_round(timeline, fallback_total: float,
                             rounds: int) -> float:
    """Steady-state coordinator egress bytes/round: the slope of the
    writer's cumulative wire.bytes_out across the per-round scrapes
    (spawn/registration burst excluded); falls back to total/rounds."""
    pts = []
    for rec in timeline:
        if rec.get("type") != "scrape" or \
                not str(rec.get("tag", "")).startswith("round-"):
            continue
        w = rec.get("roles", {}).get("writer")
        if not w:
            continue
        out = (w.get("trace_costs") or {}).get("wire.bytes_out")
        if out is not None:
            pts.append(float(out))
    if len(pts) >= 2 and pts[-1] > pts[0]:
        return (pts[-1] - pts[0]) / (len(pts) - 1)
    return fallback_total / max(rounds, 1)


def data_plane_config1(rounds: int = 3, *, standbys: int = 2,
                       validators: int = 4, quorum: int = 1,
                       model_hidden: int = 4096,
                       include_legacy: bool = True,
                       quantized: str = "i8",
                       timeout_s: float = 420.0) -> Dict:
    """Data-plane benchmark at the config-1 fleet geometry (20 clients +
    2 standbys + 4 validators + quorum-1 + WAL) with a model fat enough
    that the DATA plane, not the control plane, dominates the wire (a
    5->hidden->2 MLP on occupancy; the reference's softmax model is 48
    bytes, which would measure JSON overhead, not blob movement).

    Axes: coordinator egress bytes/round (steady-state slope of the
    writer's traced wire.bytes_out across the per-round telemetry
    scrapes), model-distribution fan-out time (the clients' fetch-phase
    histogram), steady round wall time, read-source shares, cache hit
    ratio and compression ratio — each vs a child fleet running with
    BFLC_DATA_PLANE_LEGACY=1 (no fan-out, no cache, no meta probe, no
    compression).  Certified-history integrity per leg: the replica
    replay inside run_federated_processes raises on head divergence, and
    `wire_transparent` pins that frame encodings cannot alter content.

    quantized: additionally run a leg with --delta-dtype set (opt-in
    reduced-precision uploads) and report its accuracy next to the f32
    leg's — the quantization-accuracy axis ('' skips the leg)."""
    import dataclasses

    from bflc_demo_tpu.data import load_occupancy, iid_shards
    from bflc_demo_tpu.obs.collector import load_timeline

    cfg = DEFAULT_PROTOCOL
    xtr, ytr, xte, yte = load_occupancy()
    shards = iid_shards(xtr, ytr, cfg.client_num)
    factory_kw = {"input_shape": (5,), "hidden": int(model_hidden),
                  "num_classes": 2}

    def _run(legacy: bool, delta_dtype: str = "f32") -> Dict:
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        run_cfg = dataclasses.replace(cfg, delta_dtype=delta_dtype)
        saved = {k: os.environ.get(k)
                 for k in ("BFLC_DATA_PLANE_LEGACY", "BFLC_PROC_TRACE")}
        if legacy:
            os.environ["BFLC_DATA_PLANE_LEGACY"] = "1"
        else:
            os.environ.pop("BFLC_DATA_PLANE_LEGACY", None)
        os.environ["BFLC_PROC_TRACE"] = "1"
        try:
            with tempfile.TemporaryDirectory(prefix="bflc-dp-bench-") \
                    as td:
                res = run_federated_processes(
                    "make_mlp", shards, (xte, yte), run_cfg,
                    rounds=rounds, factory_kw=factory_kw,
                    standbys=standbys, quorum=quorum,
                    bft_validators=validators,
                    wal_path=os.path.join(td, "writer.wal"),
                    telemetry_dir=os.path.join(td, "telemetry"),
                    timeout_s=timeout_s)
                timeline = load_timeline(res.telemetry_report["jsonl"]) \
                    if res.telemetry_report else []
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        info = res.final_info or {}
        costs = (info.get("perf") or {}).get("costs", {})
        bytes_out = float(costs.get("wire.bytes_out", 0.0))
        rounds_done = max(res.rounds_completed, 1)
        ts = [t for _, t in res.epoch_times]
        round_wall = ((ts[-1] - ts[0]) / (len(ts) - 1)
                      if len(ts) >= 2 else res.wall_time_s / rounds_done)
        reads = {src: _scrape_series(timeline, "client-",
                                     "dataplane_reads_total", source=src)
                 for src in ("cache", "replica", "writer")}
        reads_total = sum(reads.values())
        hits = _scrape_series(timeline, "client-",
                              "dataplane_cache_events_total", event="hit")
        misses = _scrape_series(timeline, "client-",
                                "dataplane_cache_events_total",
                                event="miss")
        n_fetch, mean_fetch = _scrape_hist(timeline, "client-",
                                           "client_phase_seconds",
                                           phase="fetch")
        zraw = _scrape_series(timeline, "", "wire_zip_bytes_total",
                              which="raw")
        zwire = _scrape_series(timeline, "", "wire_zip_bytes_total",
                               which="wire")
        fallbacks = _scrape_series(timeline, "client-",
                                   "dataplane_blob_fallback_total")
        return {
            "rounds": res.rounds_completed,
            "best_acc": round(res.best_accuracy(), 4),
            "round_wall_time_s": round(round_wall, 4),
            "writer_egress_bytes_total": int(bytes_out),
            "writer_egress_bytes_per_round": int(_writer_egress_per_round(
                timeline, bytes_out, rounds_done)),
            "model_fetch_mean_s": round(mean_fetch, 4),
            "model_fetches": n_fetch,
            "read_source_share": (
                {k: round(v / reads_total, 3) for k, v in reads.items()}
                if reads_total else None),
            "cache_hit_ratio": (round(hits / (hits + misses), 3)
                                if hits + misses else None),
            "blob_batch_fallbacks": int(fallbacks),
            "compression_ratio": (round(zraw / zwire, 2) if zwire
                                  else None),
            "delta_dtype": delta_dtype,
            "log_head": info.get("log_head"),
            "replica_verified": res.replica_report is not None,
        }

    out: Dict = {
        "geometry": {"clients": cfg.client_num, "standbys": standbys,
                     "validators": validators, "quorum": quorum,
                     "rounds": rounds, "model": "mlp",
                     "model_hidden": int(model_hidden)},
        "wire_transparent": _wire_transparency_check(),
        "fast": _run(legacy=False),
    }
    if include_legacy:
        out["pre_pr_legacy"] = _run(legacy=True)
        fast, leg = out["fast"], out["pre_pr_legacy"]
        if fast["writer_egress_bytes_per_round"]:
            out["egress_reduction_x"] = round(
                leg["writer_egress_bytes_per_round"]
                / fast["writer_egress_bytes_per_round"], 2)
        if fast["round_wall_time_s"]:
            out["round_time_speedup"] = round(
                leg["round_wall_time_s"] / fast["round_wall_time_s"], 2)
    if quantized:
        out["quantized_leg"] = _run(legacy=False, delta_dtype=quantized)
        out["quantized_acc_gap"] = round(
            out["fast"]["best_acc"] - out["quantized_leg"]["best_acc"], 4)
    return out


def sparse_config1(rounds: int = 3, *, standbys: int = 2,
                   validators: int = 4, quorum: int = 1,
                   model_hidden: int = 4096,
                   densities=(1.0, 0.1, 0.01),
                   dtypes=("f32", "i8"),
                   timeout_s: float = 420.0) -> Dict:
    """Sparse-upload benchmark: the PR-5 egress methodology swept over
    the density x dtype grid at the config-1 BFT fleet geometry
    (20 clients + 2 standbys + 4 validators + quorum-1 + WAL, the same
    fat MLP as data_plane_config1 so blob movement dominates the wire).

    One child fleet per (density, dtype) leg on the fast data plane,
    PLUS the PR-5 baseline: a dense-f32 fleet with
    BFLC_DATA_PLANE_LEGACY=1 (no fan-out, no cache, no compression) —
    the `legacy_d1_f32` leg every ratio in `egress_vs_legacy_x` is
    taken against, exactly the data_plane_config1 methodology with the
    encoding axes swept on top.  Per leg: writer egress bytes/round
    (steady-state scrape slope), best accuracy vs the fast dense-f32
    leg, and the encode/decode round shares (client-side top-k
    `sparse_encode_seconds` as a fraction of one round's wall — the
    latency a client's upload gains; writer-side densify
    `sparse_decode_seconds` summed per round against the same wall —
    both must stay noise or the egress win is an illusion).  The
    headline claims: density 0.01 x f32 beats the legacy dense-f32
    egress by >= 20x at an accuracy gap <= 0.01, and density x i8
    beats i8 alone (sparsification and quantization compose
    multiplicatively, QSGD).  Certified-history integrity per leg: the
    replica replay inside run_federated_processes raises on head
    divergence."""
    import dataclasses

    from bflc_demo_tpu.data import load_occupancy, iid_shards
    from bflc_demo_tpu.obs.collector import load_timeline

    cfg = DEFAULT_PROTOCOL
    xtr, ytr, xte, yte = load_occupancy()
    shards = iid_shards(xtr, ytr, cfg.client_num)
    factory_kw = {"input_shape": (5,), "hidden": int(model_hidden),
                  "num_classes": 2}

    def _leg(density: float, dtype: str, legacy: bool = False) -> Dict:
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        run_cfg = dataclasses.replace(cfg, delta_dtype=dtype,
                                      delta_density=float(density))
        saved = {k: os.environ.get(k)
                 for k in ("BFLC_PROC_TRACE", "BFLC_DATA_PLANE_LEGACY")}
        os.environ["BFLC_PROC_TRACE"] = "1"
        if legacy:
            os.environ["BFLC_DATA_PLANE_LEGACY"] = "1"
        else:
            os.environ.pop("BFLC_DATA_PLANE_LEGACY", None)
        try:
            with tempfile.TemporaryDirectory(
                    prefix="bflc-sparse-bench-") as td:
                res = run_federated_processes(
                    "make_mlp", shards, (xte, yte), run_cfg,
                    rounds=rounds, factory_kw=factory_kw,
                    standbys=standbys, quorum=quorum,
                    bft_validators=validators,
                    wal_path=os.path.join(td, "writer.wal"),
                    telemetry_dir=os.path.join(td, "telemetry"),
                    timeout_s=timeout_s)
                timeline = load_timeline(res.telemetry_report["jsonl"]) \
                    if res.telemetry_report else []
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        info = res.final_info or {}
        costs = (info.get("perf") or {}).get("costs", {})
        bytes_out = float(costs.get("wire.bytes_out", 0.0))
        rounds_done = max(res.rounds_completed, 1)
        ts = [t for _, t in res.epoch_times]
        round_wall = ((ts[-1] - ts[0]) / (len(ts) - 1)
                      if len(ts) >= 2 else res.wall_time_s / rounds_done)
        n_enc, mean_enc = _scrape_hist(timeline, "client-",
                                       "sparse_encode_seconds")
        n_dec, mean_dec = _scrape_hist(timeline, "writer",
                                       "sparse_decode_seconds")
        return {
            "density": float(density), "delta_dtype": dtype,
            "data_plane": "legacy" if legacy else "fast",
            "rounds": res.rounds_completed,
            "best_acc": round(res.best_accuracy(), 4),
            "round_wall_time_s": round(round_wall, 4),
            "writer_egress_bytes_per_round": int(_writer_egress_per_round(
                timeline, bytes_out, rounds_done)),
            "encode_calls": n_enc,
            "encode_mean_s": round(mean_enc, 6),
            # latency one client's upload gains per round
            "encode_share_of_round": round(
                mean_enc / max(round_wall, 1e-9), 5),
            "decode_calls": n_dec,
            "decode_mean_s": round(mean_dec, 6),
            # writer-side densify is serial on admission: whole-round sum
            "decode_share_of_round": round(
                (n_dec / rounds_done) * mean_dec
                / max(round_wall, 1e-9), 5),
            "log_head": info.get("log_head"),
            "replica_verified": res.replica_report is not None,
        }

    legs: Dict[str, Dict] = {
        # the PR-5 baseline every headline ratio is against: dense f32
        # on the LEGACY data plane (no fan-out / cache / compression)
        "legacy_d1_f32": _leg(1.0, "f32", legacy=True),
    }
    for dt in dtypes:
        for d in densities:
            legs[f"d{d:g}_{dt}"] = _leg(d, dt)
    out: Dict = {
        "geometry": {"clients": cfg.client_num, "standbys": standbys,
                     "validators": validators, "quorum": quorum,
                     "rounds": rounds, "model": "mlp",
                     "model_hidden": int(model_hidden),
                     "densities": [float(d) for d in densities],
                     "dtypes": list(dtypes)},
        "legs": legs,
    }
    legacy = legs["legacy_d1_f32"]
    if legacy["writer_egress_bytes_per_round"]:
        b = legacy["writer_egress_bytes_per_round"]
        out["egress_vs_legacy_dense_f32_x"] = {
            k: round(b / leg["writer_egress_bytes_per_round"], 2)
            for k, leg in legs.items()
            if k != "legacy_d1_f32"
            and leg["writer_egress_bytes_per_round"]}
    base = legs.get("d1_f32")
    if base:
        if base["writer_egress_bytes_per_round"]:
            b = base["writer_egress_bytes_per_round"]
            out["egress_vs_dense_f32_x"] = {
                k: round(b / leg["writer_egress_bytes_per_round"], 2)
                for k, leg in legs.items()
                if leg["writer_egress_bytes_per_round"]}
        out["acc_gap_vs_dense_f32"] = {
            k: round(base["best_acc"] - leg["best_acc"], 4)
            for k, leg in legs.items()}
    # the QSGD composition claim: sparse x i8 beats i8 alone
    i8 = legs.get("d1_i8")
    sparsest = min((float(d) for d in densities), default=1.0)
    si8 = legs.get(f"d{sparsest:g}_i8")
    if i8 and si8 and si8["writer_egress_bytes_per_round"]:
        out["sparse_i8_vs_i8_x"] = round(
            i8["writer_egress_bytes_per_round"]
            / si8["writer_egress_bytes_per_round"], 2)
    return out


def closed_loop_config1(rounds: int = 8, *, standbys: int = 0,
                        validators: int = 4, quorum: int = 0,
                        model_hidden: int = 4096,
                        density: float = 0.01,
                        adapt_start: float = 0.1,
                        timeout_s: float = 900.0) -> Dict:
    """Closed-loop compression benchmark (ISSUE 20): the sparse_config1
    methodology with the legs the loop adds.

    Five legs at the config-1 BFT fleet geometry (same fat MLP as
    sparse_config1 so blob movement dominates the wire):

    - `legacy_dense`: BFLC_DATA_PLANE_LEGACY=1 dense f32 — the egress
      baseline every reduction ratio is taken against (the PR-5/PR-12
      methodology; round-17's 23.1x was measured against this leg).
    - `dense_f32`: fast-path dense — the accuracy reference.
    - `sl_d{density}`: STATELESS sparse top-k at `density` — the PR-12
      posture whose few-round accuracy trail (~0.11 behind dense at
      density 0.01, TPU_RESULTS.md round 17) motivated the loop.
    - `ef_d{density}`: the same density with BFLC_ERROR_FEEDBACK=1 —
      client-local residual accumulation, byte-identical wire
      protocol.  BFLC deltas are model differences re-measured against
      the current global each round (core/local_train), so unapplied
      movement self-corrects and EF's win here is FASTER CATCH-UP at a
      fixed sparse density (rounds-to-0.85), not the dense-rate
      equality plain-SGD EF theory promises for gradient deltas.
    - `adaptive`: the certified genome-update loop (adapt_every=1,
      density `adapt_start` decaying toward the `density` floor on the
      fixed rule) — the leg that closes the EARLY-ROUND gap: it spends
      bandwidth while the model is far from converged and ramps to the
      floor as disagreement stabilizes.  Evidence: the effective
      density actually MOVED mid-run (final_info's eff_density /
      genome_epoch, served by the writer's certified ledger), every
      round committed, and the replica replay inside
      run_federated_processes re-derived the same head — i.e. zero
      certification refusals on the honest path while the knob
      transitioned.
    """
    import dataclasses

    from bflc_demo_tpu.data import load_occupancy, iid_shards
    from bflc_demo_tpu.obs.collector import load_timeline

    cfg = DEFAULT_PROTOCOL
    xtr, ytr, xte, yte = load_occupancy()
    shards = iid_shards(xtr, ytr, cfg.client_num)
    factory_kw = {"input_shape": (5,), "hidden": int(model_hidden),
                  "num_classes": 2}

    def _leg(run_cfg, *, error_feedback: bool = False,
             legacy_plane: bool = False) -> Dict:
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        saved = {k: os.environ.get(k)
                 for k in ("BFLC_PROC_TRACE", "BFLC_ERROR_FEEDBACK",
                           "BFLC_DATA_PLANE_LEGACY")}
        os.environ["BFLC_PROC_TRACE"] = "1"
        if error_feedback:
            os.environ["BFLC_ERROR_FEEDBACK"] = "1"
        else:
            os.environ.pop("BFLC_ERROR_FEEDBACK", None)
        if legacy_plane:
            os.environ["BFLC_DATA_PLANE_LEGACY"] = "1"
        else:
            os.environ.pop("BFLC_DATA_PLANE_LEGACY", None)
        try:
            with tempfile.TemporaryDirectory(
                    prefix="bflc-closed-loop-bench-") as td:
                res = run_federated_processes(
                    "make_mlp", shards, (xte, yte), run_cfg,
                    rounds=rounds, factory_kw=factory_kw,
                    standbys=standbys, quorum=quorum,
                    bft_validators=validators,
                    wal_path=os.path.join(td, "writer.wal"),
                    telemetry_dir=os.path.join(td, "telemetry"),
                    timeout_s=timeout_s)
                timeline = load_timeline(res.telemetry_report["jsonl"]) \
                    if res.telemetry_report else []
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        info = res.final_info or {}
        costs = (info.get("perf") or {}).get("costs", {})
        rounds_done = max(res.rounds_completed, 1)
        # time-to-quality: first committed epoch whose sponsor accuracy
        # reached 0.85 (None: never in this leg's budget) — the
        # trendable rounds-to-target axis (tools/bench_trend.py)
        to_target = next((int(e) for e, a in res.accuracy_history
                          if a >= 0.85), None)
        # the early-round criterion: sponsor accuracy after the 3rd
        # committed round (None when the leg died before round 3)
        acc3 = next((round(float(a), 4)
                     for e, a in res.accuracy_history if int(e) == 3),
                    None)
        out = {
            "density": float(run_cfg.delta_density),
            "adapt_every": int(run_cfg.adapt_every),
            "error_feedback": bool(error_feedback),
            "rounds": res.rounds_completed,
            "best_acc": round(res.best_accuracy(), 4),
            "acc_at_3": acc3,
            "rounds_to_085": to_target,
            "writer_egress_bytes_per_round": int(
                _writer_egress_per_round(
                    timeline, float(costs.get("wire.bytes_out", 0.0)),
                    rounds_done)),
            "log_head": info.get("log_head"),
            # the replica replay re-derived the committed head from the
            # raw op stream — the zero-refusal / integrity evidence
            "replica_verified": res.replica_report is not None,
        }
        if "eff_density" in info:
            out["eff_density_final"] = info["eff_density"]
            out["genome_epoch"] = info.get("genome_epoch")
        return out

    legs: Dict[str, Dict] = {
        "legacy_dense": _leg(
            dataclasses.replace(cfg, delta_density=1.0),
            legacy_plane=True),
        "dense_f32": _leg(dataclasses.replace(cfg, delta_density=1.0)),
        f"sl_d{density:g}": _leg(
            dataclasses.replace(cfg, delta_density=float(density))),
        f"ef_d{density:g}": _leg(
            dataclasses.replace(cfg, delta_density=float(density)),
            error_feedback=True),
        "adaptive": _leg(
            dataclasses.replace(cfg, delta_density=float(adapt_start),
                                adapt_every=1,
                                density_floor=float(density))),
    }
    out: Dict = {
        "geometry": {"clients": cfg.client_num, "standbys": standbys,
                     "validators": validators, "quorum": quorum,
                     "rounds": rounds, "model": "mlp",
                     "model_hidden": int(model_hidden),
                     "density": float(density),
                     "adapt_start": float(adapt_start)},
        "legs": legs,
    }
    legacy, dense = legs["legacy_dense"], legs["dense_f32"]
    sl = legs[f"sl_d{density:g}"]
    ef, ad = legs[f"ef_d{density:g}"], legs["adaptive"]

    def _ratio(leg):
        b = leg["writer_egress_bytes_per_round"]
        base = legacy["writer_egress_bytes_per_round"]
        return round(base / b, 2) if b and base else None

    # egress ratios vs the legacy dense plane (PR-12 methodology)
    out["egress_reduction_ef_x"] = _ratio(ef)
    out["egress_reduction_adaptive_x"] = _ratio(ad)
    out["egress_reduction_fast_dense_x"] = _ratio(dense)

    def _gap3(leg):
        if dense["acc_at_3"] is None or leg["acc_at_3"] is None:
            return None
        return round(dense["acc_at_3"] - leg["acc_at_3"], 4)

    # the early-round trail at the 3rd committed round (the ~0.11
    # stateless number the loop exists to govern)
    out["acc_gap_stateless"] = _gap3(sl)
    out["acc_gap_ef"] = _gap3(ef)
    out["acc_gap_adaptive"] = _gap3(ad)
    # how much of the stateless trail the EF leg recovered at round 3
    if out["acc_gap_stateless"] is not None \
            and out["acc_gap_ef"] is not None:
        out["acc_catch_up"] = round(
            out["acc_gap_stateless"] - out["acc_gap_ef"], 4)
    out["rounds_to_085_dense"] = dense["rounds_to_085"]
    out["rounds_to_085_stateless"] = sl["rounds_to_085"]
    out["rounds_to_085_ef"] = ef["rounds_to_085"]
    out["rounds_to_085_adaptive"] = ad["rounds_to_085"]
    # EF's honest win at a fixed sparse density: rounds-to-target saved
    # vs the stateless PR-12 posture
    if sl["rounds_to_085"] is not None and ef["rounds_to_085"] is not None:
        out["ef_rounds_saved"] = sl["rounds_to_085"] - ef["rounds_to_085"]
    # the matched-accuracy qualifier: the best egress ratio among legs
    # that stayed within 0.02 of dense at round 3
    matched = [r for r, g in ((_ratio(leg), _gap3(leg))
                              for leg in (sl, ef, ad))
               if r is not None and g is not None and g <= 0.02]
    if matched:
        out["egress_reduction_at_matched_acc_x"] = max(matched)
    out["adaptive_density_moved"] = (
        ad.get("eff_density_final") is not None
        and ad["eff_density_final"] < float(adapt_start)
        and ad.get("genome_epoch") is not None)
    out["adaptive_honest_path_clean"] = (
        ad["rounds"] == rounds and ad["replica_verified"])
    return out


# ------------------------------------------- hierarchical federation (PR 6)
def _flat_entries(template):
    """[(keystr, leaf_index)] of a pytree template — the canonical entry
    keys a packed blob of it carries (utils.serialization)."""
    import jax
    flat = jax.tree_util.tree_flatten_with_path(template)[0]
    return [jax.tree_util.keystr(path) for path, _ in flat]


def _spawn_bench_root(cfg, initial_blob, *, cell_registry=None,
                      validators: int = 0,
                      master_seed: bytes = b"hier-bench-fleet-01",
                      stall_timeout_s: float = 120.0):
    """Root coordinator (+ optional validator quorum) as SUBPROCESSES with
    the cost tracer armed — the hier benchmark's measured tier.  Returns
    (terminate_fn, host, port)."""
    import dataclasses as _dc
    import multiprocessing as mp

    from bflc_demo_tpu.client.process_runtime import (_cpu_spawn_env,
                                                      _validator_proc)
    from bflc_demo_tpu.hier.runtime import _root_proc

    cfg_kw = {f.name: getattr(cfg, f.name) for f in _dc.fields(cfg)}
    ctx = mp.get_context("spawn")
    host = "127.0.0.1"
    saved = os.environ.get("BFLC_PROC_TRACE")
    os.environ["BFLC_PROC_TRACE"] = "1"
    procs = []
    try:
        bft_keys, bft_eps = {}, []
        if validators:
            from bflc_demo_tpu.comm.bft import provision_validators
            _, bft_keys = provision_validators(validators, master_seed)
            for v in range(validators):
                q = ctx.Queue()
                p = ctx.Process(
                    target=_validator_proc,
                    args=(cfg_kw, master_seed + b"|bft-validator|"
                          + __import__("struct").pack("<q", v), v, q,
                          bft_keys, False, 0, None, None, cell_registry),
                    daemon=True)
                with _cpu_spawn_env():
                    p.start()
                procs.append(p)
                bft_eps.append((host, q.get(timeout=60)))
        q = ctx.Queue()
        root = ctx.Process(
            target=_root_proc,
            args=(cfg_kw, initial_blob, q, stall_timeout_s, "",
                  cell_registry or {}, bft_eps, bft_keys, False),
            daemon=True)
        with _cpu_spawn_env():
            root.start()
        procs.append(root)
        port = q.get(timeout=60)
    finally:
        if saved is None:
            os.environ.pop("BFLC_PROC_TRACE", None)
        else:
            os.environ["BFLC_PROC_TRACE"] = saved

    def _terminate():
        for p in procs:
            p.terminate()
            p.join(timeout=10)

    return _terminate, host, port


def _root_wire_stats(client) -> Dict:
    info = client.request("info")
    costs = (info.get("perf") or {}).get("costs", {})
    return {"epoch": info["epoch"],
            "log_size": info["log_size"],
            "certified_size": info.get("certified_size"),
            "bytes_out": float(costs.get("wire.bytes_out", 0.0)),
            "bytes_in": float(costs.get("wire.bytes_in", 0.0))}


def _chunked_blob_fetch(client, hashes):
    """Committee-side candidate fetch, chunked under handle_read's
    256-hash batch cap — every byte counts toward root egress."""
    from bflc_demo_tpu.comm.wire import split_blob_parts
    out = {}
    for i in range(0, len(hashes), 256):
        r = client.request("blobs", hashes=hashes[i:i + 256])
        if r.get("ok"):
            out.update(split_blob_parts(r))
    return out


def hier_scaling(clients=(1000, 10000), cells: int = 8, rounds: int = 2,
                 validators: int = 4, single_tier=(1000,),
                 shard_size: int = 16, seed: int = 0) -> Dict:
    """THE hierarchical-federation benchmark: root-coordinator cost vs
    simulated thin-client count (ROADMAP "the 10k-client round").

    Each leg stands up the REAL measured tier as OS processes — the root
    `LedgerServer` (with the cell registry in hier legs) plus a BFT
    validator quorum — and simulates the cheap tier in the driver: thin
    clients train real softmax models on synthetic shards
    (data/synthetic.py, one vmapped program over all clients), and the
    cell aggregators run the real `hier.partial` pipeline (dequantize ->
    sorted weighted partial -> evidence digest -> signed cell-aggregate
    upload) over real sockets.  What crosses the root's wire is exactly
    the two deployments' root traffic:

    - hier: O(cells) model fetches + O(cells) certified cell-aggregate
      ops per round — FLAT as the client count grows 10x (the acceptance
      bar: within 1.2x);
    - single-tier (the comparison leg — equivalently `BFLC_HIER_LEGACY=1`
      / --cells 0 on the CLI path): every client fetches the model from
      the root and uploads its own signed delta, committee members pull
      every candidate — O(clients) root egress and certified ops.

    Returns per-leg {root_egress_bytes_per_round, root_ops_per_round,
    root_certified_ops_per_round, round_wall_time_s} plus the headline
    ratios.  Measured egress is the root process's own traced
    wire.bytes_out slope across rounds (registration burst excluded).
    """
    import dataclasses as _dc
    import hashlib as _hl
    import struct as _struct

    import numpy as np

    from bflc_demo_tpu.comm.identity import Wallet, _op_bytes
    from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
    from bflc_demo_tpu.comm.wire import blob_bytes
    from bflc_demo_tpu.core.local_train import local_train_impl
    from bflc_demo_tpu.core.scoring import score_candidates
    from bflc_demo_tpu.data.partition import one_hot
    from bflc_demo_tpu.data.synthetic import synthetic_image_classification
    from bflc_demo_tpu.hier.cells import (cell_protocol, plan_cells,
                                          root_protocol)
    from bflc_demo_tpu.hier.partial import (cell_evidence_digest,
                                            cell_partial, partial_blob,
                                            split_cellmeta)
    from bflc_demo_tpu.models import make_softmax_regression
    from bflc_demo_tpu.utils.serialization import (pack_pytree,
                                                   restore_pytree,
                                                   unpack_pytree)

    import jax
    import jax.numpy as jnp

    model = make_softmax_regression()
    template = model.init_params(0)
    keys = _flat_entries(template)
    blob0 = pack_pytree(model.init_params(seed))
    lr, bs = 0.05, min(16, shard_size)

    def _sign(w, kind, epoch, payload):
        return w.sign(_op_bytes(kind, w.address, epoch, payload)).hex()

    def _shards(n):
        x, y = synthetic_image_classification(n * shard_size, (5,), 2,
                                              seed)
        yh = one_hot(y, 2)
        return (x.reshape(n, shard_size, 5),
                yh.reshape(n, shard_size, 2))

    # ONE vmapped train program per leg: every thin client trains its own
    # shard for real; identical shapes keep it a single compile
    _train_jit = jax.jit(jax.vmap(
        lambda params, x, y: local_train_impl(model.apply, params, x, y,
                                              lr, bs, 1),
        in_axes=(None, 0, 0)))

    def _train_all(params, xs, ys):
        deltas, costs = _train_jit(params, jnp.asarray(xs),
                                   jnp.asarray(ys))
        return (jax.device_get(deltas), np.asarray(costs))

    def _delta_entries(deltas_tree, i):
        leaves = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda a: np.asarray(a[i]),
                                   deltas_tree))
        return dict(zip(keys, leaves))

    def _register(conn, w):
        r = conn.request("register", addr=w.address,
                         pubkey=w.public_bytes.hex(),
                         tag=_sign(w, "register", 0, b""))
        assert r["ok"] or r.get("status") == "ALREADY_REGISTERED", r

    def _leg_stats(base_stats, round_stats, t_leg):
        # ONE definition of the headline per-round slopes (counter delta
        # over committed rounds) shared by both legs, so the hier-vs-flat
        # ratios can never drift from asymmetric edits
        first, last = base_stats, round_stats[-1]
        nr = len(round_stats)
        return {
            "rounds": nr,
            "root_egress_bytes_per_round": int(
                (last["bytes_out"] - first["bytes_out"]) / nr),
            "root_ingress_bytes_per_round": int(
                (last["bytes_in"] - first["bytes_in"]) / nr),
            "root_ops_per_round": round(
                (last["log_size"] - first["log_size"]) / nr, 1),
            "root_certified_ops_per_round": round(
                ((last["certified_size"] or 0)
                 - (first["certified_size"] or 0)) / nr, 1)
            if last["certified_size"] is not None else None,
            "round_wall_time_s": round(
                (time.monotonic() - t_leg) / nr, 3),
        }

    def _hier_leg(n: int) -> Dict:
        # "one writer admits every upload" scaled to N: the global genome
        # admits every trainer (the story the cell tier shards)
        base = _dc.replace(
            DEFAULT_PROTOCOL, client_num=n, comm_count=4,
            aggregate_count=max(n - 4, 1),
            needed_update_count=max(n - 4, 1), learning_rate=lr,
            batch_size=bs, local_epochs=1)
        plan = plan_cells(n, cells=cells)
        aggs = {c: Wallet.from_seed(b"hier-bench-agg|%d|%d" % (n, c))
                for c in range(plan.n_cells)}
        registry = {aggs[c].address: (c, len(plan.members[c]))
                    for c in range(plan.n_cells)}
        root_cfg = root_protocol(base, plan.n_cells)
        cell_cfgs = {c: cell_protocol(base, len(plan.members[c]))
                     for c in range(plan.n_cells)}
        stop, host, port = _spawn_bench_root(
            root_cfg, blob0, cell_registry=registry,
            validators=validators)
        xs, ys = _shards(n)
        t_leg = time.monotonic()
        out: Dict = {"clients": n, "cells": plan.n_cells}
        try:
            conns = {c: CoordinatorClient(host, port, timeout_s=120.0)
                     for c in range(plan.n_cells)}
            for c, w in aggs.items():
                _register(conns[c], w)
            base_stats = _root_wire_stats(conns[0])
            round_stats = []
            for rd in range(rounds):
                epoch = base_stats["epoch"] if not round_stats else \
                    round_stats[-1]["epoch"]
                # model DOWN the tree: one fetch per cell aggregator
                mblobs = {}
                for c in range(plan.n_cells):
                    mr = conns[c].request("model")
                    mblobs[c] = blob_bytes(mr["blob"])
                params = restore_pytree(template,
                                        unpack_pytree(mblobs[0]))
                deltas, costs = _train_all(params, xs, ys)
                # cell tier (driver-simulated, real hier.partial path);
                # root-committee cells score instead of uploading, so
                # skip their partial pipeline before paying for it
                for c in range(plan.n_cells):
                    w = aggs[c]
                    st = conns[c].request("state", addr=w.address)
                    if st["role"] != "trainer":
                        continue
                    cc = cell_cfgs[c]
                    members = plan.members[c]
                    trainers = members[cc.comm_count:]
                    adm_idx = list(trainers[:cc.needed_update_count])
                    admitted = [(f"0xm{i:08x}", _delta_entries(deltas, i),
                                 shard_size, float(costs[i]))
                                for i in adm_idx]
                    part, n_adm, mcost = cell_partial(admitted)
                    stacked = jax.tree_util.tree_map(
                        lambda *t: jnp.stack(t),
                        *[restore_pytree(template, f)
                          for _, f, _, _ in admitted[:8]])
                    row = np.asarray(score_candidates(
                        model.apply, params, stacked, lr,
                        jnp.asarray(xs[members[0]]),
                        jnp.asarray(ys[members[0]])))
                    ev = cell_evidence_digest(
                        epoch, c,
                        [(a, _hl.sha256(str(a).encode()).digest(), nn,
                          cc_) for a, _, nn, cc_ in admitted],
                        [float(v) for v in row], list(range(n_adm)))
                    blob = partial_blob(part, c, n_adm, ev)
                    digest = _hl.sha256(blob).digest()
                    payload = digest + _struct.pack("<qd", n_adm,
                                                    float(mcost))
                    conns[c].request(
                        "upload", addr=w.address, blob=blob,
                        hash=digest.hex(), n=n_adm, cost=float(mcost),
                        epoch=epoch,
                        tag=_sign(w, "upload", epoch, payload))
                # root committee cells score the candidate partials
                for c in range(plan.n_cells):
                    w = aggs[c]
                    if conns[c].request("state",
                                        addr=w.address)["role"] != "comm":
                        continue
                    ups = conns[c].request("updates")["updates"]
                    if not ups:
                        continue
                    fetched = _chunked_blob_fetch(
                        conns[c], [u["hash"] for u in ups])
                    cands = [restore_pytree(
                                 template,
                                 split_cellmeta(unpack_pytree(
                                     fetched[u["hash"]]))[0])
                             for u in ups]
                    stacked = jax.tree_util.tree_map(
                        lambda *t: jnp.stack(t), *cands)
                    row = [float(v) for v in np.asarray(score_candidates(
                        model.apply, params, stacked, lr,
                        jnp.asarray(xs[plan.members[c][0]]),
                        jnp.asarray(ys[plan.members[c][0]])))]
                    payload = _struct.pack(f"<{len(row)}d", *row)
                    conns[c].request(
                        "scores", addr=w.address, epoch=epoch,
                        scores=row,
                        tag=_sign(w, "scores", epoch, payload))
                deadline = time.monotonic() + 120.0
                while True:
                    stats = _root_wire_stats(conns[0])
                    if stats["epoch"] > epoch:
                        break
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"hier leg n={n}: round {rd} never "
                            f"committed at the root")
                    time.sleep(0.05)
                round_stats.append(stats)
            for c in conns.values():
                c.close()
        finally:
            stop()
        out.update(_leg_stats(base_stats, round_stats, t_leg))
        return out

    def _flat_leg(n: int) -> Dict:
        cfg = _dc.replace(DEFAULT_PROTOCOL, client_num=n, comm_count=4,
                          aggregate_count=max(n - 4, 1),
                          needed_update_count=max(n - 4, 1),
                          learning_rate=lr, batch_size=bs,
                          local_epochs=1)
        wallets = [Wallet.from_seed(b"hier-bench-flat|%d|%d" % (n, i))
                   for i in range(n)]
        stop, host, port = _spawn_bench_root(cfg, blob0,
                                             validators=validators)
        xs, ys = _shards(n)
        t_leg = time.monotonic()
        out: Dict = {"clients": n}
        try:
            conn = CoordinatorClient(host, port, timeout_s=120.0)
            for w in wallets:
                _register(conn, w)
            committee = set(conn.request("committee")["committee"])
            base_stats = _root_wire_stats(conn)
            round_stats = []
            for rd in range(rounds):
                epoch = base_stats["epoch"] if not round_stats else \
                    round_stats[-1]["epoch"]
                # every client fetches the model FROM THE ROOT — the
                # single-tier O(N) down-traffic the cell tier removes
                params = None
                for i, w in enumerate(wallets):
                    mr = conn.request("model")
                    if params is None:
                        params = restore_pytree(
                            template,
                            unpack_pytree(blob_bytes(mr["blob"])))
                deltas, costs = _train_all(params, xs, ys)
                for i, w in enumerate(wallets):
                    if w.address in committee:
                        continue
                    blob = pack_pytree(jax.tree_util.tree_map(
                        lambda a: np.asarray(a[i]), deltas))
                    digest = _hl.sha256(blob).digest()
                    payload = digest + _struct.pack(
                        "<qd", shard_size, float(costs[i]))
                    conn.request(
                        "upload", addr=w.address, blob=blob,
                        hash=digest.hex(), n=shard_size,
                        cost=float(costs[i]), epoch=epoch,
                        tag=_sign(w, "upload", epoch, payload))
                ups = conn.request("updates")["updates"]
                hashes = [u["hash"] for u in ups]
                for w in wallets:
                    if w.address not in committee:
                        continue
                    fetched = _chunked_blob_fetch(conn, hashes)
                    cands = [restore_pytree(template,
                                            unpack_pytree(fetched[h]))
                             for h in hashes]
                    stacked = jax.tree_util.tree_map(
                        lambda *t: jnp.stack(t), *cands)
                    row = [float(v) for v in np.asarray(score_candidates(
                        model.apply, params, stacked, lr,
                        jnp.asarray(xs[0]), jnp.asarray(ys[0])))]
                    payload = _struct.pack(f"<{len(row)}d", *row)
                    conn.request("scores", addr=w.address, epoch=epoch,
                                 scores=row,
                                 tag=_sign(w, "scores", epoch, payload))
                deadline = time.monotonic() + 300.0
                while True:
                    stats = _root_wire_stats(conn)
                    if stats["epoch"] > epoch:
                        break
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"flat leg n={n}: round {rd} never "
                            f"committed")
                    time.sleep(0.05)
                round_stats.append(stats)
                committee = set(conn.request("committee")["committee"])
            conn.close()
        finally:
            stop()
        out.update(_leg_stats(base_stats, round_stats, t_leg))
        return out

    out: Dict = {
        "geometry": {"cells": cells, "validators": validators,
                     "rounds": rounds, "shard_size": shard_size,
                     "model": "softmax_regression(5->2)"},
        "hier": {str(n): _hier_leg(int(n)) for n in clients},
        "single_tier": {str(n): _flat_leg(int(n)) for n in single_tier},
    }
    hs = [out["hier"][str(n)] for n in clients]
    if len(hs) >= 2 and hs[0]["root_egress_bytes_per_round"]:
        out["clients_growth_x"] = round(
            int(clients[-1]) / int(clients[0]), 1)
        out["hier_egress_ratio"] = round(
            hs[-1]["root_egress_bytes_per_round"]
            / hs[0]["root_egress_bytes_per_round"], 3)
        out["hier_ops_ratio"] = round(
            hs[-1]["root_ops_per_round"]
            / max(hs[0]["root_ops_per_round"], 1e-9), 3)
        if hs[0].get("root_certified_ops_per_round"):
            out["hier_certified_ops_ratio"] = round(
                hs[-1]["root_certified_ops_per_round"]
                / hs[0]["root_certified_ops_per_round"], 3)
    ft = out["single_tier"].get(str(clients[0])) if single_tier else None
    if ft and out["hier"].get(str(clients[0])):
        h0 = out["hier"][str(clients[0])]
        if h0["root_egress_bytes_per_round"]:
            out["single_vs_hier_egress_x"] = round(
                ft["root_egress_bytes_per_round"]
                / h0["root_egress_bytes_per_round"], 2)
        if h0["root_ops_per_round"]:
            out["single_vs_hier_ops_x"] = round(
                ft["root_ops_per_round"] / h0["root_ops_per_round"], 2)
    return out


def telemetry_overhead_config1(rounds: int = 3, trials: int = 1,
                               **kw) -> Dict:
    """Telemetry overhead measured, not asserted (the observability
    PR's acceptance bar): the identical config-1 federation with the
    scrape plane armed vs dark, steady round wall time compared.  With
    trials > 1 each leg's round time is the per-trial minimum — the
    least-contended observation on a noisy shared host."""
    on_times, off_times, on_last, off_last = [], [], None, None
    for _ in range(trials):
        on_last = federation_config1(rounds=rounds, telemetry=True, **kw)
        off_last = federation_config1(rounds=rounds, telemetry=False,
                                      **kw)
        on_times.append(on_last["fast"]["round_wall_time_s"])
        off_times.append(off_last["fast"]["round_wall_time_s"])
    on_t, off_t = min(on_times), min(off_times)
    return {
        "rounds": rounds, "trials": trials,
        # headline = per-leg minimum over trials; the full per-trial
        # lists ride along so the artifact is self-consistent (the
        # last-trial detail legs below may show different times)
        "round_wall_time_s_telemetry_on": on_t,
        "round_wall_time_s_telemetry_off": off_t,
        "round_times_on": on_times, "round_times_off": off_times,
        "overhead_frac": round(on_t / off_t - 1.0, 4) if off_t else None,
        "scrape_coverage": on_last["fast"].get("telemetry"),
        "last_trial_on": on_last["fast"],
        "last_trial_off": off_last["fast"],
    }


def _trace_summary(telemetry_dir: str) -> Optional[Dict]:
    """Compact artifact of a traced run's causal spans: trace counts,
    per-trace role coverage, and the critical-path attribution fraction
    per round (obs.trace).  None when no spans were flushed."""
    from bflc_demo_tpu.obs import trace as obs_trace
    spans = obs_trace.gather_spans(telemetry_dir)
    if not spans:
        return None
    traces = obs_trace.assemble_traces(spans)
    role_counts = [len(obs_trace.trace_role_classes(ts))
                   for ts in traces.values()]
    reports = obs_trace.round_reports(spans)
    return {
        "spans": len(spans),
        "traces": len(traces),
        "traces_ge4_roles": sum(1 for n in role_counts if n >= 4),
        "max_roles_per_trace": max(role_counts, default=0),
        "rounds_reassembled": len(reports),
        "critical_path_cover": ([round(r["covered_frac"], 3)
                                 for r in reports] or None),
    }


def _device_summary(telemetry_dir: str) -> Optional[Dict]:
    """Compact device-plane evidence off a federation's telemetry
    artifacts (obs.device + the obs.timeline joiner): per-round fleet
    fresh-compile deltas AFTER the warmup window — steady-state sync
    rounds must show zero, the recompile gate's data — plus the
    driver's storm verdicts and the worst memory watermark seen.
    None when the artifacts carry no device stream (telemetry dark or
    BFLC_DEVICE_OBS=0)."""
    from bflc_demo_tpu.obs.device import LEVELS
    from bflc_demo_tpu.obs.timeline import (DEVICE_SLO_WARMUP_ROUNDS,
                                            load_round_timeline)
    try:
        tl = load_round_timeline(telemetry_dir)
    except Exception:           # noqa: BLE001 — evidence, not gating
        return None
    deltas = {}
    for r in tl.rounds():
        d = tl.slo_summary(r).get("device_recompiles_delta")
        if d is not None:
            deltas[r] = d
    storms = [rec for rec in tl.device
              if rec.get("type") == "device_storm"]
    mems = [rec for rec in tl.device if rec.get("type") == "device_mem"]
    if not deltas and not storms and not mems:
        return None
    worst = max((LEVELS.index(rec.get("verdict", "ok"))
                 for rec in storms if rec.get("verdict") in LEVELS),
                default=0)
    return {
        "warmup_rounds": DEVICE_SLO_WARMUP_ROUNDS,
        "recompiles_delta_by_round": {str(r): d
                                      for r, d in deltas.items()},
        # the steady-state gate's headline: total fleet fresh compiles
        # over every post-warmup round (zero on a healthy sync loop)
        "steady_state_recompiles": (sum(deltas.values())
                                    if deltas else None),
        "storm_rounds": len(storms),
        "worst_storm_verdict": LEVELS[worst],
        "mem_peak_bytes": max((float(rec.get("peak_bytes", 0.0))
                               for rec in mems), default=None),
        "mem_source": mems[-1].get("source") if mems else None,
    }


def trace_overhead_config1(rounds: int = 3, trials: int = 1,
                           **kw) -> Dict:
    """Causal-tracing overhead measured, not asserted (the tracing PR's
    5% acceptance bar, same harness as telemetry_overhead_config1): the
    identical config-1 federation with every op traced (sample=1.0) vs
    tracing off, telemetry armed on BOTH legs so the delta isolates the
    span/record/`_tp` cost.  The traced leg's `trace` summary rides
    along as the reassembly evidence.

    Leg order ALTERNATES per trial: on this contended host the FIRST
    federation of a pair consistently runs ~20% hotter than the second
    regardless of code path (measured while landing the tracing PR —
    TPU_RESULTS.md round 13), so a fixed order would charge that
    session-warmup artifact to whichever leg always went first."""
    on_times, off_times, on_last, off_last = [], [], None, None
    for trial in range(trials):
        legs = [1.0, 0.0] if trial % 2 == 0 else [0.0, 1.0]
        for sample in legs:
            res = federation_config1(rounds=rounds, telemetry=True,
                                     trace_sample=sample, **kw)
            if sample:
                on_last = res
                on_times.append(res["fast"]["round_wall_time_s"])
            else:
                off_last = res
                off_times.append(res["fast"]["round_wall_time_s"])
    on_t, off_t = min(on_times), min(off_times)
    return {
        "rounds": rounds, "trials": trials,
        "round_wall_time_s_trace_on": on_t,
        "round_wall_time_s_trace_off": off_t,
        "round_times_on": on_times, "round_times_off": off_times,
        "overhead_frac": round(on_t / off_t - 1.0, 4) if off_t else None,
        "trace": on_last["fast"].get("trace"),
        "last_trial_on": on_last["fast"],
        "last_trial_off": off_last["fast"],
    }


def health_overhead_config1(rounds: int = 3, trials: int = 2,
                            **kw) -> Dict:
    """Model-quality health-plane overhead measured, not asserted (the
    health PR's 5% acceptance bar, same harness as
    trace_overhead_config1): the identical config-1 federation with
    telemetry armed on BOTH legs, health plane armed vs pinned off
    with BFLC_HEALTH_LEGACY=1 in the fleet's environment (spawned
    children inherit it), steady round wall time compared on the
    per-leg minimum over trials.

    Leg order ALTERNATES per trial — the session-warmup artifact the
    tracing PR measured (the first federation of a pair runs ~20%
    hotter on this contended host regardless of code path,
    TPU_RESULTS.md round 13) would otherwise be charged to whichever
    leg always went first; use an even `trials` so the alternation
    actually de-biases."""
    armed_times, legacy_times = [], []
    armed_last = legacy_last = None
    for trial in range(trials):
        legs = [False, True] if trial % 2 == 0 else [True, False]
        for legacy in legs:
            saved = os.environ.get("BFLC_HEALTH_LEGACY")
            if legacy:
                os.environ["BFLC_HEALTH_LEGACY"] = "1"
            else:
                os.environ.pop("BFLC_HEALTH_LEGACY", None)
            try:
                res = federation_config1(rounds=rounds, telemetry=True,
                                         **kw)
            finally:
                if saved is None:
                    os.environ.pop("BFLC_HEALTH_LEGACY", None)
                else:
                    os.environ["BFLC_HEALTH_LEGACY"] = saved
            if legacy:
                legacy_last = res
                legacy_times.append(res["fast"]["round_wall_time_s"])
            else:
                armed_last = res
                armed_times.append(res["fast"]["round_wall_time_s"])
    armed_t, legacy_t = min(armed_times), min(legacy_times)
    return {
        "rounds": rounds, "trials": trials,
        "round_wall_time_s_health_armed": armed_t,
        "round_wall_time_s_health_legacy": legacy_t,
        "round_times_armed": armed_times,
        "round_times_legacy": legacy_times,
        "overhead_frac": (round(armed_t / legacy_t - 1.0, 4)
                          if legacy_t else None),
        "last_trial_armed": armed_last["fast"],
        "last_trial_legacy": legacy_last["fast"],
    }


def slo_overhead_config1(rounds: int = 3, trials: int = 2,
                         **kw) -> Dict:
    """SLO/forensics-plane overhead measured, not asserted (the
    forensics PR's 5% acceptance bar, same harness as
    health_overhead_config1): the identical config-1 federation with
    telemetry armed on BOTH legs, the round-timeline joiner + SLO
    engine armed vs pinned off with BFLC_SLO_LEGACY=1, steady round
    wall time compared on the per-leg minimum over trials.  The plane
    is driver-side (it rides the collector's scrape tick), so the
    expected cost is the joiner/judge work per scrape — measured so a
    regression cannot hide behind 'it's only the driver'.

    Leg order ALTERNATES per trial (the session-warmup artifact,
    TPU_RESULTS.md round 13); use an even `trials`."""
    armed_times, legacy_times = [], []
    armed_last = legacy_last = None
    for trial in range(trials):
        legs = [False, True] if trial % 2 == 0 else [True, False]
        for legacy in legs:
            saved = os.environ.get("BFLC_SLO_LEGACY")
            if legacy:
                os.environ["BFLC_SLO_LEGACY"] = "1"
            else:
                os.environ.pop("BFLC_SLO_LEGACY", None)
            try:
                res = federation_config1(rounds=rounds, telemetry=True,
                                         **kw)
            finally:
                if saved is None:
                    os.environ.pop("BFLC_SLO_LEGACY", None)
                else:
                    os.environ["BFLC_SLO_LEGACY"] = saved
            if legacy:
                legacy_last = res
                legacy_times.append(res["fast"]["round_wall_time_s"])
            else:
                armed_last = res
                armed_times.append(res["fast"]["round_wall_time_s"])
    armed_t, legacy_t = min(armed_times), min(legacy_times)
    return {
        "rounds": rounds, "trials": trials,
        "round_wall_time_s_slo_armed": armed_t,
        "round_wall_time_s_slo_legacy": legacy_t,
        "round_times_armed": armed_times,
        "round_times_legacy": legacy_times,
        "overhead_frac": (round(armed_t / legacy_t - 1.0, 4)
                          if legacy_t else None),
        "last_trial_armed": armed_last["fast"],
        "last_trial_legacy": legacy_last["fast"],
    }


def device_overhead_config1(rounds: int = 3, trials: int = 2,
                            **kw) -> Dict:
    """Device-plane overhead measured, not asserted (the device PR's 1%
    acceptance bar, same harness as slo_overhead_config1): the
    identical config-1 federation with telemetry armed on BOTH legs,
    the compile/memory/storm plane armed vs pinned off with
    BFLC_DEVICE_OBS=0 in the fleet's environment (spawned children
    inherit it), steady round wall time compared on the per-leg minimum
    over trials.  The armed leg's `device` summary rides along — the
    steady-state recompile evidence (post-warmup sync rounds must show
    zero fleet fresh compiles).

    Leg order ALTERNATES per trial (the session-warmup artifact,
    TPU_RESULTS.md round 13); use an even `trials`."""
    armed_times, legacy_times = [], []
    armed_last = legacy_last = None
    for trial in range(trials):
        legs = [False, True] if trial % 2 == 0 else [True, False]
        for legacy in legs:
            saved = os.environ.get("BFLC_DEVICE_OBS")
            if legacy:
                os.environ["BFLC_DEVICE_OBS"] = "0"
            else:
                os.environ.pop("BFLC_DEVICE_OBS", None)
            try:
                res = federation_config1(rounds=rounds, telemetry=True,
                                         **kw)
            finally:
                if saved is None:
                    os.environ.pop("BFLC_DEVICE_OBS", None)
                else:
                    os.environ["BFLC_DEVICE_OBS"] = saved
            if legacy:
                legacy_last = res
                legacy_times.append(res["fast"]["round_wall_time_s"])
            else:
                armed_last = res
                armed_times.append(res["fast"]["round_wall_time_s"])
    armed_t, legacy_t = min(armed_times), min(legacy_times)
    return {
        "rounds": rounds, "trials": trials,
        "round_wall_time_s_device_armed": armed_t,
        "round_wall_time_s_device_legacy": legacy_t,
        "round_times_armed": armed_times,
        "round_times_legacy": legacy_times,
        "overhead_frac": (round(armed_t / legacy_t - 1.0, 4)
                          if legacy_t else None),
        "device": armed_last["fast"].get("device"),
        "last_trial_armed": armed_last["fast"],
        "last_trial_legacy": legacy_last["fast"],
    }


# ---------------------------------------------- certified snapshots (PR 7)
def rejoin_config1(rounds: int = 300, snapshot_every: int = 50) -> Dict:
    """Rejoin cost at a few-hundred-round chain: cold replay-from-genesis
    vs certified-snapshot state-sync, through the real serving surfaces.

    Builds a config-1-geometry ledger with `rounds` committed rounds
    directly on the ledger surface (no sockets — op application is the
    replica-side work both paths share), captures the snapshot offer the
    writer would emit at the last `snapshot_every` boundary, then serves
    the chain from a real LedgerServer and times a joiner doing

    - **cold replay** (the pre-PR path): `log_range` chunks from genesis,
      every op re-applied;
    - **state-sync** (ledger.snapshot): fetch the `snapshot` offer,
      `verify_snapshot_meta`, `restore_snapshot`, replay only the tail.

    The writer keeps its full log for this measurement (a GC'd writer
    cannot serve the cold leg at all — that is the point of the feature);
    both joiners must land on the writer's exact chain head or the
    result is discarded.
    """
    import hashlib as _hl

    import numpy as np

    from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                                   LedgerServer)
    from bflc_demo_tpu.ledger import LedgerStatus, make_ledger
    from bflc_demo_tpu.ledger.snapshot import (make_snapshot_op,
                                               restore_snapshot,
                                               snapshot_base_head,
                                               verify_snapshot_meta)
    from bflc_demo_tpu.utils.serialization import pack_pytree

    cfg = DEFAULT_PROTOCOL
    addrs = [f"0x{i:040x}" for i in range(cfg.client_num)]
    led = make_ledger(cfg, backend="python")
    for a in addrs:
        assert led.register_node(a) == LedgerStatus.OK
    snap_round = (rounds - 1) // snapshot_every * snapshot_every
    meta = None
    model_blob = pack_pytree({"W": np.zeros((5, 2), np.float32)})
    for r in range(rounds):
        ep = led.epoch
        committee = set(led.committee())
        got = 0
        for a in addrs:
            if a in committee:
                continue
            h = _hl.sha256(f"{ep}|{a}".encode()).digest()
            if led.upload_local_update(a, h, 10, 1.0,
                                       ep) == LedgerStatus.OK:
                got += 1
            if got >= cfg.needed_update_count:
                break
        row = [0.5 + 0.01 * u for u in range(cfg.needed_update_count)]
        for a in committee:
            assert led.upload_scores(a, ep, row) == LedgerStatus.OK
        model_blob = pack_pytree(
            {"W": np.full((5, 2), float(ep + 1), np.float32)})
        mh = _hl.sha256(model_blob).digest()
        assert led.commit_model(mh, ep) == LedgerStatus.OK
        if led.epoch == snap_round and meta is None and snap_round:
            pos, prev = led.log_size(), led.log_head()
            state = led.encode_state()
            op = make_snapshot_op(led)
            assert led.apply_op(op) == LedgerStatus.OK
            meta = {"i": pos, "epoch": led.epoch,
                    "gen": led.generation, "op": op, "prev_head": prev,
                    "cert": None, "state": state, "model": model_blob,
                    "final": True}
    assert meta is not None, "rounds too small for snapshot_every"
    size, head = led.log_size(), led.log_head()

    server = LedgerServer(cfg, model_blob, resume_ledger=led,
                          resume_snapshot=meta)
    server.start()
    client = CoordinatorClient(server.host, server.port)
    try:
        def _fetch_apply(dst, start, end, chunk=1024):
            for lo in range(start, end, chunk):
                r = client.request("log_range", start=lo,
                                   end=min(lo + chunk, end))
                assert r["ok"], r
                for o in r["ops"]:
                    st = dst.apply_op(bytes.fromhex(o))
                    assert st == LedgerStatus.OK, st
            return dst

        t0 = time.perf_counter()
        cold = _fetch_apply(make_ledger(cfg, backend="python"), 0, size)
        cold_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        offer = client.request("snapshot")
        assert offer.get("ok"), offer
        # hex under the legacy wire pin, raw bytes on the binary frame —
        # normalize exactly like every other offer consumer
        from bflc_demo_tpu.comm.wire import blob_bytes
        offer["state"] = blob_bytes(offer["state"])
        offer["model"] = blob_bytes(offer["model"])
        reason = verify_snapshot_meta(offer)
        assert not reason, reason
        synced = restore_snapshot(offer["state"], cfg,
                                  int(offer["i"]) + 1,
                                  snapshot_base_head(offer))
        _fetch_apply(synced, int(offer["i"]) + 1, size)
        sync_s = time.perf_counter() - t0
    finally:
        client.close()
        server.close()

    heads_equal = (cold.log_head() == head == synced.log_head())
    return {
        "rounds": rounds, "snapshot_every": snapshot_every,
        "snapshot_at_round": int(meta["epoch"]),
        "log_ops": size, "tail_ops": size - int(meta["i"]) - 1,
        "snapshot_state_bytes": len(meta["state"]),
        "snapshot_model_bytes": len(meta["model"]),
        "cold_replay_s": round(cold_s, 4),
        "state_sync_s": round(sync_s, 4),
        "speedup_x": round(cold_s / sync_s, 2) if sync_s else None,
        "heads_equal": bool(heads_equal),
    }


# ------------------------------------ async buffered aggregation (PR 9)
def _async_leg_summary(res, acc_targets) -> Dict:
    """Per-leg throughput + time-to-accuracy off the sponsor's own
    observations (epoch_times pairs with accuracy_history by epoch)."""
    t_of_epoch = dict(res.epoch_times)
    ts = [t for _, t in res.epoch_times]
    throughput = ((len(ts) - 1) / (ts[-1] - ts[0])
                  if len(ts) >= 2 and ts[-1] > ts[0] else None)
    tta, tta_net = {}, {}
    for target in acc_targets:
        hit = next((ep for ep, acc in res.accuracy_history
                    if acc >= target), None)
        if hit is not None and hit in t_of_epoch:
            tta[str(target)] = round(t_of_epoch[hit], 2)
            # net of fleet spawn (identical for both legs but large on
            # this host — 20 jax child imports): time from the FIRST
            # observed commit to the target
            tta_net[str(target)] = round(t_of_epoch[hit] - ts[0], 2) \
                if ts else None
        else:
            tta[str(target)] = tta_net[str(target)] = None
    return {
        "rounds": res.rounds_completed,
        "wall_time_s": round(res.wall_time_s, 2),
        "time_to_first_round_s": round(ts[0], 2) if ts else None,
        "round_wall_time_s": (round(1.0 / throughput, 4)
                              if throughput else None),
        "rounds_per_sec": (round(throughput, 4) if throughput
                           else None),
        "best_acc": round(res.best_accuracy(), 4),
        "final_acc": round(res.final_accuracy, 4),
        "time_to_acc_s": tta,
        "time_to_acc_net_s": tta_net,
        "chaos_violations": (res.chaos_report or {}).get("violations"),
    }


def _async_leg_traces(telemetry_dir: str) -> Optional[Dict]:
    """Straggler evidence off the causal traces: per-round top upload
    straggler and the critical-path label shares — the before/after
    instrument PR 8 staged for exactly this benchmark."""
    from bflc_demo_tpu.obs import trace as obs_trace
    spans = obs_trace.gather_spans(telemetry_dir)
    if not spans:
        return None
    reports = obs_trace.round_reports(spans)
    if not reports:
        return None
    tops = [rep["stragglers"][0] for rep in reports
            if rep["stragglers"]]
    lags = sorted(lag for _r, lag in tops)
    stats = obs_trace.segment_stats(reports)
    ranked = sorted(((lbl, s["mean_s"]) for lbl, s in stats.items()),
                    key=lambda kv: -kv[1])
    return {
        "rounds_reassembled": len(reports),
        "top_straggler_lag_p50_s": (round(lags[len(lags) // 2], 3)
                                    if lags else None),
        "top_straggler_lag_max_s": (round(lags[-1], 3)
                                    if lags else None),
        "critical_path_top_segments": [
            [lbl, round(mean, 3)] for lbl, mean in ranked[:6]],
        "critical_path_cover": [round(r["covered_frac"], 3)
                                for r in reports],
    }


def async_agg_config1(rounds: int = 6, *, buffer_k: int = 8,
                      max_staleness: int = 20,
                      chaos_seed: int = 1234,
                      trace_sample: float = 0.5,
                      acc_targets=(0.80, 0.85, 0.88),
                      clients: int = 0,
                      async_rounds: int = 0,
                      timeout_s: float = 900.0) -> Dict:
    """THE async-aggregation headline (ISSUE 9): sync vs async legs at
    config-1 BFT geometry (20 clients + 2 standbys + 4 validators +
    quorum-1 + WAL) under the `heavytail` chaos profile — every client
    gets one seeded lognormal coordinator-bound frame delay for the
    whole run, so a few clients are persistent stragglers and the
    synchronous round barrier pays for the slowest one every round.

    Sync leg: the unchanged round protocol (async_buffer=0).  Async
    leg: --async-buffer K — the writer aggregates every K admissions
    with FedBuff staleness-discounted weights (1/sqrt(1+s)) and no
    round barrier.  SAME chaos seed both legs: the per-client delay
    draw is identical, so the measured delta is pure barrier cost.

    Reports round throughput, time-to-accuracy at `acc_targets`, and
    the causal-trace evidence (tools/trace_report.py's instrument):
    per-round top-straggler lag and critical-path segment shares —
    the straggler segment must dominate the sync leg's path and
    vanish from the async leg's.

    `clients` scales the geometry down (tests/bench-budget twins);
    0 = the full config-1 fleet.  `async_rounds` gives the async leg
    its own round budget (0 = 3x `rounds`): an async round drains only
    K deltas so it is cheaper AND weaker than a full sync round —
    time-to-accuracy, not round count, is the apples-to-apples axis,
    and the async leg needs enough rounds to reach the targets."""
    import dataclasses as _dc

    from bflc_demo_tpu.data import load_occupancy, iid_shards

    base = DEFAULT_PROTOCOL
    if clients:
        n = clients
        base = ProtocolConfig(
            client_num=n, comm_count=max(2, n // 5),
            aggregate_count=max(2, n // 4),
            needed_update_count=max(2, n // 2),
            learning_rate=0.05, batch_size=32).validate()
        buffer_k = min(buffer_k, n - base.comm_count)
    xtr, ytr, xte, yte = load_occupancy()
    shards = iid_shards(xtr, ytr, base.client_num)

    def _leg(async_k: int) -> Dict:
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        cfg = (_dc.replace(base, async_buffer=async_k,
                           max_staleness=max_staleness).validate()
               if async_k else base)
        leg_rounds = ((async_rounds or 3 * rounds) if async_k
                      else rounds)
        with tempfile.TemporaryDirectory(
                prefix="bflc-async-bench-") as td:
            tdir = os.path.join(td, "telemetry")
            res = run_federated_processes(
                "make_softmax_regression", shards, (xte, yte), cfg,
                rounds=leg_rounds, standbys=2, quorum=1,
                bft_validators=4,
                wal_path=os.path.join(td, "writer.wal"),
                chaos_seed=chaos_seed, chaos_profile="heavytail",
                chaos_duration_s=timeout_s,
                chaos_dir=os.path.join(td, "chaos"),
                telemetry_dir=tdir, trace_sample=trace_sample,
                timeout_s=timeout_s)
            out = _async_leg_summary(res, acc_targets)
            out["trace"] = _async_leg_traces(tdir)
        out["async_buffer"] = async_k
        return out

    sync = _leg(0)
    async_leg = _leg(buffer_k)
    out: Dict = {
        "geometry": {"clients": base.client_num, "standbys": 2,
                     "validators": 4, "quorum": 1, "wal": True,
                     "rounds": rounds, "chaos_profile": "heavytail",
                     "chaos_seed": chaos_seed,
                     "buffer_k": buffer_k,
                     "max_staleness": max_staleness},
        "sync": sync,
        "async": async_leg,
    }
    if sync.get("rounds_per_sec") and async_leg.get("rounds_per_sec"):
        out["round_throughput_speedup"] = round(
            async_leg["rounds_per_sec"] / sync["rounds_per_sec"], 2)
    # time-to-accuracy speedup at the tightest target BOTH legs hit —
    # net of the (identical) fleet-spawn cost where possible, raw
    # otherwise
    for key in ("time_to_acc_net_s", "time_to_acc_s"):
        for target in sorted(acc_targets, reverse=True):
            ts_, ta = (sync[key].get(str(target)),
                       async_leg[key].get(str(target)))
            if ts_ is not None and ta is not None:
                # a 0.0 net time (target hit at the first observed
                # commit) is a legitimate measurement, not a miss —
                # clamp the denominator instead of skipping it
                out["time_to_acc_target"] = target
                out["time_to_acc_basis"] = key
                out["time_to_acc_speedup"] = round(
                    ts_ / max(ta, 1e-2), 2)
                break
        if "time_to_acc_speedup" in out:
            break
    return out


# ---------------------------------- on-mesh batched aggregation (meshagg)
def mesh_agg_config1(batch_sizes=(64, 256, 1024), repeats: int = 5,
                     score_leg: bool = True, seed: int = 0) -> Dict:
    """Aggregate+score wall time vs stacked-delta count: the meshagg
    engine's one-compiled-program leg against the pre-engine O(N) host
    loop, at the geometries the scaling story cares about (a hier root
    draining hundreds of cell partials, an async buffer at fleet scale).

    Per batch size N: N admitted-shaped deltas (a many-leaf
    transformer-like tree — 24 leaves, ~9.6k params — the shape where
    the host loop's NxL interpreter dispatches bite) merged under
    REDUCTION SPEC v1 by three legs: the verbatim pre-engine loop
    (``legacy``, the host-loop baseline), the spec's FTZ host loop, and
    the compiled mesh leg over ADMISSION-STAGED rows (exactly the
    writer's path: rows are flattened when each upload is admitted, so
    the aggregate pays one stack + two program dispatches).  The
    certified canonical-bytes hashes of all three must be EQUAL — the
    differential evidence rides the artifact.  Timed warm over
    `repeats` runs with the compile-bearing first mesh call reported
    separately; plus the committee-scoring axis: all N candidates
    evaluated in one batched program vs one dispatch per candidate
    (the reference's per-model loop shape, main.py:212-217).

    The host loop's cost is Θ(N x leaves) interpreter dispatches; the
    mesh leg's Python cost is O(1) — the claim is flat-or-sublinear
    growth for the mesh leg against the host loop's linear ramp, not
    absolute times (on cpu-fallback the ratios are the artifact).
    Engine evidence (platform, device count, which leg ran, compile
    count, self-check verdict) is embedded so a BENCH json can never
    again claim "cpu-fallback" with no device story.
    """
    import hashlib as _hl
    import statistics

    import numpy as np

    from bflc_demo_tpu.meshagg import spec as magg_spec
    from bflc_demo_tpu.meshagg.engine import (ENGINE, flatten_delta,
                                              score_candidates_batched)
    from bflc_demo_tpu.utils.serialization import pack_entries

    import jax
    import jax.numpy as jnp

    shapes = {f"/L{i:02d}": (20, 20) for i in range(24)}
    params_per_delta = sum(int(np.prod(s)) for s in shapes.values())
    keys = sorted(shapes)
    rng = np.random.default_rng(seed)
    g = {k: rng.standard_normal(s).astype(np.float32)
         for k, s in shapes.items()}

    def apply_fn(params, x):
        h = jnp.tanh(x @ params["/L00"])
        return h @ params["/L01"][:, :16]

    x = rng.standard_normal((64, 20)).astype(np.float32)
    y = np.eye(16, dtype=np.float32)[rng.integers(0, 16, size=64)]
    xj, yj = jnp.asarray(x), jnp.asarray(y)

    # arm the engine's one-time differential self-check so the
    # artifact's `selfcheck` verdict is a real measurement, not
    # "untested" (forced legs below bypass the policy that runs it)
    ENGINE.run_selfcheck()
    compile_before = ENGINE.compile_total
    legs: Dict = {}
    all_equal = True
    for n in batch_sizes:
        deltas = [{k: (rng.standard_normal(s) * 0.01).astype(np.float32)
                   for k, s in shapes.items()} for _ in range(n)]
        weights = [float(rng.integers(8, 64)) for _ in range(n)]
        selected = list(range(n))           # a full drain/merge
        lr = 0.05
        # the writer stages rows at ADMISSION — off the aggregate
        # critical path — so they are prebuilt (untimed) here
        rows = [flatten_delta(d, keys) for d in deltas]

        def run_mesh():
            return ENGINE.aggregate_rows(g, rows, weights, selected,
                                         lr, force_leg="mesh")

        def run_host(leg):
            return ENGINE.aggregate_flat(g, deltas, weights, selected,
                                         lr, force_leg=leg)

        # compile-bearing first mesh call, then warm medians all legs
        t0 = time.perf_counter()
        out_mesh = run_mesh()
        first_mesh_s = time.perf_counter() - t0
        mesh_t, host_t = [], []
        for _ in range(repeats):
            t0 = time.perf_counter()
            run_mesh()
            mesh_t.append(time.perf_counter() - t0)
        for _ in range(repeats):
            t0 = time.perf_counter()
            out_host = run_host("legacy")
            host_t.append(time.perf_counter() - t0)
        out_spec_host = run_host("host")
        h_host = _hl.sha256(pack_entries(out_host)).hexdigest()
        h_spec = _hl.sha256(pack_entries(out_spec_host)).hexdigest()
        h_mesh = _hl.sha256(pack_entries(out_mesh)).hexdigest()
        equal = h_host == h_mesh == h_spec
        all_equal = all_equal and equal

        row = {
            "host_agg_s": round(statistics.median(host_t), 6),
            "mesh_agg_s": round(statistics.median(mesh_t), 6),
            "mesh_first_call_s": round(first_mesh_s, 6),
            "agg_speedup_x": round(
                statistics.median(host_t)
                / max(statistics.median(mesh_t), 1e-9), 2),
            "hashes_equal": equal,
        }
        if score_leg:
            from bflc_demo_tpu.meshagg.engine import \
                stacked_tree_from_rows

            def score_once():
                # the staged-rows fast path: one stack + one device
                # put per LEAF + one vmapped program (timed end to end
                # including the stacking — the committee-at-scale cost)
                st = stacked_tree_from_rows(rows, g)
                return np.asarray(score_candidates_batched(
                    apply_fn, g, None, lr, xj, yj, stacked=st))

            score_once()                            # warm (compile)
            sc_t = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                score_once()
                sc_t.append(time.perf_counter() - t0)
            row["score_batched_s"] = round(
                statistics.median(sc_t), 6)
            # the reference-shaped loop: one dispatch per candidate
            from bflc_demo_tpu.core.losses import accuracy as _acc

            tmpl_deltas = [{k: jnp.asarray(d[k]) for k in shapes}
                           for d in deltas]

            @jax.jit
            def _eval_one(params, d, x_, y_):
                cand = {k: params[k] - lr * d[k] for k in params}
                return _acc(apply_fn(cand, x_), y_)

            _eval_one(g, tmpl_deltas[0], xj, yj)    # warm
            t0 = time.perf_counter()
            for d in tmpl_deltas:
                _eval_one(g, d, xj, yj)
            row["score_loop_s"] = round(time.perf_counter() - t0, 6)
            row["score_speedup_x"] = round(
                row["score_loop_s"] / max(row["score_batched_s"],
                                          1e-9), 2)
        legs[n] = row

    n_lo, n_hi = min(batch_sizes), max(batch_sizes)
    out = {
        "geometry": {"leaf_shapes": {k: list(s)
                                     for k, s in shapes.items()},
                     "params_per_delta": params_per_delta,
                     "batch_sizes": list(batch_sizes),
                     "spec_version": magg_spec.SPEC_VERSION},
        "platform": jax.default_backend(),
        "device_count": jax.device_count(),
        "legs": legs,
        "hashes_equal": all_equal,
        "programs_compiled": ENGINE.compile_total - compile_before,
        "engine": ENGINE.report(),
        # growth across the measured range: 1.0 = flat, n_hi/n_lo =
        # perfectly linear
        "n_growth_x": round(n_hi / n_lo, 2),
        "host_agg_growth_x": round(
            legs[n_hi]["host_agg_s"]
            / max(legs[n_lo]["host_agg_s"], 1e-9), 2),
        "mesh_agg_growth_x": round(
            legs[n_hi]["mesh_agg_s"]
            / max(legs[n_lo]["mesh_agg_s"], 1e-9), 2),
    }
    return out


# ----------------------------- blocked reduction (REDUCTION SPEC v2)
def blocked_agg_config1(batch_sizes=(64, 256), blocks_sweep=(1, 4, 16),
                        repeats: int = 3, seed: int = 0,
                        sharded_leaves: int = 96,
                        sharded_n: int = 64) -> Dict:
    """REDUCTION SPEC v2 headline: blocked aggregation vs the v1 mesh
    leg and the host loop, blocks x N sweep, byte-equality asserted on
    every cell — plus a SHARDED-MODEL leg whose stacked (N, P) delta
    matrix is deliberately larger than what the v1 single-buffer
    staging path wants to hold at once.

    Per (N, blocks) cell: the same 24-leaf admitted-shaped tree as
    ``mesh_agg_config1``, merged over ADMISSION-STAGED rows by the
    blocked mesh leg (`blocks > 1`: the params axis is partitioned
    into the genome's fixed contiguous blocks; within each block the
    accumulation is the verbatim v1 strict-slot-order FTZ chain, and
    per-block partials CONCATENATE in ascending block order — no
    cross-block arithmetic, so the bytes cannot move).  The certified
    canonical-bytes hashes of every leg (v1 mesh, blocked mesh at
    every swept geometry, v1 host loop, blocked host reference) must
    be EQUAL — the differential evidence rides the artifact, and
    `agg_speedup_vs_v1_x` (best blocked cell vs the v1 mesh leg at the
    largest N) is evidence, not a gate, on cpu-fallback.

    The sharded-model leg scales P up (`sharded_leaves` x (40, 40)
    leaves) until the v1 path's one (N, P) float32 staging buffer is
    `single_buffer_bytes` while the blocked leg's peak per-program
    staging is ~1/blocks of that (`blocked_staging_bytes`) — the
    geometry where a round whose delta matrix exceeds one chip's HBM
    runs as a sequence of per-block programs (or one params-sharded
    cube program on a multi-chip mesh) instead of falling back to the
    host loop.  Both legs must COMPLETE with equal hashes here; walls
    ride the artifact."""
    import hashlib as _hl
    import statistics

    import numpy as np

    from bflc_demo_tpu.meshagg import spec as magg_spec
    from bflc_demo_tpu.meshagg.engine import ENGINE, flatten_delta
    from bflc_demo_tpu.utils.serialization import pack_entries

    import jax

    shapes = {f"/L{i:02d}": (20, 20) for i in range(24)}
    params_per_delta = sum(int(np.prod(s)) for s in shapes.values())
    keys = sorted(shapes)
    rng = np.random.default_rng(seed)
    g = {k: rng.standard_normal(s).astype(np.float32)
         for k, s in shapes.items()}

    ENGINE.run_selfcheck()
    compile_before = ENGINE.compile_total
    legs: Dict = {}
    all_equal = True
    speedup_vs_v1 = None
    for n in batch_sizes:
        deltas = [{k: (rng.standard_normal(s) * 0.01).astype(np.float32)
                   for k, s in shapes.items()} for _ in range(n)]
        weights = [float(rng.integers(8, 64)) for _ in range(n)]
        selected = list(range(n))
        lr = 0.05
        rows = [flatten_delta(d, keys) for d in deltas]

        def run(leg, blocks):
            return ENGINE.aggregate_rows(g, rows, weights, selected,
                                         lr, force_leg=leg,
                                         blocks=blocks)

        # v1 host loop: the normative reference bytes for this cell
        out_host = run("host", 1)
        h_ref = _hl.sha256(pack_entries(out_host)).hexdigest()
        cells: Dict = {}
        v1_median = None
        for blocks in blocks_sweep:
            b = min(int(blocks), params_per_delta)
            out_b = run("mesh", b)               # compile-bearing
            t_first = None
            ts = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                run("mesh", b)
                ts.append(time.perf_counter() - t0)
            h_b = _hl.sha256(pack_entries(out_b)).hexdigest()
            # the blocked HOST reference must agree too (spec leg)
            out_bh = run("host", b) if b > 1 else out_host
            equal = (h_b == h_ref
                     and _hl.sha256(pack_entries(out_bh)).hexdigest()
                     == h_ref)
            all_equal = all_equal and equal
            med = statistics.median(ts)
            if b == 1:
                v1_median = med
            cells[b] = {"mesh_agg_s": round(med, 6),
                        "hashes_equal": equal}
            if b > 1 and v1_median is not None:
                cells[b]["speedup_vs_v1_x"] = round(
                    v1_median / max(med, 1e-9), 2)
        legs[n] = cells
        if v1_median is not None and n == max(batch_sizes):
            best = max((c.get("speedup_vs_v1_x", 0.0)
                        for b, c in cells.items() if b > 1),
                       default=None)
            speedup_vs_v1 = best

    # --- sharded-model leg: P large enough that the v1 (N, P) stack
    # is the problem, not the reduction
    sh_shapes = {f"/S{i:03d}": (40, 40) for i in range(sharded_leaves)}
    sh_params = sum(int(np.prod(s)) for s in sh_shapes.values())
    sh_keys = sorted(sh_shapes)
    sh_g = {k: rng.standard_normal(s).astype(np.float32)
            for k, s in sh_shapes.items()}
    sh_deltas = [{k: (rng.standard_normal(s) * 0.01).astype(np.float32)
                  for k, s in sh_shapes.items()}
                 for _ in range(sharded_n)]
    sh_w = [float(rng.integers(8, 64)) for _ in range(sharded_n)]
    sh_sel = list(range(sharded_n))
    sh_rows = [flatten_delta(d, sh_keys) for d in sh_deltas]
    sh_blocks = max(b for b in blocks_sweep if b > 1) \
        if any(b > 1 for b in blocks_sweep) else 16
    sharded = {
        "leaves": sharded_leaves, "params_per_delta": sh_params,
        "n": sharded_n, "blocks": sh_blocks,
        # the v1 mesh leg stages ONE (N, P) float32 buffer; the
        # blocked leg's peak per-program staging is one (N, ceil(P/B))
        # block — the ~1/B memory story in bytes
        "single_buffer_bytes": 4 * sharded_n * sh_params,
        "blocked_staging_bytes": 4 * sharded_n
        * (-(-sh_params // sh_blocks)),
    }
    try:
        t0 = time.perf_counter()
        out_v1 = ENGINE.aggregate_rows(sh_g, sh_rows, sh_w, sh_sel,
                                       0.05, force_leg="mesh",
                                       blocks=1)
        sharded["v1_wall_s"] = round(time.perf_counter() - t0, 6)
        v1_ok = True
    except Exception as e:                      # noqa: BLE001 — the
        # single-buffer path MAY legitimately die on a too-large stack
        # (the exact failure the blocked leg exists to remove)
        sharded["v1_error"] = f"{type(e).__name__}: {e}"[:200]
        out_v1, v1_ok = None, False
    t0 = time.perf_counter()
    out_blk = ENGINE.aggregate_rows(sh_g, sh_rows, sh_w, sh_sel, 0.05,
                                    force_leg="mesh", blocks=sh_blocks)
    sharded["blocked_wall_s"] = round(time.perf_counter() - t0, 6)
    sharded["completed"] = True
    h_blk = _hl.sha256(pack_entries(out_blk)).hexdigest()
    if v1_ok:
        sharded["hashes_equal"] = (
            h_blk == _hl.sha256(pack_entries(out_v1)).hexdigest())
    else:
        # no v1 bytes to compare — the blocked host reference is the
        # normative stand-in
        ref = ENGINE.aggregate_rows(sh_g, sh_rows, sh_w, sh_sel, 0.05,
                                    force_leg="host",
                                    blocks=sh_blocks)
        sharded["hashes_equal"] = (
            h_blk == _hl.sha256(pack_entries(ref)).hexdigest())
    all_equal = all_equal and sharded["hashes_equal"]

    out = {
        "geometry": {"params_per_delta": params_per_delta,
                     "batch_sizes": list(batch_sizes),
                     "blocks_sweep": list(blocks_sweep),
                     "spec_version": magg_spec.SPEC_VERSION},
        "platform": jax.default_backend(),
        "device_count": jax.device_count(),
        "legs": legs,
        "sharded_model": sharded,
        "hashes_equal": all_equal,
        "programs_compiled": ENGINE.compile_total - compile_before,
        "engine": ENGINE.report(),
    }
    if speedup_vs_v1 is not None:
        out["agg_speedup_vs_v1_x"] = speedup_vs_v1
    return out
