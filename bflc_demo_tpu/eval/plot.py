"""Run-evidence plots: the reference's `imgs/runtime.jpg` made reproducible.

The reference's only published run evidence is a screenshot of terminal
logs — the sponsor accuracy line and four identical node-loss lines
(README.md:400-410).  This renders the same evidence from a
`SimulationResult` (any runtime) as an actual artifact: sponsor test
accuracy per epoch with the reference's 0.9214 acceptance line, global
training loss on a log axis, and per-round wall time.

Headless-safe (Agg backend, set before pyplot import).  CLI:
`python -m bflc_demo_tpu --config config1 --plot-path run.png`.
"""

from __future__ import annotations

from typing import Optional

REFERENCE_ACC = 0.9214          # sponsor line at epoch 009, imgs/runtime.jpg


def plot_run(result, path: str, title: str = "",
             reference_acc: Optional[float] = REFERENCE_ACC) -> str:
    """Write a 3-panel PNG for a finished run; returns the path."""
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    acc = list(result.accuracy_history)
    losses = list(getattr(result, "loss_history", []) or [])
    times = list(getattr(result, "round_times_s", []) or [])
    n_panels = 1 + bool(losses) + bool(times)
    fig, axes = plt.subplots(1, n_panels, figsize=(5 * n_panels, 3.4))
    if n_panels == 1:
        axes = [axes]
    ax = axes[0]
    if acc:
        ax.plot([e for e, _ in acc], [a for _, a in acc],
                marker="o", lw=1.5, label="sponsor test acc")
    if reference_acc is not None:
        ax.axhline(reference_acc, ls="--", lw=1, color="0.4",
                   label=f"reference {reference_acc:.4f}")
    ax.set_xlabel("epoch")
    ax.set_ylabel("test accuracy")
    ax.legend(loc="lower right", fontsize=8)
    ax.set_title(title or "sponsor accuracy")
    i = 1
    if losses:
        # loss_history entries are (epoch, loss) tuples (SimulationResult)
        axes[i].plot([e for e, _ in losses], [v for _, v in losses],
                     marker=".", lw=1.2)
        axes[i].set_yscale("log")
        axes[i].set_xlabel("epoch")
        axes[i].set_ylabel("global loss")
        axes[i].set_title("committee-selected avg cost")
        i += 1
    if times:
        axes[i].bar(range(len(times)), times, width=0.8)
        axes[i].set_xlabel("round")
        axes[i].set_ylabel("seconds")
        axes[i].set_title("round wall time")
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path
