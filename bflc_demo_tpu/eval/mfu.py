"""MFU accounting: XLA-measured FLOPs per protocol round / chip peak.

The perf bar for a TPU-native framework is model-FLOPs utilisation, not
just wall time (VERDICT round-2 weak #3).  The numerator here is NOT a
hand-derived formula: the mesh runtime lowers its round program with the
real staged arguments and reads XLA's compiled cost analysis, so training,
ring committee scoring, the decision, the psum merge and the fingerprints
are all counted exactly as compiled (remat recompute included).

The denominator is the chip's published peak (bf16 MXU throughput — the
dense-matmul ceiling; running f32 makes the reported MFU conservative).
`BFLC_TPU_PEAK_TFLOPS` overrides for unlisted hardware.
"""

from __future__ import annotations

import os
from typing import Optional

# published dense bf16 peaks, TFLOP/s per chip
_PEAKS_TFLOPS = (
    ("v6", 918.0),          # Trillium
    ("trillium", 918.0),
    ("v5p", 459.0),
    ("v5 lite", 197.0),     # v5e device_kind string
    ("v5e", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def chip_peak_flops(device=None) -> Optional[float]:
    """Peak FLOP/s for one chip, or None when unknown / not an accelerator.
    Env override: BFLC_TPU_PEAK_TFLOPS (in TFLOP/s)."""
    env = os.environ.get("BFLC_TPU_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    if device is None:
        import jax
        device = jax.devices()[0]
    if device.platform != "tpu":
        return None
    kind = getattr(device, "device_kind", "").lower()
    for token, tflops in _PEAKS_TFLOPS:
        if token in kind:
            return tflops * 1e12
    return None


def cost_analysis_flops(compiled, family: str = "mfu") -> float:
    """FLOPs from a jax AOT `compiled` object; 0.0 when the backend does
    not report them.  Routed through the device plane's ONE shared
    helper (obs.device.cost_analysis_stats), so an unusable backend
    reply counts ``device_cost_analysis_unavailable_total{family}``
    instead of vanishing in a bare swallow."""
    from bflc_demo_tpu.obs import device as obs_device
    return obs_device.cost_analysis_stats(compiled, family)["flops"]
