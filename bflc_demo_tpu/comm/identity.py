"""Client identity + authenticated coordinator access.

The reference gives every simulated client an ECDSA keypair
(bin/get_batch_accounts.sh; SDK signer patch README.md:348-359) and the chain
authenticates transactions at the transport layer — the contract itself
trusts `origin`.  This module plays the same role at the same boundary:

- `KeyRing`: derives per-client secrets from a master seed (the
  get_batch_accounts.sh equivalent — one command provisions N identities)
  and issues per-op MACs;
- `AuthenticatedLedger`: a proxy that verifies a client's MAC over the
  canonical op bytes before forwarding to ANY ledger backend — mutations
  from unknown identities or with bad/replayed tags are rejected with
  BAD_ARG before the coordinator sees them, exactly as the chain rejected
  unsigned transactions before the contract ran.

MACs are HMAC-SHA256 (shared-secret, provisioned at registration — the
trust bootstrap the reference got from copying PEM files to clients).  Tags
bind the op KIND, the sender, the epoch and the payload, and each tag is
single-use per ledger instance (replay of an observed tag is rejected).
"""

from __future__ import annotations

import hashlib
import hmac
import struct
from typing import Dict, Sequence

from bflc_demo_tpu.ledger.base import LedgerStatus


class KeyRing:
    """Per-client secrets derived from one master seed."""

    def __init__(self, master_seed: bytes):
        if len(master_seed) < 16:
            raise ValueError("master seed must be at least 16 bytes")
        self._master = bytes(master_seed)

    def secret_for(self, address: str) -> bytes:
        return hashlib.sha256(self._master + b"|" + address.encode()).digest()

    def mac(self, address: str, op_bytes: bytes) -> bytes:
        return hmac.new(self.secret_for(address), op_bytes,
                        hashlib.sha256).digest()


def _op_bytes(kind: str, sender: str, epoch: int, payload: bytes) -> bytes:
    b = bytearray()
    kb = kind.encode()
    sb = sender.encode()
    b += struct.pack("<q", len(kb)) + kb
    b += struct.pack("<q", len(sb)) + sb
    b += struct.pack("<q", epoch)
    b += struct.pack("<q", len(payload)) + payload
    return bytes(b)


class AuthenticatedLedger:
    """MAC-verifying proxy in front of a ledger backend.

    Client-originated mutations (register/upload/scores) require a valid
    tag; reads and the runtime's coordinator-side ops (commit, recovery)
    pass through — they are issued by the op-log writer itself, whose
    authority is the log (comm/multihost.is_ledger_writer), not a client
    identity.
    """

    def __init__(self, inner, keyring: KeyRing):
        self._inner = inner
        self._keys = keyring
        # replay tracking bucketed by op epoch: stale buckets are pruned once
        # the ledger moves past them (replays of old-epoch tags already fail
        # the inner WRONG_EPOCH guard), keeping the set O(ops per round)
        self._seen_tags: Dict[int, set] = {}

    # --- authenticated mutations ---
    def _verify(self, kind: str, sender: str, epoch: int, payload: bytes,
                tag: bytes) -> bool:
        expect = self._keys.mac(sender, _op_bytes(kind, sender, epoch,
                                                  payload))
        if not hmac.compare_digest(expect, tag):
            return False
        return tag not in self._seen_tags.get(epoch, ())

    def _consume(self, epoch: int, tag: bytes) -> None:
        """Mark a tag used — called only after the inner ledger ACCEPTED the
        op, so a transiently-rejected op (e.g. scores before the round fills)
        can be legitimately retried with the same deterministic MAC."""
        current = self._inner.epoch
        for ep in [e for e in self._seen_tags if e < current]:
            del self._seen_tags[ep]
        self._seen_tags.setdefault(epoch, set()).add(tag)

    def register_node(self, addr: str, tag: bytes) -> LedgerStatus:
        if not self._verify("register", addr, 0, b"", tag):
            return LedgerStatus.BAD_ARG
        st = self._inner.register_node(addr)
        if st == LedgerStatus.OK:
            self._consume(0, tag)
        return st

    def upload_local_update(self, sender: str, payload_hash: bytes,
                            n_samples: int, avg_cost: float, epoch: int,
                            tag: bytes) -> LedgerStatus:
        body = payload_hash + struct.pack("<qd", n_samples, avg_cost)
        if not self._verify("upload", sender, epoch, body, tag):
            return LedgerStatus.BAD_ARG
        st = self._inner.upload_local_update(sender, payload_hash,
                                             n_samples, avg_cost, epoch)
        if st == LedgerStatus.OK:
            self._consume(epoch, tag)
        return st

    def upload_scores(self, sender: str, epoch: int,
                      scores: Sequence[float], tag: bytes) -> LedgerStatus:
        body = struct.pack(f"<{len(scores)}d", *scores)
        if not self._verify("scores", sender, epoch, body, tag):
            return LedgerStatus.BAD_ARG
        st = self._inner.upload_scores(sender, epoch, scores)
        if st == LedgerStatus.OK:
            self._consume(epoch, tag)
        return st

    # --- everything else passes through to the backend ---
    def __getattr__(self, name):
        return getattr(self._inner, name)


def sign_register(keys: KeyRing, addr: str) -> bytes:
    return keys.mac(addr, _op_bytes("register", addr, 0, b""))


def sign_upload(keys: KeyRing, sender: str, payload_hash: bytes,
                n_samples: int, avg_cost: float, epoch: int) -> bytes:
    body = payload_hash + struct.pack("<qd", n_samples, avg_cost)
    return keys.mac(sender, _op_bytes("upload", sender, epoch, body))


def sign_scores(keys: KeyRing, sender: str, epoch: int,
                scores: Sequence[float]) -> bytes:
    body = struct.pack(f"<{len(scores)}d", *scores)
    return keys.mac(sender, _op_bytes("scores", sender, epoch, body))
