"""Client identity + authenticated coordinator access.

The reference gives every simulated client an ECDSA keypair
(bin/get_batch_accounts.sh; SDK signer patch README.md:348-359) and the chain
authenticates transactions at the transport layer — the contract itself
trusts `origin`.  This module plays the same role at the same boundary, with
TWO trust models:

- `KeyRing`: HMAC-SHA256 shared secrets derived from a master seed — cheap,
  dependency-free, but the verifier can forge any client's tag (documented
  round-1 weakness; kept for closed single-operator deployments and tests);
- `Wallet` / `PublicDirectory`: per-client Ed25519 signing keys, matching
  the reference's trust model exactly — the coordinator holds ONLY public
  keys, so it can verify but never fabricate a client's op, and addresses
  are self-authenticating (derived from the public key like an Ethereum
  address, so claiming an address requires its private key).  Wallets also
  carry an X25519 key: `pair_secret` gives any client pair a shared seed via
  Diffie-Hellman, which `parallel.secure` uses to derive pairwise masks the
  aggregator cannot strip (closing the round-1 secure-agg key-agreement
  stub).

Both implement the same signer surface (`mac`) and verifier surface
(`verify`), so `AuthenticatedLedger` and `FLNode` take either
interchangeably.  Tags bind the op KIND, the sender, the epoch and the
payload, and each tag is single-use per ledger instance (replay of an
observed tag is rejected; Ed25519 is deterministic per RFC 8032 so honest
retries after a transient rejection re-produce the same tag).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct
import time
from typing import Dict, List, Sequence, Tuple

from bflc_demo_tpu.ledger.base import LedgerStatus
from bflc_demo_tpu.utils import tracing

try:                                    # prefer the C-backed implementation
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey, Ed25519PublicKey)
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey, X25519PublicKey)
    from cryptography.hazmat.primitives import serialization as _ser
    from cryptography.exceptions import InvalidSignature
    ED25519_BACKEND = "cryptography"
except ImportError:
    # hosts without the `cryptography` wheel (this jax image, for one) fall
    # back to the from-first-principles implementation — same key, tag and
    # DH bytes (RFC-vector-tested), so wallets interoperate across backends
    ED25519_BACKEND = "pure-python"

from bflc_demo_tpu.comm import pure25519 as _pure

# asymmetric identity is always available now that a pure-Python backend
# exists; the flag survives for callers that gated on it historically
HAVE_ED25519 = True


# --- verification memo (PR 3): repeated (pubkey, payload, sig) checks are
# structural on the certificate paths — a standby re-verifies the same cert
# sigs its promotion later re-checks, a client's retry re-verifies the ack
# certificate it already accepted once, resync replays re-present certified
# history.  Verification is a deterministic pure function, so a bounded
# memo keyed on the full triple is semantically invisible.  Disabled (like
# every control-plane fast path) by BFLC_CONTROL_PLANE_LEGACY=1 at import.
_MEMO_ENABLED = not os.environ.get("BFLC_CONTROL_PLANE_LEGACY")
_VERIFY_MEMO: Dict[bytes, bool] = {}
_VERIFY_MEMO_MAX = 8192


def _memo_key(public_bytes: bytes, message: bytes, signature: bytes,
              domain: bytes = b"1") -> bytes:
    # length-prefixed so (pub, msg, sig) concatenation is unambiguous;
    # the domain byte separates cofactorless (per-item) verdicts from
    # cofactored (batch) ones — the two semantics differ on
    # torsion-defective signatures and must never answer for each other
    h = hashlib.sha256()
    h.update(domain)
    h.update(struct.pack("<qq", len(public_bytes), len(signature)))
    h.update(public_bytes)
    h.update(signature)
    h.update(message)
    return h.digest()


def _verify_signature_raw(public_bytes: bytes, message: bytes,
                          signature: bytes) -> bool:
    if ED25519_BACKEND == "cryptography":
        try:
            Ed25519PublicKey.from_public_bytes(public_bytes).verify(
                signature, message)
            return True
        except (InvalidSignature, ValueError):
            return False
    return _pure.ed25519_verify(public_bytes, message, signature)


def _verify_signature_timed(public_bytes: bytes, message: bytes,
                            signature: bytes) -> bool:
    tr = tracing.PROC
    if tr.enabled:
        t0 = time.perf_counter()
        ok = _verify_signature_raw(public_bytes, message, signature)
        tr.charge("crypto.verify_s", time.perf_counter() - t0)
        tr.charge("crypto.verify_n")
        return ok
    return _verify_signature_raw(public_bytes, message, signature)


def verify_signature(public_bytes: bytes, message: bytes,
                     signature: bytes) -> bool:
    """THE Ed25519 verification chokepoint: every tag, promotion-evidence
    and commit-certificate check in the repo funnels here, so the two
    backends cannot drift between enforcement points.  Never raises on
    malformed input — a hostile peer's garbage is a False, not a crash."""
    if not _MEMO_ENABLED:
        return _verify_signature_timed(public_bytes, message, signature)
    key = _memo_key(public_bytes, message, signature)
    hit = _VERIFY_MEMO.get(key)
    if hit is not None:
        return hit
    ok = _verify_signature_timed(public_bytes, message, signature)
    _memo_store(key, ok)
    return ok


def _memo_store(key: bytes, ok: bool) -> None:
    if len(_VERIFY_MEMO) >= _VERIFY_MEMO_MAX:
        try:
            _VERIFY_MEMO.pop(next(iter(_VERIFY_MEMO)))
        except KeyError:                # racing evictors: already gone
            pass
    _VERIFY_MEMO[key] = ok


def verify_signatures_batch(items: Sequence[Tuple[bytes, bytes, bytes]],
                            ) -> bool:
    """Batch chokepoint: True iff EVERY (pubkey, message, signature)
    triple verifies (cofactored semantics for items that reach the
    batch — see pure25519.ed25519_verify_batch).  False only says "at
    least one failed" — a caller that needs attribution falls back to
    per-item `verify_signature`.  Under the pure-Python backend this is
    real Ed25519 batch verification (one shared multiscalar mul) fed
    through the verify memo, so a re-presented certificate (standby
    re-verify, client retry, resync replay) costs a dict lookup per
    signature instead of any curve arithmetic; under the `cryptography`
    wheel (no batch API) it is a loop, already fast there.  Honest
    batches never take the fallback."""
    if ED25519_BACKEND == "cryptography" or not _MEMO_ENABLED:
        return all(verify_signature(p, m, s) for p, m, s in items)
    pending = []
    for it in items:
        key = _memo_key(it[0], it[1], it[2], domain=b"8")
        hit = _VERIFY_MEMO.get(key)
        if hit is False:
            return False
        if hit is None:
            pending.append((key, it))
    if not pending:
        return True
    tr = tracing.PROC
    if tr.enabled:
        t0 = time.perf_counter()
        ok = _pure.ed25519_verify_batch([it for _, it in pending])
        tr.charge("crypto.verify_s", time.perf_counter() - t0)
        tr.charge("crypto.verify_n", len(pending))
    else:
        ok = _pure.ed25519_verify_batch([it for _, it in pending])
    if ok:
        # only positive results memoize here: a failed batch does not
        # attribute, and the per-item fallback will memo each verdict
        for key, _ in pending:
            _memo_store(key, True)
    return ok


class KeyRing:
    """Per-client secrets derived from one master seed (HMAC trust model)."""

    def __init__(self, master_seed: bytes):
        if len(master_seed) < 16:
            raise ValueError("master seed must be at least 16 bytes")
        self._master = bytes(master_seed)

    def secret_for(self, address: str) -> bytes:
        return hashlib.sha256(self._master + b"|" + address.encode()).digest()

    def mac(self, address: str, op_bytes: bytes) -> bytes:
        return hmac.new(self.secret_for(address), op_bytes,
                        hashlib.sha256).digest()

    def verify(self, address: str, op_bytes: bytes, tag: bytes) -> bool:
        return hmac.compare_digest(self.mac(address, op_bytes), tag)


def address_of(public_bytes: bytes) -> str:
    """Self-authenticating address: 0x + first 20 bytes of sha256(pubkey) —
    the Ethereum-style derivation, so an address claim is checkable against
    the public key that signs for it."""
    return "0x" + hashlib.sha256(public_bytes).hexdigest()[:40]


class Wallet:
    """One client's asymmetric identity: Ed25519 signing + X25519 agreement.

    The get_batch_accounts.sh equivalent (one PEM per client,
    README.md:348-359): `Wallet.from_seed` provisions deterministically for
    tests; `Wallet.generate` draws fresh OS randomness for real use.

    Constructed from RAW 32-byte private keys so the wallet is
    backend-portable: the same bytes yield identical public keys,
    signatures (Ed25519 is deterministic) and DH secrets under the
    `cryptography` wheel and the pure-Python fallback.
    """

    def __init__(self, sign_private: bytes, dh_private: bytes):
        if len(sign_private) != 32 or len(dh_private) != 32:
            raise ValueError("wallet private keys must be 32 raw bytes")
        self._sign_sk = bytes(sign_private)
        self._dh_sk = bytes(dh_private)
        if ED25519_BACKEND == "cryptography":
            self._sign = Ed25519PrivateKey.from_private_bytes(self._sign_sk)
            self._dh = X25519PrivateKey.from_private_bytes(self._dh_sk)
            self.public_bytes = self._sign.public_key().public_bytes(
                _ser.Encoding.Raw, _ser.PublicFormat.Raw)
            self.dh_public_bytes = self._dh.public_key().public_bytes(
                _ser.Encoding.Raw, _ser.PublicFormat.Raw)
        else:
            self.public_bytes = _pure.ed25519_public(self._sign_sk)
            self.dh_public_bytes = _pure.x25519_public(self._dh_sk)
        self.address = address_of(self.public_bytes)

    @classmethod
    def generate(cls) -> "Wallet":
        return cls(os.urandom(32), os.urandom(32))

    @classmethod
    def from_seed(cls, seed: bytes) -> "Wallet":
        sk = hashlib.sha256(b"bflc-ed25519|" + seed).digest()
        dk = hashlib.sha256(b"bflc-x25519|" + seed).digest()
        return cls(sk, dk)

    def sign(self, op_bytes: bytes) -> bytes:
        tr = tracing.PROC
        t0 = time.perf_counter() if tr.enabled else 0.0
        if ED25519_BACKEND == "cryptography":
            sig = self._sign.sign(op_bytes)
        else:
            sig = _pure.ed25519_sign(self._sign_sk, op_bytes)
        if tr.enabled:
            tr.charge("crypto.sign_s", time.perf_counter() - t0)
            tr.charge("crypto.sign_n")
        return sig

    # signer surface shared with KeyRing so FLNode/sign_* helpers take either
    def mac(self, address: str, op_bytes: bytes) -> bytes:
        if address != self.address:
            raise ValueError(f"wallet for {self.address} cannot sign for "
                             f"{address}")
        return self.sign(op_bytes)

    def pair_secret(self, their_dh_public: bytes, context: bytes = b"",
                    ) -> bytes:
        """X25519 shared secret with another wallet, hashed with `context`
        (e.g. the round number) — both endpoints derive the same bytes; the
        coordinator, holding neither private key, cannot."""
        if ED25519_BACKEND == "cryptography":
            shared = self._dh.exchange(X25519PublicKey.from_public_bytes(
                their_dh_public))
        else:
            shared = _pure.x25519_exchange(self._dh_sk, their_dh_public)
        return hashlib.sha256(b"bflc-pair|" + shared + b"|" + context
                              ).digest()


class PublicDirectory:
    """Verifier-side registry: address -> Ed25519 public key, nothing else.

    This is what the coordinator holds — it can check any tag but cannot
    produce one, which is the reference's trust model (the chain verifies
    ECDSA transaction signatures; node operators never hold client keys).
    """

    def __init__(self):
        self._raw: Dict[str, bytes] = {}

    def enroll(self, public_bytes: bytes) -> str:
        addr = address_of(public_bytes)
        self._raw[addr] = bytes(public_bytes)
        return addr

    def export_raw(self) -> Dict[str, bytes]:
        """address -> raw public key bytes — the standby-mirroring surface
        (public keys are public; addresses are self-authenticating, so an
        importer re-checks address_of(pub) == addr)."""
        return dict(self._raw)

    def knows(self, address: str) -> bool:
        return address in self._raw

    def verify(self, address: str, op_bytes: bytes, tag: bytes) -> bool:
        pub = self._raw.get(address)
        if pub is None:
            return False
        return verify_signature(pub, op_bytes, tag)


def provision_wallets(n: int, master_seed: bytes,
                      ) -> Tuple[List[Wallet], PublicDirectory]:
    """Provision N wallets + the coordinator's public directory — the
    one-command batch bootstrap of get_batch_accounts.sh (-n 20)."""
    wallets = [Wallet.from_seed(master_seed + struct.pack("<q", i))
               for i in range(n)]
    directory = PublicDirectory()
    for w in wallets:
        directory.enroll(w.public_bytes)
    return wallets, directory


class ReplayGuard:
    """Single-use-tag tracking bucketed by op epoch.

    Shared by `AuthenticatedLedger` (in-process trust boundary) and
    `comm.ledger_service.LedgerServer` (socket trust boundary) so the two
    enforcement points are structurally identical — not mirrored by hand.
    Buckets for epochs the ledger has moved past are pruned on consume:
    replays of old-epoch tags already fail the inner WRONG_EPOCH guard, so
    the set stays O(ops per round).
    """

    def __init__(self):
        self._seen: Dict[int, set] = {}

    def seen(self, epoch: int, tag: bytes) -> bool:
        return tag in self._seen.get(epoch, ())

    def consume(self, current_epoch: int, epoch: int, tag: bytes) -> None:
        """Mark a tag used — call only after the inner ledger ACCEPTED the
        op, so a transiently-rejected op (e.g. scores before the round
        fills) can be retried with the same deterministic signature."""
        for ep in [e for e in self._seen if e < current_epoch]:
            del self._seen[ep]
        self._seen.setdefault(epoch, set()).add(tag)


def _op_bytes(kind: str, sender: str, epoch: int, payload: bytes) -> bytes:
    b = bytearray()
    kb = kind.encode()
    sb = sender.encode()
    b += struct.pack("<q", len(kb)) + kb
    b += struct.pack("<q", len(sb)) + sb
    b += struct.pack("<q", epoch)
    b += struct.pack("<q", len(payload)) + payload
    return bytes(b)


class AuthenticatedLedger:
    """Tag-verifying proxy in front of a ledger backend.

    Client-originated mutations (register/upload/scores) require a valid
    tag; reads and the runtime's coordinator-side ops (commit, recovery)
    pass through — they are issued by the op-log writer itself, whose
    authority is the log (comm/multihost.is_ledger_writer), not a client
    identity.

    `keyring` is anything with verify(address, op_bytes, tag) -> bool:
    a `KeyRing` (HMAC shared-secret) or a `PublicDirectory` (Ed25519 —
    the verifier cannot forge).
    """

    def __init__(self, inner, keyring):
        self._inner = inner
        self._keys = keyring
        self._guard = ReplayGuard()

    # --- authenticated mutations ---
    def _verify(self, kind: str, sender: str, epoch: int, payload: bytes,
                tag: bytes) -> LedgerStatus:
        """OK = fresh valid tag; DUPLICATE = valid but already consumed (an
        honest retry whose reply was lost, or an eavesdropper's replay —
        either way the op is already in); BAD_ARG = signature failure."""
        if not self._keys.verify(sender, _op_bytes(kind, sender, epoch,
                                                   payload), tag):
            return LedgerStatus.BAD_ARG
        if self._guard.seen(epoch, tag):
            return LedgerStatus.DUPLICATE
        return LedgerStatus.OK

    def _consume(self, epoch: int, tag: bytes) -> None:
        self._guard.consume(self._inner.epoch, epoch, tag)

    def register_node(self, addr: str, tag: bytes) -> LedgerStatus:
        v = self._verify("register", addr, 0, b"", tag)
        if v != LedgerStatus.OK:
            return v
        st = self._inner.register_node(addr)
        if st == LedgerStatus.OK:
            self._consume(0, tag)
        return st

    def upload_local_update(self, sender: str, payload_hash: bytes,
                            n_samples: int, avg_cost: float, epoch: int,
                            tag: bytes) -> LedgerStatus:
        body = payload_hash + struct.pack("<qd", n_samples, avg_cost)
        v = self._verify("upload", sender, epoch, body, tag)
        if v != LedgerStatus.OK:
            return v
        st = self._inner.upload_local_update(sender, payload_hash,
                                             n_samples, avg_cost, epoch)
        if st == LedgerStatus.OK:
            self._consume(epoch, tag)
        return st

    def upload_scores(self, sender: str, epoch: int,
                      scores: Sequence[float], tag: bytes) -> LedgerStatus:
        body = struct.pack(f"<{len(scores)}d", *scores)
        v = self._verify("scores", sender, epoch, body, tag)
        if v != LedgerStatus.OK:
            return v
        st = self._inner.upload_scores(sender, epoch, scores)
        if st == LedgerStatus.OK:
            self._consume(epoch, tag)
        return st

    # --- everything else passes through to the backend ---
    def __getattr__(self, name):
        return getattr(self._inner, name)


def sign_register(keys: KeyRing, addr: str) -> bytes:
    return keys.mac(addr, _op_bytes("register", addr, 0, b""))


def sign_upload(keys: KeyRing, sender: str, payload_hash: bytes,
                n_samples: int, avg_cost: float, epoch: int) -> bytes:
    body = payload_hash + struct.pack("<qd", n_samples, avg_cost)
    return keys.mac(sender, _op_bytes("upload", sender, epoch, body))


def sign_scores(keys: KeyRing, sender: str, epoch: int,
                scores: Sequence[float]) -> bytes:
    body = struct.pack(f"<{len(scores)}d", *scores)
    return keys.mac(sender, _op_bytes("scores", sender, epoch, body))
