"""Pure-Python Ed25519 (RFC 8032) + X25519 (RFC 7748) fallback backend.

The asymmetric identity layer (comm.identity) is the reference's trust
model — the coordinator verifies but cannot forge — and round 6's BFT
commit certificates extend it to validator co-signing.  That layer must not
evaporate on hosts where the `cryptography` wheel is absent (this image
bakes in the jax toolchain, not OpenSSL bindings), so this module provides
the same two primitives from first principles over Python integers, the
same way the native ledger carries its own SHA-256 (ledger/src/sha256.cpp)
instead of assuming a crypto runtime.

Compatibility contract (exercised by tests/test_identity.py whenever both
backends are importable): byte-identical public keys, signatures and DH
shared secrets for the same raw private keys — Ed25519 is deterministic
per RFC 8032 and X25519 clamps the scalar the same way, so a wallet
provisioned under one backend verifies under the other.

Performance: a scalar multiplication is ~1 ms of bigint arithmetic — three
orders of magnitude slower than libsodium, irrelevant for control-plane
signing rates (tens of ops per federated round), and not a side-channel
surface worth hardening here (coordinator-side verification handles only
public data; test deployments on crypto-less hosts accept the caveat).
"""

from __future__ import annotations

import hashlib

_P = 2 ** 255 - 19                      # the curve25519 field prime
_L = 2 ** 252 + 27742317777372353535851937790883648493   # group order
_D = (-121665 * pow(121666, _P - 2, _P)) % _P            # edwards d


def _inv(x: int) -> int:
    return pow(x, _P - 2, _P)


# ---------------------------------------------------------------- ed25519
# Points are extended homogeneous coordinates (X, Y, Z, T) with x = X/Z,
# y = Y/Z, x*y = T/Z — the standard complete addition law, so no special
# cases for doubling or the identity.

def _pt_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % _P
    b = (y1 + x1) * (y2 + x2) % _P
    c = 2 * t1 * t2 * _D % _P
    d = 2 * z1 * z2 % _P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)


def _pt_mul(s: int, p):
    q = (0, 1, 1, 0)                    # neutral element
    while s > 0:
        if s & 1:
            q = _pt_add(q, p)
        p = _pt_add(p, p)
        s >>= 1
    return q


def _pt_equal(p, q) -> bool:
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return ((x1 * z2 - x2 * z1) % _P == 0
            and (y1 * z2 - y2 * z1) % _P == 0)


def _recover_x(y: int, sign: int):
    """x from the curve equation given y and the sign bit; None if y is
    not on the curve (RFC 8032 §5.1.3 decoding)."""
    if y >= _P:
        return None
    x2 = (y * y - 1) * _inv(_D * y * y + 1) % _P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (_P + 3) // 8, _P)
    if (x * x - x2) % _P != 0:
        x = x * pow(2, (_P - 1) // 4, _P) % _P
    if (x * x - x2) % _P != 0:
        return None
    if (x & 1) != sign:
        x = _P - x
    return x


_GY = 4 * _inv(5) % _P
_GX = _recover_x(_GY, 0)
_G = (_GX, _GY, 1, _GX * _GY % _P)      # the base point


def _compress(p) -> bytes:
    x, y, z, _ = p
    zi = _inv(z)
    x, y = x * zi % _P, y * zi % _P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def _decompress(s: bytes):
    if len(s) != 32:
        return None
    y = int.from_bytes(s, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % _P)


def _expand_seed(seed: bytes):
    """RFC 8032 §5.1.5: seed -> (clamped scalar, nonce prefix)."""
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def ed25519_public(seed: bytes) -> bytes:
    """32-byte public key for a 32-byte private seed."""
    if len(seed) != 32:
        raise ValueError("ed25519 seed must be 32 bytes")
    a, _ = _expand_seed(seed)
    return _compress(_pt_mul(a, _G))


def ed25519_sign(seed: bytes, message: bytes) -> bytes:
    """Deterministic 64-byte signature (RFC 8032 §5.1.6)."""
    a, prefix = _expand_seed(seed)
    pub = _compress(_pt_mul(a, _G))
    r = int.from_bytes(hashlib.sha512(prefix + message).digest(),
                       "little") % _L
    r_enc = _compress(_pt_mul(r, _G))
    h = int.from_bytes(hashlib.sha512(r_enc + pub + message).digest(),
                       "little") % _L
    s = (r + h * a) % _L
    return r_enc + int.to_bytes(s, 32, "little")


def ed25519_verify(public: bytes, message: bytes, signature: bytes) -> bool:
    """True iff `signature` is a valid signature of `message` by `public`
    (RFC 8032 §5.1.7; cofactorless equation, matching modern verifiers on
    honestly-generated signatures).  Never raises on malformed inputs."""
    if len(public) != 32 or len(signature) != 64:
        return False
    a_pt = _decompress(public)
    r_pt = _decompress(signature[:32])
    if a_pt is None or r_pt is None:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= _L:                         # malleability rejection
        return False
    h = int.from_bytes(hashlib.sha512(signature[:32] + public
                                      + message).digest(), "little") % _L
    return _pt_equal(_pt_mul(s, _G), _pt_add(r_pt, _pt_mul(h, a_pt)))


# ----------------------------------------------------------------- x25519
def _clamp(k: bytes) -> int:
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(bytes(b), "little")


def _x25519_ladder(k: int, u: int) -> int:
    """Montgomery ladder (RFC 7748 §5) — constant structure, variable-time
    bigints (see module docstring for why that is acceptable here)."""
    x1 = u
    x2, z2, x3, z3 = 1, 0, u, 1
    swap = 0
    for t in reversed(range(255)):
        kt = (k >> t) & 1
        if swap ^ kt:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = x1 * z3 * z3 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + 121665 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return x2 * pow(z2, _P - 2, _P) % _P


def x25519_exchange(private: bytes, peer_public: bytes) -> bytes:
    """Shared secret u-coordinate for (our scalar, their public)."""
    if len(private) != 32 or len(peer_public) != 32:
        raise ValueError("x25519 keys must be 32 bytes")
    u = int.from_bytes(peer_public, "little") & ((1 << 255) - 1)
    out = _x25519_ladder(_clamp(private), u)
    if out == 0:                        # small-order peer point
        raise ValueError("x25519: degenerate shared secret")
    return int.to_bytes(out, 32, "little")


def x25519_public(private: bytes) -> bytes:
    """Public u-coordinate for a 32-byte scalar (base point u=9)."""
    if len(private) != 32:
        raise ValueError("x25519 keys must be 32 bytes")
    return int.to_bytes(_x25519_ladder(_clamp(private), 9), 32, "little")
