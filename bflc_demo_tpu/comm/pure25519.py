"""Pure-Python Ed25519 (RFC 8032) + X25519 (RFC 7748) fallback backend.

The asymmetric identity layer (comm.identity) is the reference's trust
model — the coordinator verifies but cannot forge — and round 6's BFT
commit certificates extend it to validator co-signing.  That layer must not
evaporate on hosts where the `cryptography` wheel is absent (this image
bakes in the jax toolchain, not OpenSSL bindings), so this module provides
the same two primitives from first principles over Python integers, the
same way the native ledger carries its own SHA-256 (ledger/src/sha256.cpp)
instead of assuming a crypto runtime.

Compatibility contract (exercised by tests/test_identity.py whenever both
backends are importable): byte-identical public keys, signatures and DH
shared secrets for the same raw private keys — Ed25519 is deterministic
per RFC 8032 and X25519 clamps the scalar the same way, so a wallet
provisioned under one backend verifies under the other.

Performance: a scalar multiplication is ~1 ms of bigint arithmetic — three
orders of magnitude slower than libsodium and, since round 6, squarely on
the control plane's critical path (every BFT commit certificate costs a
sign per validator plus a verify per signature at the writer, the
standbys AND every certificate-checking client).  Three caches close most
of that gap without touching the math:

- a windowed fixed-base table for basepoint scalar mults (`_pt_mul_base`:
  4-bit windows, 64x16 precomputed multiples of G, built lazily once per
  process) — every sign and the s*G half of every verify;
- a per-PUBKEY decompressed-point cache (`_decompress_pub`) so repeated
  verifies under the same key — the normal case: four fixed validator
  keys sign everything — skip the two field exponentiations of RFC 8032
  point decoding (signature R points stay uncached: unique per sig);
- a per-seed expanded-key cache (`_expanded`) so a long-lived wallet does
  not re-derive scalar/prefix/public key on every signature.

All three are transparent: outputs are byte-identical to the naive path
(the randomized cross-check in tests/test_identity.py pins table vs
ladder on random scalars, and the RFC 8032 vectors still pass).  Setting
BFLC_CONTROL_PLANE_LEGACY=1 in the environment before import disables
them — the before/after switch eval.benchmarks.federation_config1 uses.
Variable-time bigints remain acceptable here for the reasons above.
"""

from __future__ import annotations

import hashlib
import os

_P = 2 ** 255 - 19                      # the curve25519 field prime
_L = 2 ** 252 + 27742317777372353535851937790883648493   # group order
_D = (-121665 * pow(121666, _P - 2, _P)) % _P            # edwards d


def _inv(x: int) -> int:
    return pow(x, _P - 2, _P)


# ---------------------------------------------------------------- ed25519
# Points are extended homogeneous coordinates (X, Y, Z, T) with x = X/Z,
# y = Y/Z, x*y = T/Z — the standard complete addition law, so no special
# cases for doubling or the identity.

def _pt_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % _P
    b = (y1 + x1) * (y2 + x2) % _P
    c = 2 * t1 * t2 * _D % _P
    d = 2 * z1 * z2 % _P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)


def _pt_mul(s: int, p):
    q = (0, 1, 1, 0)                    # neutral element
    while s > 0:
        if s & 1:
            q = _pt_add(q, p)
        p = _pt_add(p, p)
        s >>= 1
    return q


def _pt_dbl(p):
    """Dedicated doubling (dbl-2008-hwcd, a=-1): 4 squarings + 4
    multiplications versus the unified law's 9 — doublings dominate every
    variable-base ladder, so this is the cheapest 20% in the file.  Same
    group element as _pt_add(p, p) (cross-checked in tests)."""
    x1, y1, z1, _ = p
    a = x1 * x1 % _P
    b = y1 * y1 % _P
    c = 2 * z1 * z1 % _P
    e = ((x1 + y1) * (x1 + y1) - a - b) % _P
    g = (b - a) % _P                    # a=-1: D + B with D = -A
    f = (g - c) % _P
    h = (-a - b) % _P
    return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)


def _pt_neg(p):
    x, y, z, t = p
    return ((-x) % _P, y, z, (-t) % _P)


def _pt_mul_wnaf(s: int, p):
    """Variable-base scalar mult via width-4 NAF: ~s.bit_length()
    doublings (dedicated formula) + ~bits/5 additions from a 4-entry
    odd-multiples table — the h*A half of every signature verification.
    Same group element as _pt_mul(s, p)."""
    if s <= 0:
        return _pt_mul(s, p)            # 0: neutral (loop never runs)
    p2 = _pt_dbl(p)
    tbl = [p]                           # p, 3p, 5p, 7p
    for _ in range(3):
        tbl.append(_pt_add(tbl[-1], p2))
    digits = []
    while s > 0:
        if s & 1:
            d = s & 15
            if d >= 8:
                d -= 16
            digits.append(d)
            s -= d
        else:
            digits.append(0)
        s >>= 1
    q = (0, 1, 1, 0)
    for d in reversed(digits):
        q = _pt_dbl(q)
        if d > 0:
            q = _pt_add(q, tbl[d >> 1])
        elif d < 0:
            q = _pt_add(q, _pt_neg(tbl[(-d) >> 1]))
    return q


def _pt_equal(p, q) -> bool:
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return ((x1 * z2 - x2 * z1) % _P == 0
            and (y1 * z2 - y2 * z1) % _P == 0)


def _recover_x(y: int, sign: int):
    """x from the curve equation given y and the sign bit; None if y is
    not on the curve (RFC 8032 §5.1.3 decoding)."""
    if y >= _P:
        return None
    x2 = (y * y - 1) * _inv(_D * y * y + 1) % _P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (_P + 3) // 8, _P)
    if (x * x - x2) % _P != 0:
        x = x * pow(2, (_P - 1) // 4, _P) % _P
    if (x * x - x2) % _P != 0:
        return None
    if (x & 1) != sign:
        x = _P - x
    return x


_GY = 4 * _inv(5) % _P
_GX = _recover_x(_GY, 0)
_G = (_GX, _GY, 1, _GX * _GY % _P)      # the base point

# ----------------------------------------------------------- fast path
# See module docstring.  The legacy switch is read once at import: child
# processes of the federation benchmark inherit it through the spawn env;
# in-process tests drive the underlying functions directly instead.
_FAST_DISABLED = bool(os.environ.get("BFLC_CONTROL_PLANE_LEGACY"))

_BASE_TABLE = None                      # built lazily on first basepoint mul


def _build_base_table():
    """table[w][d] = d * 16**w * G for 4-bit windows w in [0, 64): one
    point addition per nonzero scalar digit replaces the ladder's ~255
    doublings + ~127 additions."""
    rows = []
    base = _G
    for _ in range(64):
        row = [(0, 1, 1, 0)]
        for _ in range(15):
            row.append(_pt_add(row[-1], base))
        rows.append(row)
        for _ in range(4):
            base = _pt_add(base, base)
    return rows


def _pt_mul_base(s: int):
    """s * G via the fixed-base window table — the same group element as
    _pt_mul(s, _G), hence byte-identical compressed output (projective
    coordinates differ; _compress normalizes)."""
    global _BASE_TABLE
    if _BASE_TABLE is None:
        _BASE_TABLE = _build_base_table()
    q = (0, 1, 1, 0)
    w = 0
    while s > 0:
        d = s & 15
        if d:
            q = _pt_add(q, _BASE_TABLE[w][d])
        s >>= 4
        w += 1
    return q


def _mul_base(s: int):
    if _FAST_DISABLED:
        return _pt_mul(s, _G)
    return _pt_mul_base(s)


def _pt_multi_mul(pairs):
    """sum(s_i * P_i) with ONE shared doubling chain (Straus): the
    backbone of batch verification — n points cost ~max_bits doublings
    total instead of ~256 each."""
    q = (0, 1, 1, 0)
    top = 0
    for s, _ in pairs:
        top = max(top, s.bit_length())
    for b in range(top - 1, -1, -1):
        q = _pt_dbl(q)
        for s, pt in pairs:
            if (s >> b) & 1:
                q = _pt_add(q, pt)
    return q


def _compress(p) -> bytes:
    x, y, z, _ = p
    zi = _inv(z)
    x, y = x * zi % _P, y * zi % _P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def _decompress(s: bytes):
    if len(s) != 32:
        return None
    y = int.from_bytes(s, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % _P)


def _expand_seed(seed: bytes):
    """RFC 8032 §5.1.5: seed -> (clamped scalar, nonce prefix)."""
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


# seed -> (scalar, prefix, compressed public): a wallet signs many ops
# per round; re-deriving the key material per signature wastes a full
# basepoint mul.  Bounded — a process holds a handful of identities.
_SEED_CACHE: dict = {}
_SEED_CACHE_MAX = 64


def _expanded(seed: bytes):
    e = _SEED_CACHE.get(seed)
    if e is None:
        a, prefix = _expand_seed(seed)
        e = (a, prefix, _compress(_mul_base(a)))
        if not _FAST_DISABLED:
            if len(_SEED_CACHE) >= _SEED_CACHE_MAX:
                _SEED_CACHE.pop(next(iter(_SEED_CACHE)))
            _SEED_CACHE[bytes(seed)] = e
    return e


# pubkey -> decompressed extended point.  Verifier-side mirror of the
# seed cache: decompression costs two field exponentiations, and the
# same few validator/standby/client keys verify everything.
_PUB_CACHE: dict = {}
_PUB_CACHE_MAX = 1024


def _decompress_pub(public: bytes):
    if _FAST_DISABLED:
        return _decompress(public)
    p = _PUB_CACHE.get(public)
    if p is None:
        p = _decompress(public)
        if p is not None:
            if len(_PUB_CACHE) >= _PUB_CACHE_MAX:
                try:
                    _PUB_CACHE.pop(next(iter(_PUB_CACHE)))
                except KeyError:        # racing evictors: already gone
                    pass
            _PUB_CACHE[bytes(public)] = p
    return p


def ed25519_public(seed: bytes) -> bytes:
    """32-byte public key for a 32-byte private seed."""
    if len(seed) != 32:
        raise ValueError("ed25519 seed must be 32 bytes")
    return _expanded(seed)[2]


def ed25519_sign(seed: bytes, message: bytes) -> bytes:
    """Deterministic 64-byte signature (RFC 8032 §5.1.6)."""
    a, prefix, pub = _expanded(seed)
    r = int.from_bytes(hashlib.sha512(prefix + message).digest(),
                       "little") % _L
    r_enc = _compress(_mul_base(r))
    h = int.from_bytes(hashlib.sha512(r_enc + pub + message).digest(),
                       "little") % _L
    s = (r + h * a) % _L
    return r_enc + int.to_bytes(s, 32, "little")


def ed25519_verify(public: bytes, message: bytes, signature: bytes) -> bool:
    """True iff `signature` is a valid signature of `message` by `public`
    (RFC 8032 §5.1.7; cofactorless equation, matching modern verifiers on
    honestly-generated signatures).  Never raises on malformed inputs."""
    if len(public) != 32 or len(signature) != 64:
        return False
    a_pt = _decompress_pub(public)
    r_pt = _decompress(signature[:32])
    if a_pt is None or r_pt is None:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= _L:                         # malleability rejection
        return False
    h = int.from_bytes(hashlib.sha512(signature[:32] + public
                                      + message).digest(), "little") % _L
    if _FAST_DISABLED:
        return _pt_equal(_pt_mul(s, _G), _pt_add(r_pt, _pt_mul(h, a_pt)))
    return _pt_equal(_mul_base(s), _pt_add(r_pt, _pt_mul_wnaf(h, a_pt)))


def ed25519_verify_batch(items) -> bool:
    """Batch verification of (public, message, signature) triples via a
    random linear combination: 8·(sum z_i s_i) G == 8·(sum z_i R_i
    + sum_{pubkeys} (sum z_i h_i) A) — one shared-doubling multiscalar
    mul for the whole batch instead of two ladder muls per signature.

    The equation is COFACTORED (both sides multiplied by 8, RFC 8032
    §8.9 / the standard Ed25519 batch equation), which is what makes the
    result DETERMINISTIC: honest signatures satisfy the per-item
    equation exactly, so any combination holds (no randomness in the
    accept direction); a signature with only a small-torsion defect is
    consistently ACCEPTED (8 annihilates the torsion component on every
    call — never a coin flip that could make one verifier count a quorum
    another rejects); a genuinely forged signature survives with
    probability ~2^-128 over the blinding scalars z_i.

    True therefore means every triple verifies under cofactored
    semantics.  False means at least one failed: callers needing
    attribution fall back to per-item ed25519_verify (cofactorless —
    strictly stricter, so the fallback never accepts what the batch
    refused).  Never raises on malformed input."""
    if not items:
        return True
    rnd = os.urandom(16 * len(items))
    s_acc = 0
    pairs = []
    a_coeff: dict = {}                  # pubkey -> [coeff, point]
    for j, (pub, msg, sig) in enumerate(items):
        if not (isinstance(pub, (bytes, bytearray))
                and isinstance(sig, (bytes, bytearray))
                and len(pub) == 32 and len(sig) == 64):
            return False
        pub, sig = bytes(pub), bytes(sig)
        a_pt = _decompress_pub(pub)
        r_pt = _decompress(sig[:32])
        if a_pt is None or r_pt is None:
            return False
        s = int.from_bytes(sig[32:], "little")
        if s >= _L:                     # malleability rejection
            return False
        h = int.from_bytes(hashlib.sha512(sig[:32] + pub
                                          + bytes(msg)).digest(),
                           "little") % _L
        z = 1 + int.from_bytes(rnd[16 * j:16 * (j + 1)], "little")
        s_acc = (s_acc + z * s) % _L
        pairs.append((z, r_pt))
        entry = a_coeff.get(pub)
        if entry is None:
            a_coeff[pub] = [z * h % _L, a_pt]
        else:
            entry[0] = (entry[0] + z * h) % _L
    pairs.extend((c, pt) for c, pt in a_coeff.values())
    lhs, rhs = _mul_base(s_acc), _pt_multi_mul(pairs)
    for _ in range(3):                  # cofactor 8: three doublings
        lhs, rhs = _pt_dbl(lhs), _pt_dbl(rhs)
    return _pt_equal(lhs, rhs)


# ----------------------------------------------------------------- x25519
def _clamp(k: bytes) -> int:
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(bytes(b), "little")


def _x25519_ladder(k: int, u: int) -> int:
    """Montgomery ladder (RFC 7748 §5) — constant structure, variable-time
    bigints (see module docstring for why that is acceptable here)."""
    x1 = u
    x2, z2, x3, z3 = 1, 0, u, 1
    swap = 0
    for t in reversed(range(255)):
        kt = (k >> t) & 1
        if swap ^ kt:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = x1 * z3 * z3 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + 121665 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return x2 * pow(z2, _P - 2, _P) % _P


def x25519_exchange(private: bytes, peer_public: bytes) -> bytes:
    """Shared secret u-coordinate for (our scalar, their public)."""
    if len(private) != 32 or len(peer_public) != 32:
        raise ValueError("x25519 keys must be 32 bytes")
    u = int.from_bytes(peer_public, "little") & ((1 << 255) - 1)
    out = _x25519_ladder(_clamp(private), u)
    if out == 0:                        # small-order peer point
        raise ValueError("x25519: degenerate shared secret")
    return int.to_bytes(out, 32, "little")


def x25519_public(private: bytes) -> bytes:
    """Public u-coordinate for a 32-byte scalar (base point u=9)."""
    if len(private) != 32:
        raise ValueError("x25519 keys must be 32 bytes")
    return int.to_bytes(_x25519_ladder(_clamp(private), 9), 32, "little")
