"""Mesh-executor coordinator: the socket control plane owning the TPU
data plane.

Round 2 left the two halves of the deployment story unassembled: the
process federation (client/process_runtime.py) pinned every client to CPU
JAX, and the device-resident mesh data plane (client/mesh_runtime.py) ran
only in-process.  This service fuses them — the reference's deployment
shape (OS processes + a chain they talk to over sockets,
python-sdk/main.py:343-358) running the BASELINE north-star data plane
(every round one SPMD program over the accelerator mesh):

- the coordinator process owns the device mesh.  Clients register and
  STAGE their shard once (a signed `stage` request; tensors cross the
  socket a single time), then drive rounds by watching the ledger;
- each round executes via `parallel.make_sharded_protocol_round` — local
  SGD for every staged client, ring committee scoring, the replicated
  decision and the psum FedAvg, all in one dispatch on the mesh — while
  the LEDGER remains the authority exactly as in the mesh runtime: the
  executor replays uploads/scores/commits into it and any divergence
  raises;
- clients fetch the committed model over the socket each epoch and verify
  progress on their own shard; the parent sponsor evaluates held-out
  accuracy (main.py:280-340).

Trust model (explicit, different from the pure process federation): the
executor SEES staged training data — this is the cross-silo "sponsor-owned
accelerator" deployment where silos delegate compute to a TPU pod they
trust with data but not with the protocol (the signed op log still pins
registration/staging identity and every round's decisions).  Silos that do
not trust the executor with raw data keep the CPU-local process federation
or the secure-aggregation mesh path (parallel.secure) instead.

Score attestation (`attest_scores=True`) additionally removes the
centralized-scoring divergence (PARITY.md "Trust-model divergences" #1):
the executor must collect an Ed25519 attestation from every committee
member — who re-scores the round's candidate deltas against its own
shard — before the round reaches the ledger; a fabricated score row gets
no signature and the round aborts.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Dict, Optional

import numpy as np

from bflc_demo_tpu.comm.identity import _op_bytes
from bflc_demo_tpu.comm.ledger_service import LedgerServer
from bflc_demo_tpu.comm.wire import blob_bytes
from bflc_demo_tpu.obs import metrics as obs_metrics
from bflc_demo_tpu.protocol.constants import ProtocolConfig
from bflc_demo_tpu.utils.serialization import pack_pytree, unpack_pytree

# executor telemetry (the `telemetry` scrape RPC itself is inherited
# from LedgerServer): mesh-round progress and per-round dispatch time
_G_MESH_ROUNDS = obs_metrics.REGISTRY.gauge(
    "executor_rounds_done", "mesh protocol rounds executed")
_M_MESH_ROUND = obs_metrics.REGISTRY.histogram(
    "executor_round_seconds",
    "one SPMD protocol round on the mesh (dispatch + audit + publish)")


class MeshExecutorServer(LedgerServer):
    """LedgerServer + staged shards + a mesh round-runner thread.

    Extra protocol method:
        stage {addr, x, y, tag}  — one-time shard staging (x: feature blob,
        y: int label blob, both packed pytrees {"x": ...}/{"y": ...});
        signed with kind="stage" over sha256(x_blob)+sha256(y_blob).

    Data-plane reads (``blob``/``blobs``/``model`` — the attestation
    evidence fetches and every thin client's per-epoch model poll) are
    inherited from LedgerServer and therefore ride the ONE shared
    hash-addressed dispatch (comm.dataplane.handle_read): batched blobs,
    the ``model`` meta probe and client-side caching all work against
    this executor exactly as against the coordinator or a standby read
    replica.

    Once every registered client has staged, the runner thread executes
    `rounds` protocol rounds on the mesh, replaying each into the ledger
    (upload fingerprints, score rows, commit) — the mesh_runtime contract
    behind the socket boundary.
    """

    def __init__(self, cfg: ProtocolConfig, model_factory: str,
                 factory_kw: Optional[dict] = None, *,
                 rounds: int = 5, mesh=None, seed: int = 0,
                 init_seed: int = 0, client_chunk: int = 0,
                 remat: bool = False, attest_scores: bool = False,
                 attest_timeout_s: float = 60.0, **server_kw):
        import bflc_demo_tpu.models as models

        self.model = getattr(models, model_factory)(**(factory_kw or {}))
        initial_params = self.model.init_params(init_seed)
        super().__init__(cfg, pack_pytree(initial_params), **server_kw)
        self.rounds = rounds
        self.seed = seed
        self._mesh = mesh
        self._client_chunk = client_chunk
        self._remat = remat
        self._params = initial_params
        self._staged_x: Dict[str, np.ndarray] = {}
        self._staged_y: Dict[str, np.ndarray] = {}
        self._runner: Optional[threading.Thread] = None
        self.rounds_done = 0
        self.runner_error: Optional[str] = None
        # score attestation (closes the centralized-scoring trust
        # divergence, PARITY.md "Trust-model divergences" #1): before a
        # round's decision reaches the ledger, every committee member's
        # process must fetch the K candidate deltas, RE-SCORE them locally
        # against its own shard, check the device-computed row matches,
        # and sign it (the same Ed25519 scores codec the ledger path
        # verifies).  A coordinator that fabricates a row gets no
        # signature and the round aborts.
        self.attest_scores = attest_scores
        self.attest_timeout_s = attest_timeout_s
        self._pending_attest: Optional[dict] = None
        self._attested: Dict[str, str] = {}      # addr -> sig hex (epoch's)
        self.attest_log: Dict[int, Dict[str, str]] = {}

    # ----------------------------------------------------------- dispatch
    def _dispatch(self, method: str, m: dict) -> dict:
        if method == "stage":
            with self._lock:
                addr = m["addr"]
                xb = blob_bytes(m["x"])
                yb = blob_bytes(m["y"])
                payload = (hashlib.sha256(xb).digest()
                           + hashlib.sha256(yb).digest())
                if self.require_auth and not self.directory.verify(
                        addr, _op_bytes("stage", addr, 0, payload),
                        bytes.fromhex(m.get("tag", ""))):
                    return {"ok": False, "status": "BAD_ARG",
                            "error": "bad signature"}
                try:
                    x = unpack_pytree(xb)["x"]
                    y = unpack_pytree(yb)["y"]
                except (KeyError, ValueError, TypeError) as e:
                    return {"ok": False, "status": "BAD_ARG",
                            "error": f"undecodable shard: {e}"}
                if len(x) == 0 or len(x) != len(y):
                    return {"ok": False, "status": "BAD_ARG",
                            "error": "empty or mismatched shard"}
                self._staged_x[addr] = np.asarray(x)
                self._staged_y[addr] = np.asarray(y)
                self._touch(addr)
                self._maybe_start_runner()
                return {"ok": True, "staged": len(self._staged_x)}
        if method == "progress":
            return {"ok": True, "rounds_done": self.rounds_done,
                    "rounds": self.rounds, "error": self.runner_error}
        if method == "round_pending":
            # a committee member asks whether a round awaits its attestation
            with self._lock:
                p = self._pending_attest
                addr = m.get("addr", "")
                if p is None or addr not in p["rows"] \
                        or addr in self._attested:
                    return {"ok": True, "epoch": None}
                return {"ok": True, "epoch": p["epoch"],
                        "s_pad": p["s_pad"], "hashes": p["hashes"],
                        "row": p["rows"][addr]}
        if method == "attest":
            with self._lock:
                p = self._pending_attest
                addr = m.get("addr", "")
                if p is None or int(m.get("epoch", -1)) != p["epoch"]:
                    return {"ok": False, "status": "WRONG_EPOCH"}
                if addr not in p["rows"]:
                    return {"ok": False, "status": "NOT_COMMITTEE"}
                scores = [float(s) for s in m["scores"]]
                row = p["rows"][addr]
                if len(scores) != len(row) or any(
                        abs(a - b) > 1e-6 for a, b in zip(scores, row)):
                    # the client signed a different row than the device
                    # computed — surfaced, never silently accepted
                    return {"ok": False, "status": "ROW_MISMATCH"}
                import struct as _struct
                payload = _struct.pack(f"<{len(scores)}d", *scores)
                if self.require_auth and not self.directory.verify(
                        addr, _op_bytes("scores", addr, p["epoch"], payload),
                        bytes.fromhex(m.get("tag", ""))):
                    return {"ok": False, "status": "BAD_ARG",
                            "error": "bad signature"}
                self._attested[addr] = m.get("tag", "")
                self._cv.notify_all()
                return {"ok": True,
                        "missing": len(p["rows"]) - len(self._attested)}
        return super()._dispatch(method, m)

    # -------------------------------------------------------- round runner
    def _maybe_start_runner(self) -> None:
        if self._runner is not None:
            return
        # FL starts when all clients registered (epoch leaves the genesis
        # sentinel) AND all have staged; mismatched register/stage identity
        # sets surface as a runner error via `progress`
        if self.ledger.epoch < 0 or len(self._staged_x) < self.cfg.client_num:
            return
        self._runner = threading.Thread(target=self._run_rounds,
                                        daemon=True)
        self._runner.start()

    def _run_rounds(self) -> None:
        try:
            self._run_rounds_inner()
        except Exception as e:      # noqa: BLE001 — surface via `progress`
            self.runner_error = f"{type(e).__name__}: {e}"
            if self.verbose:
                print(f"[executor] runner failed: {self.runner_error}",
                      flush=True)

    def _collect_attestations(self, epoch, addrs, uploader_ids,
                              committee_ids, delta_fps, score_rows,
                              cand_deltas, s_pad) -> None:
        """Publish the round's scoring evidence and block until every
        committee member re-scored and SIGNED its row (or raise).

        Evidence: the K candidate deltas become fetchable blobs keyed by
        their on-device fingerprints (the same ids the ledger will record),
        plus each member's device-computed row.  The member recomputes the
        row from the blobs against its own shard (trust locality — the
        scorer, not the aggregator, vouches for the score) and signs the
        exact scores-op payload.  Missing/refused attestation = the round
        never reaches the ledger.
        """
        import jax

        from bflc_demo_tpu.ops.fingerprint import fingerprint_to_bytes

        cands_host = jax.device_get(cand_deltas)
        hashes = []
        fp_keys = []
        with self._lock:
            for j, uid in enumerate(uploader_ids):
                one = jax.tree_util.tree_map(lambda l: np.asarray(l[j]),
                                             cands_host)
                fp = fingerprint_to_bytes(delta_fps[uid])
                self._blobs[fp] = pack_pytree(one)
                fp_keys.append(fp)
                hashes.append(fp.hex())
            self._pending_attest = {
                "epoch": epoch, "s_pad": int(s_pad), "hashes": hashes,
                "rows": {addrs[c]: [float(score_rows[c, u])
                                    for u in uploader_ids]
                         for c in committee_ids}}
            self._attested = {}
            deadline = time.monotonic() + self.attest_timeout_s
            while len(self._attested) < len(committee_ids):
                rem = deadline - time.monotonic()
                if rem <= 0:
                    missing = [a for a in self._pending_attest["rows"]
                               if a not in self._attested]
                    self._pending_attest = None
                    raise RuntimeError(
                        f"epoch {epoch}: committee members {missing} did "
                        f"not attest their score rows — refusing to commit "
                        f"the round")
                self._cv.wait(rem)
            self.attest_log[epoch] = dict(self._attested)
            self._pending_attest = None
            # the evidence blobs served their purpose (every member
            # re-scored and signed); without this prune a long run grows
            # by K model-sized blobs per round until the coordinator OOMs
            for fp in fp_keys:
                self._blobs.pop(fp, None)

    def _run_rounds_inner(self) -> None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from bflc_demo_tpu.client.staging import (
            audit_round, largest_divisor_device_count, stage_padded_arrays)
        from bflc_demo_tpu.parallel.fedavg import (AXIS,
                                                   make_sharded_protocol_round)
        from bflc_demo_tpu.parallel.mesh import client_axis_mesh

        cfg = self.cfg
        n = cfg.client_num
        with self._lock:
            # ledger registration order fixes the slot order
            addrs = [a for a in self._staged_x]
            addrs.sort(key=lambda a: int(a, 16))
            xs_list = [self._staged_x[a] for a in addrs]
            ys_list = [self._staged_y[a] for a in addrs]
        # same staging rules as the in-process mesh runtime (shared helper:
        # cyclic padding, dtype preservation, empty-shard rejection)
        xs_np, ys_np, sizes = stage_padded_arrays(
            xs_list, ys_list, self.model.num_classes)

        mesh = self._mesh
        if mesh is None:
            mesh = client_axis_mesh(largest_divisor_device_count(n))
        sharding = NamedSharding(mesh, P(AXIS))
        xs = jax.device_put(jnp.asarray(xs_np), sharding)
        ys = jax.device_put(jnp.asarray(ys_np), sharding)
        ns = jax.device_put(jnp.asarray(sizes, jnp.int32), sharding)
        round_fn = make_sharded_protocol_round(
            mesh, self.model.apply, client_num=n, lr=cfg.learning_rate,
            batch_size=cfg.batch_size, local_epochs=cfg.local_epochs,
            aggregate_count=cfg.aggregate_count,
            client_chunk=self._client_chunk, remat=self._remat,
            comm_count=cfg.comm_count,
            needed_update_count=cfg.needed_update_count,
            expose_candidates=self.attest_scores)

        params = self._params
        rng = np.random.default_rng(self.seed)
        k = cfg.needed_update_count
        for _ in range(self.rounds):
            t_round = (time.perf_counter()
                       if obs_metrics.REGISTRY.enabled else 0.0)
            with self._lock:
                epoch = self.ledger.epoch
                committee_ids = sorted(
                    addrs.index(a) for a in self.ledger.committee())
            trainer_ids = [i for i in range(n) if i not in committee_ids]
            pick = rng.permutation(len(trainer_ids))[:k]
            uploader_ids = sorted(trainer_ids[int(j)] for j in pick)
            up_mask = np.zeros(n, bool)
            up_mask[uploader_ids] = True
            cm_mask = np.zeros(n, bool)
            cm_mask[committee_ids] = True
            res = round_fn(params, xs, ys, ns, jnp.asarray(up_mask),
                           jnp.asarray(cm_mask))
            params = res.params
            delta_fps = np.asarray(res.delta_fps)
            score_rows = np.asarray(res.score_matrix)
            avg_costs = np.asarray(res.avg_costs)
            sel_device = np.flatnonzero(np.asarray(res.selected))

            if self.attest_scores:
                self._collect_attestations(epoch, addrs, uploader_ids,
                                           committee_ids, delta_fps,
                                           score_rows, res.cand_deltas,
                                           xs_np.shape[1])

            with self._lock:
                # full participation: client ids ARE the device slots
                audit_round(self.ledger, lambda cid: addrs[cid], epoch,
                            uploader_ids, committee_ids, uploader_ids,
                            committee_ids, delta_fps,
                            lambda cid: sizes[cid], avg_costs, score_rows,
                            sel_device, res.params_fp)
                # publish the committed model for socket clients
                blob = pack_pytree(jax.device_get(params))
                self._model_blob = blob
                self._model_hash = hashlib.sha256(blob).digest()
                self._params = params
                self.rounds_done += 1
                self._rounds_completed += 1
                self._last_progress = time.monotonic()
                self._cv.notify_all()
                if t_round:
                    _G_MESH_ROUNDS.set(self.rounds_done)
                    _M_MESH_ROUND.observe(time.perf_counter() - t_round)
                if self.verbose:
                    print(f"[executor] epoch {epoch} mesh round done "
                          f"(loss={self.ledger.last_global_loss:.5f})",
                          flush=True)
