"""Transport split: control plane vs data plane (SURVEY.md §7 step 3).

The reference's fabric is Channel-TLS RPC + PBFT carrying JSON-in-ABI strings
(SURVEY.md §2c).  Here the planes are separated:

- control plane: small typed messages to the ledger (register / state /
  hashes / scores) — in-process today, socket/DCN later; every mutation is a
  ledger op, so the transport only needs ordered delivery to the log writer.
- data plane: tensor payloads keyed by content hash in an `UpdateStore`
  (HBM/host memory), aggregated on device via the collectives in
  `bflc_demo_tpu.parallel` — tensors never transit the control plane.
"""

from bflc_demo_tpu.comm.store import UpdateStore  # noqa: F401
from bflc_demo_tpu.comm.identity import (  # noqa: F401
    KeyRing, AuthenticatedLedger, sign_register, sign_upload, sign_scores)
