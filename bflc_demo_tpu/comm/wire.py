"""Length-prefixed message framing for the control-plane socket protocol.

The reference's client↔chain transport is the FISCO Channel protocol: TLS
frames carrying ABI-encoded calls with JSON payloads inside
(README.md:240-260; SURVEY.md §2c).  This is the equivalent boundary for the
TPU-native coordinator: a trivially parseable frame format —

    [4-byte big-endian length][UTF-8 JSON object]

— where binary fields (digests, signatures, op bytes, tensor blobs) travel
hex-encoded inside the JSON.  Control messages are tiny (hashes + scores +
meta; tensors cross separately as store blobs), so JSON's overhead is
irrelevant and its debuggability is worth more than a binary codec here.
Integrity/authenticity comes from Ed25519 op tags (comm.identity), not the
transport.

Frames are capped at 256 MiB: a hostile or corrupt length prefix must not
drive an unbounded allocation (same rule as the ledger's op-byte bounds).

Fault injection (bflc_demo_tpu.chaos): every frame send/receive consults a
process-local injector when one is installed — partition windows surface
as connection errors, delay windows as latency, drop windows as lost
frames.  This IS the socket boundary, so chaos exercises exactly the
failure modes real networks produce (a dropped reply, for instance, makes
the client retry an op the server already applied — the
duplicate-delivery path).  Without an installed injector the hot path
pays one None check per frame.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, Optional

MAX_FRAME = 256 << 20

# process-local fault injector (chaos.hooks.FaultInjector) or None.
# Installed once at child-process startup by the chaos campaign; never
# mutated afterwards, so no locking is needed on the read side.
_INJECTOR = None


def set_fault_injector(injector) -> None:
    """Install (or clear, with None) the process-local fault injector
    consulted on every frame.  The injector's on_send/on_recv may sleep
    (delay), raise WireError (partition / dropped frame), or pass."""
    global _INJECTOR
    _INJECTOR = injector


class WireError(ConnectionError):
    """Framing violation or unexpected EOF mid-frame."""


def send_msg(sock: socket.socket, msg: Dict[str, Any]) -> None:
    data = json.dumps(msg, separators=(",", ":")).encode()
    if len(data) > MAX_FRAME:
        raise WireError(f"frame too large: {len(data)}")
    if _INJECTOR is not None:
        _INJECTOR.on_send(sock)
    sock.sendall(struct.pack(">I", len(data)) + data)


def recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes; None on clean EOF at a frame boundary."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            if not buf:
                return None
            raise WireError(f"EOF mid-frame ({len(buf)}/{n} bytes)")
        buf += chunk
    return bytes(buf)


def recv_msg(sock: socket.socket) -> Optional[Dict[str, Any]]:
    """Receive one frame; None on clean EOF (peer closed)."""
    if _INJECTOR is not None:
        _INJECTOR.on_recv(sock)
    header = recv_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME:
        raise WireError(f"frame length {length} exceeds cap")
    body = recv_exact(sock, length)
    if body is None:
        raise WireError("EOF between header and body")
    try:
        msg = json.loads(body.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"undecodable frame: {e}") from e
    if not isinstance(msg, dict):
        raise WireError("frame is not a JSON object")
    return msg
