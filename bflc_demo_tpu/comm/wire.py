"""Length-prefixed message framing for the control-plane socket protocol.

The reference's client↔chain transport is the FISCO Channel protocol: TLS
frames carrying ABI-encoded calls with JSON payloads inside
(README.md:240-260; SURVEY.md §2c).  This is the equivalent boundary for the
TPU-native coordinator: a trivially parseable frame format —

    [4-byte big-endian length][UTF-8 JSON object]

— where binary fields (digests, signatures, op bytes) travel hex-encoded
inside the JSON.  Control messages are tiny (hashes + scores + meta), so
JSON's overhead is irrelevant and its debuggability is worth more than a
binary codec here.  Integrity/authenticity comes from Ed25519 op tags
(comm.identity), not the transport.

Blob-carrying messages (upload payloads, blob mirroring, model fetch) are
the exception (PR 3): hex-doubling a model blob inside a JSON string both
inflates the wire 2x and forces a JSON parse of megabyte strings.  Any
top-level `bytes` value in a message therefore rides a BINARY frame
variant —

    [4-byte length][\\x00BIN1][4-byte header length][JSON header][raw tail]

— where the JSON header is the message minus its bytes-valued fields plus
a `_bin: [[field, length], ...]` manifest, and the raw tail is those
fields' bytes concatenated in manifest order.  Old-format (pure-JSON)
frames remain accepted on every receive path — the first body byte
distinguishes them ('{' vs NUL) — so mixed-version peers interoperate,
and hex-string senders keep working: `blob_bytes` decodes either
representation at the consumption sites.  BFLC_CONTROL_PLANE_LEGACY=1 at
import forces hex-in-JSON sends (the before/after benchmark switch).

Compressed frames (data-plane PR): a frame body — binary OR plain JSON —
whose encoded size crosses a threshold (default 4 KiB,
BFLC_WIRE_COMPRESS_MIN) is sent as

    [4-byte length][\\x00ZIP1][4-byte raw length][deflate(body)]

when compression actually shrinks it (incompressible blob tails ride
uncompressed — negotiation is PER-FRAME, keyed off each frame's leading
magic, so compressed, BIN1 and legacy hex-JSON frames interleave freely
on one socket and mixed-version peers interoperate).  zlib is the
default codec (stdlib everywhere, so any receiver can inflate it); zstd
(magic \\x00ZST1) is accepted whenever the `zstandard` wheel exists but
SENT only with BFLC_WIRE_ZSTD=1 — a fleet opts in once it knows every
receiver holds the wheel.  BFLC_DATA_PLANE_LEGACY=1 (or
the older BFLC_CONTROL_PLANE_LEGACY=1) pins compression off — the
before/after benchmark switch.  The chaos injector fires on send/recv
BEFORE any decoding, so compressed frames are partitioned/dropped/
delayed exactly like every other frame.

Frames are capped at 256 MiB: a hostile or corrupt length prefix must not
drive an unbounded allocation (same rule as the ledger's op-byte bounds).
The binary header length and every manifest entry are validated against
the same cap — a lying manifest is a WireError, never an overread; a
compressed frame's CLAIMED raw length is checked against the cap before
inflation and the inflater is hard-bounded by it, so a deflate bomb costs
at most one capped allocation.

Fault injection (bflc_demo_tpu.chaos): every frame send/receive — JSON
and binary alike — consults a process-local injector when one is
installed; partition windows surface as connection errors, delay windows
as latency, drop windows as lost frames.  This IS the socket boundary, so
chaos exercises exactly the failure modes real networks produce (a
dropped reply, for instance, makes the client retry an op the server
already applied — the duplicate-delivery path).  Without an installed
injector the hot path pays one None check per frame.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import struct
import time
import zlib
from typing import Any, Dict, Optional

try:                                    # optional zstd (not in this image)
    import zstandard as _zstd
except ImportError:                     # pragma: no cover - env dependent
    _zstd = None

# zstd SENDING is opt-in (BFLC_WIRE_ZSTD=1): a receiver without the
# wheel cannot inflate \x00ZST1, so a sender must not pick it just
# because its own host has the module — that would wedge every large
# frame to a zlib-only peer.  Receiving zstd works whenever the wheel
# exists; zlib is the mixed-fleet-safe default (stdlib everywhere).
_SEND_ZSTD = _zstd is not None and bool(os.environ.get("BFLC_WIRE_ZSTD"))

from bflc_demo_tpu.obs import metrics as obs_metrics
from bflc_demo_tpu.obs import trace as obs_trace
from bflc_demo_tpu.utils import tracing

MAX_FRAME = 256 << 20

# frame-mix telemetry (obs.metrics; no-ops unless the process registry
# is enabled): how much of the wire rides the PR-3 binary variant vs
# legacy hex-JSON, per direction, plus raw byte volume.  Latency stays
# on the tracer charges below (wire.send_s / wire.recv_s) — absorbed
# into every telemetry snapshot via trace_costs.
_M_FRAMES = obs_metrics.REGISTRY.counter(
    "wire_frames_total", "frames by direction and encoding",
    ("dir", "kind"))
_M_BYTES = obs_metrics.REGISTRY.counter(
    "wire_bytes_total", "frame bytes (incl. length prefix) by direction",
    ("dir",))
_M_ZBYTES = obs_metrics.REGISTRY.counter(
    "wire_zip_bytes_total",
    "outbound compressed-frame volume: raw (pre-deflate) vs wire "
    "(post-deflate) bytes", ("which",))

# binary-frame sentinel: a JSON object frame's first byte is '{', so a
# NUL-led magic is unambiguous on the same socket
_BIN_MAGIC = b"\x00BIN1"
# compressed-frame sentinels: [magic][4-byte raw len][compressed body]
_ZLIB_MAGIC = b"\x00ZIP1"
_ZSTD_MAGIC = b"\x00ZST1"

# legacy switch (see module docstring): force hex-in-JSON frames
_JSON_ONLY = bool(os.environ.get("BFLC_CONTROL_PLANE_LEGACY"))
# data-plane legacy switch: pin compression off (the egress benchmark's
# before leg); the control-plane switch implies it (that pins the whole
# pre-PR-3 wire, which predates compression too)
_NO_COMPRESS = _JSON_ONLY or bool(os.environ.get("BFLC_DATA_PLANE_LEGACY"))
# only bodies past this size are worth a deflate pass (tiny control
# frames would pay latency for nothing)
_COMPRESS_MIN = int(os.environ.get("BFLC_WIRE_COMPRESS_MIN", 4096))

# process-local fault injector (chaos.hooks.FaultInjector) or None.
# Installed once at child-process startup by the chaos campaign; never
# mutated afterwards, so no locking is needed on the read side.
_INJECTOR = None


def set_fault_injector(injector) -> None:
    """Install (or clear, with None) the process-local fault injector
    consulted on every frame.  The injector's on_send/on_recv may sleep
    (delay), raise WireError (partition / dropped frame), or pass."""
    global _INJECTOR
    _INJECTOR = injector


class WireError(ConnectionError):
    """Framing violation or unexpected EOF mid-frame."""


def blob_bytes(value) -> bytes:
    """Decode a blob-carrying message field: raw bytes from a binary
    frame, or a hex string from a legacy JSON frame (mixed-version
    peers).  Raises ValueError on anything else, like bytes.fromhex."""
    if isinstance(value, (bytes, bytearray)):
        return bytes(value)
    if isinstance(value, str):
        return bytes.fromhex(value)
    raise ValueError(f"blob field is {type(value).__name__}, "
                     f"expected bytes or hex str")


def split_blob_parts(reply: Dict[str, Any]) -> Dict[str, bytes]:
    """Decode a batched content-addressed blob reply
    (``{parts: [[hex_hash, length], ...], blob: <concatenated tail>}`` —
    the coordinator's ``blobs`` method) into {hex_hash: bytes}.

    Every part is verified against its own hash and malformed or lying
    entries are simply omitted — callers treat absence as a miss and
    fall back to per-hash fetches, so a hostile or buggy peer can cause
    extra round-trips, never a crash or a wrong blob."""
    out: Dict[str, bytes] = {}
    try:
        raw = blob_bytes(reply.get("blob", b""))
        off = 0
        for entry in reply.get("parts", []):
            h, n = str(entry[0]), int(entry[1])
            if n < 0 or off + n > len(raw):
                break
            part = raw[off:off + n]
            off += n
            if hashlib.sha256(part).hexdigest() == h:
                out[h] = part
    except (TypeError, ValueError, IndexError, KeyError,
            AttributeError):
        pass
    return out


def _encode(msg: Dict[str, Any]) -> bytes:
    """Message dict -> frame body.  bytes-valued top-level fields select
    the binary variant (unless the legacy switch forces hex-in-JSON)."""
    bin_fields = [(k, v) for k, v in msg.items()
                  if isinstance(v, (bytes, bytearray, memoryview))]
    if not bin_fields:
        return json.dumps(msg, separators=(",", ":")).encode()
    if _JSON_ONLY:
        patched = {k: (bytes(v).hex()
                       if isinstance(v, (bytes, bytearray, memoryview))
                       else v) for k, v in msg.items()}
        return json.dumps(patched, separators=(",", ":")).encode()
    head = {k: v for k, v in msg.items()
            if not isinstance(v, (bytes, bytearray, memoryview))}
    head["_bin"] = [[k, len(v)] for k, v in bin_fields]
    hdata = json.dumps(head, separators=(",", ":")).encode()
    return b"".join([_BIN_MAGIC, struct.pack(">I", len(hdata)), hdata]
                    + [bytes(v) for _, v in bin_fields])


def _decode_binary(body: bytes) -> Dict[str, Any]:
    """Binary frame body -> message dict with bytes-valued blob fields.
    Every length is validated against the actual body: a corrupt or
    hostile manifest is a WireError, never an overread or a giant
    allocation past the frame cap (the body itself is already capped)."""
    off = len(_BIN_MAGIC)
    if len(body) < off + 4:
        raise WireError("truncated binary frame header")
    (hlen,) = struct.unpack_from(">I", body, off)
    off += 4
    if hlen > len(body) - off:
        raise WireError(f"binary frame header length {hlen} overruns "
                        f"frame of {len(body)} bytes")
    try:
        msg = json.loads(body[off:off + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"undecodable binary frame header: {e}") from e
    if not isinstance(msg, dict):
        raise WireError("binary frame header is not a JSON object")
    off += hlen
    manifest = msg.pop("_bin", [])
    if not isinstance(manifest, list):
        raise WireError("binary frame manifest is not a list")
    for entry in manifest:
        try:
            key, n = str(entry[0]), int(entry[1])
        except (TypeError, ValueError, IndexError, KeyError) as e:
            raise WireError(f"malformed binary manifest entry: {e}") from e
        if n < 0 or n > len(body) - off:
            raise WireError(f"binary field {key!r} length {n} overruns "
                            f"frame tail of {len(body) - off} bytes")
        msg[key] = body[off:off + n]
        off += n
    if off != len(body):
        raise WireError(f"{len(body) - off} trailing bytes after the "
                        f"binary frame manifest")
    return msg


def _maybe_compress(data: bytes) -> bytes:
    """Deflate an encoded frame body when it is big enough AND the
    deflate actually wins; otherwise return it unchanged.  Level 1: the
    data plane's fat tails are float tensors — the cheap pass captures
    most of what any level would, without stalling the accept loop."""
    if _NO_COMPRESS or len(data) < _COMPRESS_MIN:
        return data
    if _SEND_ZSTD:
        comp = _zstd.ZstdCompressor(level=3).compress(data)
        magic = _ZSTD_MAGIC
    else:
        comp = zlib.compress(data, 1)
        magic = _ZLIB_MAGIC
    framed = magic + struct.pack(">I", len(data)) + comp
    if len(framed) >= len(data):
        return data                     # incompressible: send raw
    if obs_metrics.REGISTRY.enabled:
        _M_ZBYTES.inc(len(data), which="raw")
        _M_ZBYTES.inc(len(framed), which="wire")
    return framed


def _decompress(body: bytes) -> bytes:
    """Inflate a compressed frame body back to its inner (JSON or BIN1)
    body.  The claimed raw length is validated against the frame cap
    BEFORE inflation and the inflater is bounded by it — a lying or
    hostile frame is a WireError, never an unbounded allocation."""
    magic, zdata = body[:5], body[9:]
    if len(body) < 9:
        raise WireError("truncated compressed frame header")
    (raw_len,) = struct.unpack_from(">I", body, 5)
    if not 0 < raw_len <= MAX_FRAME:
        # raw_len == 0 must die here too: zlib's max_length=0 and zstd's
        # max_output_size=0 both mean UNBOUNDED, which would reopen the
        # deflate-bomb hole this cap exists to close (no honest sender
        # compresses an empty body — the threshold gate is above 0)
        raise WireError(f"compressed frame claims {raw_len} raw bytes, "
                        f"outside (0, cap]")
    try:
        if magic == _ZSTD_MAGIC:
            if _zstd is None:
                raise WireError("zstd frame received but the zstandard "
                                "module is unavailable")
            raw = _zstd.ZstdDecompressor().decompress(
                zdata, max_output_size=raw_len)
        else:
            d = zlib.decompressobj()
            raw = d.decompress(zdata, raw_len)
            if d.unconsumed_tail or not d.eof:
                raise WireError("compressed frame body overruns its "
                                "claimed raw length")
    except (zlib.error, MemoryError) as e:
        raise WireError(f"undecodable compressed frame: {e}") from e
    except Exception as e:              # zstd raises its own error type
        if isinstance(e, WireError):
            raise
        raise WireError(f"undecodable compressed frame: {e}") from e
    if len(raw) != raw_len:
        raise WireError(f"compressed frame inflated to {len(raw)} bytes, "
                        f"claimed {raw_len}")
    return raw


def send_msg(sock: socket.socket, msg: Dict[str, Any]) -> None:
    # causal trace context (obs.trace, Dapper-style): while a sampled
    # span is active on THIS thread, its traceparent rides as a `_tp`
    # header field — plain JSON data, so it survives the BIN1, legacy
    # hex-JSON and compressed variants unchanged and untraced peers
    # ignore the extra key.  Tracing off = one attribute check.
    if obs_trace.TRACE.enabled and "_tp" not in msg:
        _tp = obs_trace.TRACE.current_traceparent()
        if _tp is not None:
            msg = {**msg, "_tp": _tp}
    tr = tracing.PROC
    t0 = time.perf_counter() if tr.enabled else 0.0
    data = _encode(msg)
    if len(data) > MAX_FRAME:
        # cap the RAW encoded size, pre-compression: an oversized body
        # that happens to deflate under the cap would otherwise send
        # fine and then die remotely at the receiver's raw-length check
        # — an opaque disconnect instead of this local, attributable
        # error (compression is win-gated, so a passing raw size can
        # never compress to a failing wire size)
        raise WireError(f"frame too large: {len(data)}")
    data = _maybe_compress(data)
    if _INJECTOR is not None:
        _INJECTOR.on_send(sock)
    sock.sendall(struct.pack(">I", len(data)) + data)
    if tr.enabled:
        tr.charge("wire.send_s", time.perf_counter() - t0)
        tr.charge("wire.bytes_out", 4 + len(data))
    if obs_metrics.REGISTRY.enabled:
        _M_FRAMES.inc(dir="out", kind=_frame_kind(data))
        _M_BYTES.inc(4 + len(data), dir="out")


def _frame_kind(body: bytes) -> str:
    if body[:1] != b"\x00":
        return "json"
    if body[:5] in (_ZLIB_MAGIC, _ZSTD_MAGIC):
        return "zip"
    return "bin"


def recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes; None on clean EOF at a frame boundary."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            if not buf:
                return None
            raise WireError(f"EOF mid-frame ({len(buf)}/{n} bytes)")
        buf += chunk
    return bytes(buf)


def recv_msg(sock: socket.socket) -> Optional[Dict[str, Any]]:
    """Receive one frame; None on clean EOF (peer closed).  Accepts both
    the JSON and the binary variant on the same socket — the peer's
    version never matters to the receiver."""
    if _INJECTOR is not None:
        _INJECTOR.on_recv(sock)
    header = recv_exact(sock, 4)
    if header is None:
        return None
    # timing starts AFTER the length prefix arrived: the wait for a
    # frame's first bytes is the PEER's think time (or idle), not wire
    # cost — charging it would drown the attribution in blocking reads
    tr = tracing.PROC
    t0 = time.perf_counter() if tr.enabled else 0.0
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME:
        raise WireError(f"frame length {length} exceeds cap")
    body = recv_exact(sock, length)
    if body is None:
        raise WireError("EOF between header and body")
    try:
        inner = (_decompress(body)
                 if body[:5] in (_ZLIB_MAGIC, _ZSTD_MAGIC) else body)
        if inner.startswith(_BIN_MAGIC):
            return _decode_binary(inner)
        try:
            msg = json.loads(inner.decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise WireError(f"undecodable frame: {e}") from e
        if not isinstance(msg, dict):
            raise WireError("frame is not a JSON object")
        return msg
    finally:
        if tr.enabled:
            tr.charge("wire.recv_s", time.perf_counter() - t0)
            tr.charge("wire.bytes_in", 4 + len(body))
        if obs_metrics.REGISTRY.enabled:
            _M_FRAMES.inc(dir="in", kind=_frame_kind(body))
            _M_BYTES.inc(4 + len(body), dir="in")
