"""Data-plane fast path: blob cache, replica read fan-out, shared reads.

The round's fat bytes — the global model broadcast and the committee's
candidate-delta fetches — used to move exclusively through the writer's
accept loop: O(N x model size) coordinator egress per round, the opposite
of the ROADMAP's sharding/caching north star and the canonical FL
bottleneck (Konečný et al. 2016; PAPERS.md).  This module takes them off
it, WITHOUT touching the trust model — every byte any party accepts here
is verified against a content hash it already trusts (the certified op's
payload hash, or the writer-served model hash):

- ``handle_read`` is the ONE read-serving dispatch for the
  ``blob``/``blobs``/``model`` wire methods.  The coordinator
  (comm.ledger_service), the mesh executor (comm.executor_service, via
  inheritance) and standby read replicas (below) all serve reads through
  it, so the hash-addressed protocol cannot drift between roles.  The
  ``model`` method gains a ``meta`` flag: epoch + hash + the advertised
  read set, no blob — the cheap "did anything change?" probe.

- ``ReadFanoutServer`` is the standby-side half: a minimal socket server
  over the standby's ALREADY-MIRRORED state (every blob is mirrored
  before the op ack, comm.failover round 7; the model blob is
  hash-checked against the replayed ledger).  Standbys advertise its
  endpoint when they subscribe; the writer republishes the live set in
  ``model`` replies.  Serving reads costs the replica nothing it did not
  already pay for.

- ``BlobCache`` is a content-addressed LRU with a byte budget: a client
  that already holds hash H (the global model across quiescent epochs, a
  delta it produced itself) never re-fetches it.

- ``ReadRouter`` is the client half: fetch the model meta from the
  writer (authoritative hash), then satisfy the bytes from cache ->
  round-robin over the advertised read set -> the coordinator as the
  always-correct fallback.  A replica serving wrong bytes fails the hash
  check and is simply skipped; a replica dying mid-fetch degrades to the
  coordinator (chaos-covered, tests/test_chaos.py) — fan-out can only
  ever cost an extra round-trip, never correctness.

BFLC_DATA_PLANE_LEGACY=1 pins the whole fast path off (no cache, no
fan-out, no meta probe, no wire compression) — the egress benchmark's
before leg (eval.benchmarks.data_plane_config1).
"""

from __future__ import annotations

import collections
import hashlib
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from bflc_demo_tpu.comm.wire import (WireError, blob_bytes, recv_msg,
                                     send_msg, split_blob_parts)
from bflc_demo_tpu.obs import metrics as obs_metrics
from bflc_demo_tpu.obs import trace as obs_trace

Endpoint = Tuple[str, int]

# --- data-plane telemetry (obs.metrics; no-ops unless enabled): where a
# client's reads were satisfied (the read-set share / cache-hit axes the
# egress benchmark and tools/fleet_top.py report)
_M_READS = obs_metrics.REGISTRY.counter(
    "dataplane_reads_total",
    "blob/model reads by where the bytes came from", ("kind", "source"))
_M_CACHE = obs_metrics.REGISTRY.counter(
    "dataplane_cache_events_total",
    "content-addressed blob cache hits/misses", ("event",))
_G_CACHE_BYTES = obs_metrics.REGISTRY.gauge(
    "dataplane_cache_bytes", "bytes currently held by the blob cache")
_M_FALLBACK = obs_metrics.REGISTRY.counter(
    "dataplane_blob_fallback_total",
    "per-hash fallback fetches after a batched blobs reply omitted or "
    "garbled the part")
_M_SERVED = obs_metrics.REGISTRY.counter(
    "readfan_requests_total",
    "reads served by this replica's fan-out server", ("method",))


def data_plane_legacy() -> bool:
    """True when the fast path is pinned off (benchmark before-leg)."""
    return bool(os.environ.get("BFLC_DATA_PLANE_LEGACY"))


class BlobCache:
    """Content-addressed LRU keyed by hex sha256, bounded by bytes.

    Correctness is free: a key IS its value's hash (callers only insert
    verified pairs), so a hit can never serve wrong bytes — the budget
    only trades memory for round-trips.
    """

    def __init__(self, max_bytes: int = 64 << 20):
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._store: "collections.OrderedDict[str, bytes]" = \
            collections.OrderedDict()
        self._bytes = 0

    def get(self, hex_hash: str) -> Optional[bytes]:
        with self._lock:
            blob = self._store.get(hex_hash)
            if blob is not None:
                self._store.move_to_end(hex_hash)
        if obs_metrics.REGISTRY.enabled:
            _M_CACHE.inc(event="hit" if blob is not None else "miss")
        return blob

    def put(self, hex_hash: str, blob: bytes) -> None:
        if len(blob) > self.max_bytes:
            return                      # one oversized blob must not
        with self._lock:                # flush the whole working set
            old = self._store.pop(hex_hash, None)
            if old is not None:
                self._bytes -= len(old)
            self._store[hex_hash] = blob
            self._bytes += len(blob)
            while self._bytes > self.max_bytes:
                _, evicted = self._store.popitem(last=False)
                self._bytes -= len(evicted)
            if obs_metrics.REGISTRY.enabled:
                _G_CACHE_BYTES.set(self._bytes)

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)


# ------------------------------------------------------- shared read serve
def handle_read(method: str, m: dict, *,
                blob_lookup: Callable[[bytes], Optional[bytes]],
                model_state: Callable[[], Optional[Tuple[int, bytes,
                                                         bytes]]],
                read_set: object = (),
                snapshot_state: Optional[Callable[[], Optional[dict]]]
                = None) -> Optional[dict]:
    """Serve one ``blob``/``blobs``/``model`` read; None for any other
    method (the caller falls through to its own dispatch).

    This is the ONE implementation of the hash-addressed read protocol —
    writer, mesh executor and standby replicas all answer through it, so
    a client-side verifier sees identical semantics regardless of which
    role served the bytes.

    ``read_set`` may be a sequence of endpoints or a zero-arg callable
    returning one — only the ``model`` branch evaluates it, so a caller
    sitting on a hot dispatch path (the writer serves EVERY rpc through
    here first) does not pay the lookup for non-model methods.
    """
    if method == "blob":
        digest = bytes.fromhex(m["hash"])
        blob = blob_lookup(digest)
        if blob is None:
            return {"ok": False, "error": "unknown blob"}
        return {"ok": True, "blob": blob}
    if method == "blobs":
        # batched content-addressed fetch (PR 3): held blobs ride the
        # binary tail back-to-back with a [hash, length] manifest;
        # unknown hashes are simply absent (callers fall back per-hash,
        # same contract as "blob").
        parts: List[List] = []
        tail: List[bytes] = []
        for h in list(m.get("hashes", []))[:256]:
            try:
                b = blob_lookup(bytes.fromhex(h))
            except (TypeError, ValueError):
                b = None
            if b is not None:
                parts.append([h, len(b)])
                tail.append(b)
        return {"ok": True, "parts": parts, "blob": b"".join(tail)}
    if method == "model":
        st = model_state()
        if st is None:
            return {"ok": False, "error": "no model blob held"}
        epoch, model_hash, model_blob = st
        want = m.get("want")
        if want and want != model_hash.hex():
            # the caller names the exact model it needs (the hash the
            # writer asserted): answering a DIFFERENT blob would only
            # waste the wire — a stale replica declines in one tiny
            # frame and the router moves on
            return {"ok": False, "status": "STALE",
                    "epoch": epoch, "hash": model_hash.hex()}
        reply: dict = {"ok": True, "epoch": epoch,
                       "hash": model_hash.hex()}
        rs = read_set() if callable(read_set) else read_set
        if rs:
            reply["read_set"] = [list(ep) for ep in rs]
        if not m.get("meta"):
            # bytes value -> binary wire frame: the model blob is the
            # fattest reply on the control plane (comm.wire, PR 3)
            reply["blob"] = model_blob
        return reply
    if method == "snapshot" and snapshot_state is not None:
        # certified-checkpoint state-sync (ledger.snapshot): a replica
        # serves the snapshot it already mirrored, so a joiner's fattest
        # fetch — state bytes + model blob — comes off the writer's
        # accept loop like any other read.  Trust is unchanged: the
        # joiner verifies the WRITER-asserted (op, cert) binding and the
        # state/model hashes before installing, so a stale or lying
        # replica costs a declined/refused round-trip, never wrong state.
        from bflc_demo_tpu.ledger.snapshot import offer_to_wire
        snap = snapshot_state()
        if snap is None:
            return {"ok": False, "error": "no snapshot mirrored"}
        want = m.get("want_i")
        if want is not None and int(want) != int(snap["i"]):
            # the caller names the exact checkpoint it verified against
            # the writer: a replica holding a different one declines in
            # one tiny frame (same shape as the model `want` probe)
            return {"ok": False, "status": "STALE", "i": int(snap["i"])}
        return offer_to_wire(snap)
    return None


class ReadFanoutServer:
    """A replica's read-only serving socket: ``blob``/``blobs``/``model``
    over already-mirrored, hash-verifiable state.

    Deliberately mutation-free: it holds no ledger authority, so a
    Byzantine or stale replica can at worst serve bytes that FAIL the
    client's hash check (a skipped endpoint), never bind state.  Started
    by a Standby at construction and closed at promotion (the promoted
    LedgerServer then serves everything on the real port).
    """

    def __init__(self,
                 blob_lookup: Callable[[bytes], Optional[bytes]],
                 model_state: Callable[[], Optional[Tuple[int, bytes,
                                                          bytes]]],
                 host: str = "127.0.0.1", port: int = 0, tls=None,
                 snapshot_state: Optional[Callable[[], Optional[dict]]]
                 = None):
        self._blob_lookup = blob_lookup
        self._model_state = model_state
        self._snapshot_state = snapshot_state
        self._tls = tls                 # ssl.SSLContext or None
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(32)
        self.host, self.port = self._sock.getsockname()

    @property
    def endpoint(self) -> Endpoint:
        return (self.host, self.port)

    def start(self) -> None:
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        if self._tls is not None:
            import ssl as _ssl
            try:
                conn.settimeout(10.0)   # bound the handshake
                conn = self._tls.wrap_socket(conn, server_side=True)
                conn.settimeout(None)
            except (_ssl.SSLError, OSError):
                try:
                    conn.close()
                except OSError:
                    pass
                return
        try:
            while not self._stop.is_set():
                msg = recv_msg(conn)
                if msg is None:
                    return
                method = msg.get("method", "")
                try:
                    # causal span adopted from the frame's `_tp` — the
                    # replica-side leg of a traced read fan-out fetch
                    with obs_trace.server_span(msg, "replica.read",
                                               method=method):
                        reply = handle_read(
                            method, msg, blob_lookup=self._blob_lookup,
                            model_state=self._model_state,
                            snapshot_state=self._snapshot_state)
                    if reply is None:
                        reply = {"ok": False,
                                 "error": f"read replica: unknown method "
                                          f"{method!r}"}
                    elif obs_metrics.REGISTRY.enabled:
                        _M_SERVED.inc(method=method)
                except Exception as e:      # noqa: BLE001 — an error
                    # frame, never a silently-dropped connection
                    reply = {"ok": False,
                             "error": f"{type(e).__name__}: {e}"}
                send_msg(conn, reply)
        except (WireError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


# ------------------------------------------------------------ client side
class ReadRouter:
    """Client-side read path: cache -> advertised read set -> writer.

    ``control`` is the authoritative request surface (CoordinatorClient
    or FailoverClient): it answers the cheap ``model`` META probe (and
    thereby keeps the read set fresh) and remains the always-correct
    fallback for the bytes themselves.  Replica reads are verified
    against the hash the WRITER asserted, so fan-out moves bytes, never
    trust; a dead, lying or lagging replica costs one extra round-trip.
    """

    def __init__(self, control, cache: Optional[BlobCache] = None,
                 timeout_s: float = 30.0, tls=None):
        self.control = control
        self.cache = cache if cache is not None else BlobCache()
        self.legacy = data_plane_legacy()
        self._timeout_s = timeout_s
        self._tls = tls                 # for dialing TLS read replicas
        self._read_set: List[Endpoint] = []
        self._conns: Dict[Endpoint, object] = {}
        self._rr = os.getpid()          # de-phase the fleet's round-robin

    # -- read-set upkeep ---------------------------------------------------
    def note_read_set(self, reply: dict) -> None:
        rs = reply.get("read_set")
        if not isinstance(rs, list):
            return
        eps: List[Endpoint] = []
        for ep in rs:
            try:
                eps.append((str(ep[0]), int(ep[1])))
            except (TypeError, ValueError, IndexError):
                continue
        if eps != self._read_set:
            for ep in set(self._conns) - set(eps):
                self._drop_conn(ep)
            self._read_set = eps

    def _drop_conn(self, ep: Endpoint) -> None:
        c = self._conns.pop(ep, None)
        if c is not None:
            try:
                c.close()
            except OSError:
                pass

    def _replica_request(self, method: str, **fields) -> Optional[dict]:
        """One read request against the read set, round-robin with
        failover; None when no replica answered usefully.  The rotation
        base is FIXED for the whole sweep (advancing ``_rr`` mid-sweep
        would re-probe the replica that just declined and skip the
        others) and only moves past a replica that actually served."""
        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        n = len(self._read_set)
        base = self._rr
        for k in range(n):
            ep = self._read_set[(base + k) % n]
            try:
                c = self._conns.get(ep)
                if c is None:
                    c = CoordinatorClient(ep[0], ep[1],
                                          timeout_s=self._timeout_s,
                                          tls=self._tls)
                    self._conns[ep] = c
                reply = c.request(method, **fields)
            except (ConnectionError, WireError, OSError):
                self._drop_conn(ep)
                continue
            if reply.get("ok"):
                self._rr = (base + k + 1) % n
                return reply
        return None

    # -- model distribution ------------------------------------------------
    def _take_writer_model(self, r: dict) -> dict:
        """Decode, cache and tag a FULL writer model reply — the one
        shared tail of the cold-start, mixed-version and fallback paths
        (a fix here must not fork across them)."""
        if r.get("ok"):
            self.note_read_set(r)
            blob = blob_bytes(r["blob"])
            self.cache.put(hashlib.sha256(blob).hexdigest(), blob)
            r["blob"] = blob
            r["source"] = "writer"
            _M_READS.inc(kind="model", source="writer")
        return r

    def fetch_model(self) -> dict:
        """The committed global model as ``{ok, epoch, hash, blob}`` with
        ``blob`` always raw bytes and ``source`` recording who actually
        moved them (cache / replica / writer)."""
        with obs_trace.TRACE.span("read.model") as sp:
            r = self._fetch_model()
            if isinstance(r, dict) and r.get("source"):
                sp["source"] = r["source"]
            return r

    def _fetch_model(self) -> dict:
        if self.legacy:
            r = self.control.request("model")
            if r.get("ok"):
                r["blob"] = blob_bytes(r["blob"])
                r["source"] = "writer"
            return r
        if not self._read_set and not len(self.cache):
            # cold start with no known replicas: a meta probe could not
            # save anything — fetch in one round-trip (the full reply
            # still carries the read_set, so fan-out starts right after)
            return self._take_writer_model(self.control.request("model"))
        meta = self.control.request("model", meta=1)
        if not meta.get("ok"):
            return meta
        self.note_read_set(meta)
        want_hex = meta.get("hash", "")
        if "blob" in meta:
            # a pre-fan-out server ignores the meta flag and answers in
            # full — mixed-version compat; take the bytes it already sent
            return self._take_writer_model(meta)
        blob = self.cache.get(want_hex)
        if blob is not None:
            _M_READS.inc(kind="model", source="cache")
            return {**meta, "blob": blob, "source": "cache"}
        if self._read_set:
            # ask replicas for EXACTLY the model the writer asserted
            # (`want`): a stale replica declines in one tiny frame (no
            # wasted blob transfer) and the round-robin tries the next.
            # Right after a commit every replica can be briefly behind
            # (the commit op must certify + stream first), so one short
            # retry bridges that window before the writer fallback.
            for attempt in range(2):
                r = self._replica_request("model", want=want_hex)
                if r is not None:
                    try:
                        blob = blob_bytes(r.get("blob", b""))
                    except ValueError:
                        blob = b""
                    if hashlib.sha256(blob).hexdigest() == want_hex:
                        self.cache.put(want_hex, blob)
                        _M_READS.inc(kind="model", source="replica")
                        return {**meta, "blob": blob,
                                "source": "replica"}
                    break               # lying replica: writer fallback
                if attempt == 0:
                    time.sleep(0.2)
        # fallback: the coordinator itself (always correct; the reply's
        # own epoch/hash supersede the meta — the round may have turned)
        return self._take_writer_model(self.control.request("model"))

    # -- content-addressed blob fetches ------------------------------------
    def fetch_blobs(self, hashes: Sequence[str]) -> Dict[str, bytes]:
        """{hex_hash: verified bytes} for every requested hash: cache ->
        batched replica fetch -> batched writer fetch -> per-hash writer
        fallback (counted per hash: a batched reply that silently omits
        or garbles a part costs visible round-trips, never silence).
        Raises LookupError when a hash cannot be fetched anywhere."""
        with obs_trace.TRACE.span("read.blobs", n=len(hashes)):
            return self._fetch_blobs(hashes)

    def _fetch_blobs(self, hashes: Sequence[str]) -> Dict[str, bytes]:
        out: Dict[str, bytes] = {}
        need: List[str] = []
        for h in hashes:
            b = self.cache.get(h) if not self.legacy else None
            if b is not None:
                out[h] = b
                _M_READS.inc(kind="blob", source="cache")
            elif h not in need:
                need.append(h)
        if need and not self.legacy and self._read_set:
            # up to two replica sweeps: a replica that has mirrored only
            # part of the round's blobs answers with what it holds
            # (absent parts cost nothing) and the round-robin lets the
            # next replica cover the remainder
            for _ in range(min(2, len(self._read_set))):
                r = self._replica_request("blobs", hashes=need)
                if r is None:
                    break
                for h, part in split_blob_parts(r).items():
                    if h in need:
                        out[h] = part
                        self.cache.put(h, part)
                        _M_READS.inc(kind="blob", source="replica")
                need = [h for h in need if h not in out]
                if not need:
                    break
        if need:
            r = self.control.request("blobs", hashes=need)
            if r.get("ok"):
                for h, part in split_blob_parts(r).items():
                    if h in need:
                        out[h] = part
                        if not self.legacy:
                            self.cache.put(h, part)
                        _M_READS.inc(kind="blob", source="writer")
            need = [h for h in need if h not in out]
        for h in need:
            # the batched reply omitted/garbled this part: per-hash
            # fallback, COUNTED (a silent partial batch was the round-9
            # review finding this metric closes)
            _M_FALLBACK.inc()
            r = self.control.request("blob", hash=h)
            if r.get("ok"):
                try:
                    b = blob_bytes(r.get("blob", b""))
                except ValueError:
                    continue
                if hashlib.sha256(b).hexdigest() == h:
                    out[h] = b
                    if not self.legacy:
                        self.cache.put(h, b)
                    _M_READS.inc(kind="blob", source="writer")
        missing = [h for h in hashes if h not in out]
        if missing:
            raise LookupError(
                f"blobs unavailable from every source: "
                f"{[h[:12] for h in missing]}")
        return out

    def close(self) -> None:
        for ep in list(self._conns):
            self._drop_conn(ep)
