"""Minimal pure-Python x509: Ed25519 CA + server cert, no wheel needed.

`comm.tls.provision_tls` historically required the `cryptography` wheel
(the one dependency in the repo with no fallback — ROADMAP open item;
tests/test_tls.py skipped on containers without it).  This module closes
that: just enough DER to emit what `ssl` actually needs to load —

- a self-signed Ed25519 CA certificate (BasicConstraints CA:TRUE,
  critical),
- an Ed25519 server certificate signed by that CA, carrying the
  SubjectAlternativeName entries `client_context` verifies against
  (check_hostname stays ON — IP SANs included),
- the server's PKCS#8 private key (RFC 5958 / RFC 8410 layout: a fixed
  16-byte prefix + the raw 32-byte seed).

Ed25519 everywhere because the repo already HAS Ed25519
(comm.identity.Wallet -> comm.pure25519, RFC 8032): certificate signing
is one `wallet.sign(tbs_der)` — no ASN.1 signature wrapping, no other
curve math.  OpenSSL >= 1.1.1 (this container: 1.1.1w) accepts Ed25519
certificates and negotiates TLS 1.3 with them.

Scope is provisioning only: parsing/validation stays with `ssl` —
exactly the split the cryptography-backed path has.  Validity uses
UTCTime, so not_after is capped at 2049 (two-digit years roll over in
2050; a demo CA has no business outliving that).
"""

from __future__ import annotations

import base64
import datetime
import ipaddress
import os
from typing import Iterable, List, Tuple

from bflc_demo_tpu.comm.identity import Wallet

_OID_ED25519 = bytes([0x2B, 0x65, 0x70])            # 1.3.101.112
_OID_CN = bytes([0x55, 0x04, 0x03])                 # 2.5.4.3
_OID_BASIC_CONSTRAINTS = bytes([0x55, 0x1D, 0x13])  # 2.5.29.19
_OID_SAN = bytes([0x55, 0x1D, 0x11])                # 2.5.29.17

# UTCTime encodes two-digit years (< 2050); RFC 5280 requires rolling to
# GeneralizedTime beyond that — capping is simpler and was the one bug
# the prototype hit ('55' parsed as 1955 -> "certificate has expired")
_UTCTIME_MAX = datetime.datetime(2049, 12, 31, 23, 59, 59,
                                 tzinfo=datetime.timezone.utc)


def _tlv(tag: int, content: bytes) -> bytes:
    n = len(content)
    if n < 0x80:
        return bytes([tag, n]) + content
    ln = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([tag, 0x80 | len(ln)]) + ln + content


def _seq(*parts: bytes) -> bytes:
    return _tlv(0x30, b"".join(parts))


def _set(*parts: bytes) -> bytes:
    return _tlv(0x31, b"".join(parts))


def _int(v: int) -> bytes:
    raw = v.to_bytes(max(1, (v.bit_length() + 7) // 8), "big")
    if raw[0] & 0x80:
        raw = b"\x00" + raw             # positive INTEGERs stay positive
    return _tlv(0x02, raw)


def _oid(der_body: bytes) -> bytes:
    return _tlv(0x06, der_body)


def _octets(b: bytes) -> bytes:
    return _tlv(0x04, b)


def _bitstring(b: bytes) -> bytes:
    return _tlv(0x03, b"\x00" + b)      # zero unused bits


def _bool_true() -> bytes:
    return _tlv(0x01, b"\xff")


def _utf8(s: str) -> bytes:
    return _tlv(0x0C, s.encode())


def _utctime(dt: datetime.datetime) -> bytes:
    return _tlv(0x17, dt.strftime("%y%m%d%H%M%SZ").encode())


def _explicit(n: int, content: bytes) -> bytes:
    return _tlv(0xA0 | n, content)      # [n] EXPLICIT, constructed


def _name(common_name: str) -> bytes:
    return _seq(_set(_seq(_oid(_OID_CN), _utf8(common_name))))


def _algo_ed25519() -> bytes:
    return _seq(_oid(_OID_ED25519))     # RFC 8410: parameters ABSENT


def _spki(public_bytes: bytes) -> bytes:
    return _seq(_algo_ed25519(), _bitstring(public_bytes))


def _extension(oid: bytes, critical: bool, inner_der: bytes) -> bytes:
    parts = [_oid(oid)]
    if critical:
        parts.append(_bool_true())
    parts.append(_octets(inner_der))
    return _seq(*parts)


def _san_extension(names: Iterable[str]) -> bytes:
    """SubjectAlternativeName: dNSName [2] IA5String (implicit,
    primitive) / iPAddress [7] OCTET STRING — the GeneralName choices
    `ssl`'s check_hostname matches against."""
    general: List[bytes] = []
    for n in names:
        try:
            ip = ipaddress.ip_address(n)
            general.append(_tlv(0x87, ip.packed))
        except ValueError:
            general.append(_tlv(0x82, n.encode()))
    return _extension(_OID_SAN, False, _seq(*general))


def _basic_constraints_ca() -> bytes:
    # CA:TRUE, pathLenConstraint 0 — same shape the cryptography-backed
    # provisioner emits
    return _extension(_OID_BASIC_CONSTRAINTS, True,
                      _seq(_bool_true(), _int(0)))


def _certificate(*, subject_cn: str, issuer_cn: str,
                 subject_pub: bytes, issuer_wallet: Wallet,
                 serial: int, days: int,
                 extensions: List[bytes]) -> bytes:
    now = datetime.datetime.now(datetime.timezone.utc)
    not_before = now - datetime.timedelta(minutes=5)
    not_after = min(now + datetime.timedelta(days=days), _UTCTIME_MAX)
    tbs = _seq(
        _explicit(0, _int(2)),          # version v3
        _int(serial),
        _algo_ed25519(),
        _name(issuer_cn),
        _seq(_utctime(not_before), _utctime(not_after)),
        _name(subject_cn),
        _spki(subject_pub),
        _explicit(3, _seq(*extensions)))
    sig = issuer_wallet.sign(tbs)       # Ed25519 signs the DER directly
    return _seq(tbs, _algo_ed25519(), _bitstring(sig))


def _pkcs8_ed25519(sign_private: bytes) -> bytes:
    # RFC 5958 OneAsymmetricKey with RFC 8410 CurvePrivateKey: the inner
    # OCTET STRING wraps the raw 32-byte seed
    return _seq(_int(0), _algo_ed25519(),
                _octets(_octets(sign_private)))


def _pem(label: str, der: bytes) -> bytes:
    b64 = base64.b64encode(der)
    lines = [b64[i:i + 64] for i in range(0, len(b64), 64)]
    return (f"-----BEGIN {label}-----\n".encode()
            + b"\n".join(lines)
            + f"\n-----END {label}-----\n".encode())


def provision_tls_pure(cert_dir: str, common_name: str = "127.0.0.1",
                       days: int = 365,
                       include_loopback: bool = True,
                       ) -> Tuple[str, str, str]:
    """Pure-Python drop-in for `comm.tls.provision_tls`'s generation
    step: writes ca.pem / server.pem / server.key under cert_dir and
    returns the three paths.  Same SAN policy as the cryptography-backed
    path (the deployment's common name, plus localhost/127.0.0.1 unless
    include_loopback=False), same 0600 key permissions."""
    os.makedirs(cert_dir, exist_ok=True)
    ca_path = os.path.join(cert_dir, "ca.pem")
    crt_path = os.path.join(cert_dir, "server.pem")
    key_path = os.path.join(cert_dir, "server.key")

    ca_wallet = Wallet.generate()
    srv_wallet = Wallet.generate()
    ca_cert = _certificate(
        subject_cn="bflc-demo-tpu-ca", issuer_cn="bflc-demo-tpu-ca",
        subject_pub=ca_wallet.public_bytes, issuer_wallet=ca_wallet,
        serial=int.from_bytes(os.urandom(16), "big") >> 1, days=days,
        extensions=[_basic_constraints_ca()])
    sans = []
    if include_loopback:
        sans.append("localhost")
    sans.append(common_name)
    if include_loopback and common_name != "127.0.0.1":
        sans.append("127.0.0.1")
    srv_cert = _certificate(
        subject_cn=common_name, issuer_cn="bflc-demo-tpu-ca",
        subject_pub=srv_wallet.public_bytes, issuer_wallet=ca_wallet,
        serial=int.from_bytes(os.urandom(16), "big") >> 1, days=days,
        extensions=[_san_extension(sans)])

    with open(ca_path, "wb") as f:
        f.write(_pem("CERTIFICATE", ca_cert))
    with open(crt_path, "wb") as f:
        f.write(_pem("CERTIFICATE", srv_cert))
    # 0600: the unencrypted server key must not be world-readable
    fd = os.open(key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(_pem("PRIVATE KEY",
                     _pkcs8_ed25519(srv_wallet._sign_sk)))
    return ca_path, crt_path, key_path
