"""Multi-host bring-up: ICI data plane + DCN control plane.

The reference scales by running one FISCO node per machine with PBFT over
P2P (README.md:162-183) and clients dialing any node over Channel TLS.  The
TPU-native equivalent (BASELINE.json north star: "one FISCO node per TPU VM
on a pod slice"):

- the DATA plane needs no bespoke backend: `jax.distributed.initialize` +
  a global mesh makes every collective in this package (psum FedAvg, ring
  scoring, ring attention, tp/ep/pp shardings) run over ICI within a slice
  and DCN across slices — XLA routes them, exactly as on the virtual CPU
  mesh used in tests;
- the CONTROL plane is the ledger: one host (process_index 0 by convention)
  owns the writer; other hosts replicate by replaying the op stream
  (`ledger.apply_op`) and verify with the chained head digest — the same
  replication contract the tests exercise in-process.

Single real multi-host runs cannot execute in this environment (one chip);
`initialize()` is a thin, testable wrapper that no-ops gracefully on a
single process so the same entry point works everywhere.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Initialise jax.distributed from args or BFLC_COORDINATOR /
    BFLC_NUM_PROCESSES / BFLC_PROCESS_ID env vars.  Returns True if a
    multi-process runtime was initialised, False for single-process."""
    coordinator_address = coordinator_address or os.environ.get(
        "BFLC_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("BFLC_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("BFLC_PROCESS_ID", "0"))
    if not coordinator_address or num_processes <= 1:
        return False
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def is_ledger_writer() -> bool:
    """The op-log writer host (the control-plane serialization point)."""
    return jax.process_index() == 0


def global_mesh(axis_names=("clients",), shape=None):
    """Mesh over every device across all hosts (ICI within a slice, DCN
    between slices — XLA picks the fabric per collective)."""
    import numpy as np
    from jax.sharding import Mesh
    devs = jax.devices()
    if shape is None:
        shape = (len(devs),)
    return Mesh(np.asarray(devs[: int(np.prod(shape))]).reshape(shape),
                axis_names)
