"""The networked coordinator: the ledger behind a real socket boundary.

This is the process that plays the reference's blockchain node: it owns the
authoritative ledger state machine, verifies client signatures, stores
update payloads, runs the aggregation when a round completes, and streams
the replicated op log to live replicas — the roles FISCO-BCOS gave the
reference's contract via PBFT + Channel TLS (SURVEY.md §1 L0-L2;
CommitteePrecompiled.cpp:349-456 for on-chain aggregation).  Every client
interaction crosses a length-prefixed socket frame (comm.wire): no caller
shares memory with the coordinator.

Trust model: client mutations carry Ed25519 tags verified against a
public-key directory (comm.identity) — the server can verify but not forge.
Registration is trust-on-first-use by default (the address must match the
presented public key) or closed-enrollment when a pre-provisioned directory
is passed.  Coordinator-side ops (aggregate/commit, recovery) are the
writer's own authority, exactly like the in-process runtimes.

Replication: replicas connect and `subscribe`; the server pushes canonical
op bytes (the same bytes `ledger.log_op` serves and the WAL stores), and the
replica's replayed head digest must equal the writer's at every index — the
multi-node consistency check the reference evidenced with identical loss
lines in all four node logs (imgs/runtime.jpg).

Failure detection: a monitor thread watches round progress; on a stall it
drives the ledger's recovery ops (close_round → reseat_committee with
recently-seen clients → force_aggregate), each an op in the replicated log.
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from bflc_demo_tpu.comm.dataplane import data_plane_legacy, handle_read
from bflc_demo_tpu.comm.identity import (PublicDirectory, ReplayGuard,
                                         address_of, _op_bytes)
from bflc_demo_tpu.comm.wire import (blob_bytes, send_msg, recv_msg,
                                     WireError)
from bflc_demo_tpu.obs import device as obs_device
from bflc_demo_tpu.obs import flight as obs_flight
from bflc_demo_tpu.obs import health as obs_health
from bflc_demo_tpu.obs import metrics as obs_metrics
from bflc_demo_tpu.obs import trace as obs_trace
from bflc_demo_tpu.utils import tracing
from bflc_demo_tpu.ledger import (async_enabled, make_ledger,
                                  LedgerStatus)
from bflc_demo_tpu.protocol.constants import ProtocolConfig
from bflc_demo_tpu.utils.serialization import (densify_entries,
                                               dequantize_entries,
                                               pack_entries, sparse_enabled,
                                               unpack_pytree)


# --- admission-control gas (reference: CommitteePrecompiled.cpp:143,151,
# 468-469 meters every storage op — a DoS bound on the node).  Storage ops
# (register/upload/scores) charge a per-sender, per-epoch budget at the
# socket boundary, AFTER signature verification (gas binds to a proven
# identity, not a claimed address) and BEFORE any state mutation; queries
# are free.  Uploads charge per payload byte so a client cannot stream
# unbounded blob traffic inside one epoch's allowance.
GAS_REGISTER = 1_000
GAS_UPLOAD_BASE = 1_000
GAS_SCORES = 500

# --- writer-side telemetry (obs.metrics; no-ops unless the registry is
# enabled).  Instantaneous state (round, uncertified backlog) is set at
# scrape time inside the `telemetry` dispatch — a gauge sampled when it
# is read is always current; the latency/size distributions accumulate
# where the work happens.
_M_RPC = obs_metrics.REGISTRY.histogram(
    "rpc_latency_seconds",
    "server-side request handling time (dispatch + certification + "
    "quorum wait) per wire method", ("method",))
_M_CERTIFY = obs_metrics.REGISTRY.histogram(
    "certify_latency_seconds",
    "one certification round-trip to the validator quorum", ("mode",))
_M_CERT_BATCH = obs_metrics.REGISTRY.histogram(
    "cert_batch_size", "ops certified per certify_range round-trip",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, float("inf")))
_G_ROUND = obs_metrics.REGISTRY.gauge(
    "round", "current ledger epoch (completed FL rounds)")
_G_BACKLOG = obs_metrics.REGISTRY.gauge(
    "uncertified_backlog", "chain ops not yet quorum-certified")
_G_SUBS = obs_metrics.REGISTRY.gauge(
    "op_stream_subscribers", "live op-stream subscribers")
# --- certified snapshots (ledger.snapshot): age of the newest certified
# checkpoint, its byte weight, the GC'd-prefix depth, and how many log
# ops GC reclaimed — the bounded-growth evidence tools/fleet_top.py and
# the endurance run read.
_G_SNAP_AGE = obs_metrics.REGISTRY.gauge(
    "snapshot_age_rounds",
    "epochs since the newest certified snapshot (-1 = none yet)")
_G_SNAP_BYTES = obs_metrics.REGISTRY.gauge(
    "snapshot_bytes",
    "artifact size of the newest certified snapshot (state + model)")
_G_LOG_BASE = obs_metrics.REGISTRY.gauge(
    "log_base", "first chain position still held (GC'd prefix depth)")
_M_GC_OPS = obs_metrics.REGISTRY.counter(
    "ledger_gc_ops_total", "log ops reclaimed by snapshot GC")
# --- straggler evidence (the async-aggregation item's baseline): how
# far behind the round's FIRST admitted upload each later upload lands,
# writer-side.  Heavy-tailed client delay shows up as a fat tail here;
# tools/trace_report.py cross-checks the per-client ranking off the
# causal traces against this aggregate distribution.
_M_UPLOAD_LAG = obs_metrics.REGISTRY.histogram(
    "upload_lag_seconds",
    "per-round client upload admission lag behind the round's first "
    "admitted upload")
# --- async buffered aggregation (--async-buffer K; FedBuff): buffer
# occupancy sampled at scrape time, the staleness distribution of every
# admitted delta (epochs behind the current model at admission), and the
# aggregation counter whose timeline slope IS aggregations/sec —
# rendered by tools/fleet_top.py and tools/profile_round.py.
_G_ABUF_DEPTH = obs_metrics.REGISTRY.gauge(
    "async_buffer_depth",
    "staleness-tagged deltas currently buffered (async mode)")
_M_ASTALENESS = obs_metrics.REGISTRY.histogram(
    "async_admitted_staleness",
    "staleness (epochs) of each admitted async delta",
    buckets=(0, 1, 2, 3, 5, 8, 13, 21, float("inf")))
_M_AAGG = obs_metrics.REGISTRY.counter(
    "async_aggregations_total",
    "buffered aggregations committed (async mode)")
_M_RESEAT = obs_metrics.REGISTRY.counter(
    "committee_reseats_total",
    "async committee re-elections applied "
    "(ProtocolConfig.async_reseat_every)")
_G_COMM_SIZE = obs_metrics.REGISTRY.gauge(
    "committee_size", "currently seated committee members")
# --- sparse upload deltas (--delta-density; utils.serialization): the
# protocol density this writer admits (1.0 = dense) and the writer-side
# decode cost of the densify inverse at admission — the operator's
# evidence that sparse decode stays off the round critical path
# (tools/fleet_top.py renders both; clients time the encode half).
_G_DENSITY = obs_metrics.REGISTRY.gauge(
    "delta_density",
    "protocol upload-delta density this writer admits (1.0 = dense)")
_M_SPARSE_DECODE = obs_metrics.REGISTRY.histogram(
    "sparse_decode_seconds",
    "writer-side sparse delta decode (dequantize + densify) per "
    "admitted blob")
# --- closed-loop compression (--adapt-every; ledger.OP_GENOME): the
# LIVE effective knobs the certified genome schedule currently pins
# (delta_density above already tracks the effective density), the epoch
# of the last applied genome-update op, and how many the chain carries
# — tools/fleet_top.py renders the writer-row adaptive panel off these.
_G_EFF_STALENESS = obs_metrics.REGISTRY.gauge(
    "effective_staleness",
    "effective FedBuff max-staleness bound (certified genome schedule)")
_G_GENOME_EPOCH = obs_metrics.REGISTRY.gauge(
    "genome_epoch",
    "epoch of the last applied genome-update op (-1: none yet)")
_M_GENOME = obs_metrics.REGISTRY.counter(
    "genome_updates_total",
    "certified genome-update ops applied (closed-loop compression)")

_PROMO_MAGIC = b"BFLCPROM1"


def chain_head_at(ledger, upto: int) -> bytes:
    """Digest of the op hash chain after ops[0..upto-1] (b"" at upto=0).

    Served by the ledger's own `head_at` (both backends; the python
    backend additionally answers below a GC'd prefix only at the exact
    base — heads below it are gone with the compacted ops, and callers
    that ask get the ValueError).  The chain-rule fold over `log_op`
    remains as the fallback for ledger-likes without `head_at`.
    """
    head_at = getattr(ledger, "head_at", None)
    if head_at is not None:
        return head_at(upto)
    h = b""
    for i in range(upto):
        d = hashlib.sha256()
        if h:
            d.update(h)
        d.update(ledger.log_op(i))
        h = d.digest()
    return h


def _promotion_evidence_bytes(gen: int, ix: int, prev_head: bytes,
                              standby_index: int) -> bytes:
    return (_PROMO_MAGIC + struct.pack("<qqI", gen, ix, standby_index)
            + prev_head)


def make_promotion_evidence(ledger, wallet, standby_index: int) -> dict:
    """Signed, chain-bound proof of a promotion this standby just fenced.

    Call AFTER `promote_writer` appended its op (the op sits at position
    log_size-1).  The evidence binds (generation, op position, the chain
    head digest immediately BEFORE the promote op, the standby's identity)
    under the standby's Ed25519 signature.  Any party holding the standby's
    public key and the shared chain prefix can verify it
    (`verify_promotion_evidence`) — in particular the pre-partition writer,
    whose own ops[0..ix-1] are byte-identical to the promoted chain's
    prefix (the standby replayed them from that very writer).
    """
    ix = ledger.log_size() - 1
    prev = chain_head_at(ledger, ix)
    gen = ledger.generation
    sig = wallet.sign(_promotion_evidence_bytes(gen, ix, prev,
                                                standby_index))
    return {"gen": gen, "ix": ix, "prev": prev.hex(),
            "sb": standby_index, "sig": sig.hex()}


def verify_promotion_signature(ev, standby_keys) -> bool:
    """Signature-only check of promotion evidence — what a CLIENT can
    verify without holding the chain.  True iff the evidence parses and
    its Ed25519 signature is by the provisioned standby it names.  (The
    chain-prefix binding is the WRITER's additional check,
    `verify_promotion_evidence`.)"""
    try:
        gen, ix, sb = int(ev["gen"]), int(ev["ix"]), int(ev["sb"])
        prev = bytes.fromhex(ev["prev"])
        sig = bytes.fromhex(ev["sig"])
    except (KeyError, TypeError, ValueError):
        return False
    pub = (standby_keys or {}).get(sb)
    if pub is None:
        return False
    from bflc_demo_tpu.comm.identity import verify_signature
    return verify_signature(pub, _promotion_evidence_bytes(gen, ix, prev,
                                                           sb), sig)


def verify_promotion_evidence(ev, ledger, standby_keys) -> bool:
    """True iff `ev` proves a promotion PAST `ledger`'s generation on a
    chain sharing this ledger's prefix, signed by a provisioned standby.

    The three checks together close the round-4 advisor DoS (a bare
    client-supplied fence integer could demote any writer):
    - signature: only a holder of a provisioned standby key can produce it;
    - generation: stale/duplicate evidence (gen <= ours) proves nothing;
    - chain binding: prev_head must equal OUR head at the claimed position,
      so evidence from a different deployment (or a fabricated chain)
      cannot fence this writer.
    """
    if not verify_promotion_signature(ev, standby_keys):
        return False
    gen, ix = int(ev["gen"]), int(ev["ix"])
    if gen <= ledger.generation or not 0 <= ix <= ledger.log_size():
        return False
    try:
        return chain_head_at(ledger, ix) == bytes.fromhex(ev["prev"])
    except ValueError:
        # the claimed position sits below OUR GC'd snapshot base: the
        # heads there are gone, so the chain binding cannot be proven —
        # unverifiable evidence never demotes a writer
        return False


def _aggregate_flat(global_flat: Dict[str, np.ndarray],
                    delta_flats: List[Dict[str, np.ndarray]],
                    weights: List[float], selected: List[int],
                    lr: float, blocks: int = 1) -> Dict[str, np.ndarray]:
    """Server-side FedAvg on flat entries: global -= lr * weighted mean of
    the selected deltas (CommitteePrecompiled.cpp:403-414 semantics, the
    same arithmetic `core.aggregate.apply_selection` implements on device).

    `weights` is the per-delta merge weight: n_samples on the sync path,
    n_samples * 1/sqrt(1+staleness) on the async buffered path
    (ledger.base.staleness_weight) — one arithmetic, two weightings.

    The reduction runs through the batched meshagg engine under
    REDUCTION SPEC v1 (meshagg.spec): at round geometry the N admitted
    deltas stack into one pytree and reduce in a single jitted program;
    small batches and `BFLC_MESH_AGG_LEGACY=1` keep the pre-engine host
    loop.  The legs are byte-identical by construction (fixed-order
    float32 accumulation, differential-tested), so the certified model
    hash never depends on which leg ran.  `blocks` is the genome's
    reduce_blocks (REDUCTION SPEC v2) — an execution-shape knob, also
    byte-invariant."""
    from bflc_demo_tpu.meshagg.engine import ENGINE
    return ENGINE.aggregate_flat(global_flat, delta_flats, weights,
                                 selected, lr, blocks=blocks)


class LedgerServer:
    """Coordinator process body: socket server + aggregator + stall monitor.

    Run via `serve_forever()` (blocking; typical use inside a dedicated
    OS process — client/process_runtime.py spawns it) or `start()` for an
    in-thread server in tests.
    """

    def __init__(self, cfg: ProtocolConfig, initial_model_blob: bytes,
                 host: str = "127.0.0.1", port: int = 0, *,
                 directory: Optional[PublicDirectory] = None,
                 ledger_backend: str = "auto",
                 wal_path: str = "",
                 require_auth: bool = True,
                 stall_timeout_s: float = 10.0,
                 resume_ledger=None,
                 resume_blobs: Optional[Dict[bytes, bytes]] = None,
                 sock: Optional[socket.socket] = None,
                 tls=None,
                 standby_keys: Optional[Dict[int, bytes]] = None,
                 promotion_evidence: Optional[dict] = None,
                 gas_budget_per_epoch: Optional[int] = None,
                 quorum: int = 0,
                 quorum_timeout_s: float = 5.0,
                 bft_validators: Optional[List[Tuple[str, int]]] = None,
                 bft_keys: Optional[Dict[int, bytes]] = None,
                 bft_quorum: Optional[int] = None,
                 bft_timeout_s: float = 10.0,
                 resume_certs: Optional[Dict[int, dict]] = None,
                 cell_registry: Optional[Dict[str, Tuple[int, int]]] = None,
                 snapshot_interval: int = 0,
                 snapshot_dir: str = "",
                 snapshot_keep: int = 2,
                 resume_snapshot: Optional[dict] = None,
                 verbose: bool = False):
        """resume_ledger/resume_blobs/sock: the promotion surface
        (comm.failover.Standby) — a server constructed over a replica's
        replayed ledger, its mirrored blob store, the CURRENT model blob as
        `initial_model_blob`, and an already-listening socket whose backlog
        holds the failed-over clients.  `open_enrollment` stays available
        (a reconnecting client re-presents its pubkey; addresses are
        self-authenticating)."""
        cfg.validate()
        self.cfg = cfg
        self.verbose = verbose
        self.require_auth = require_auth
        self.stall_timeout_s = stall_timeout_s
        # ssl.SSLContext (comm.tls.server_context) or None for plaintext;
        # the handshake happens in the per-connection thread so a stalled
        # or plaintext peer never blocks the accept loop
        self._tls = tls
        self._open_enrollment = directory is None
        self.directory = directory if directory is not None \
            else PublicDirectory()

        # one lock serializes ledger + blob + model state — the consensus
        # point (the reference leaned on PBFT ordering here); subscribers
        # wait on the condition for new log entries
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        # --- certified snapshots + ledger compaction (ledger.snapshot):
        # every `snapshot_interval` rounds the writer appends a snapshot
        # op (state digest re-derived by every replica/validator before
        # it binds), persists the artifact tmp-then-rename under
        # snapshot_dir (newest `snapshot_keep` retained), and GCs the
        # log/WAL prefix behind it.  0 (the default) or
        # BFLC_SNAPSHOT_LEGACY=1 pins the replay-from-genesis behavior
        # byte-for-byte: no snapshot op ever enters the chain.
        from bflc_demo_tpu.ledger.snapshot import snapshot_legacy
        self._snap_interval = (0 if snapshot_legacy()
                               else max(int(snapshot_interval), 0))
        self._snap_dir = snapshot_dir
        self._snap_keep = max(int(snapshot_keep), 1)
        if self._snap_interval and resume_ledger is None:
            # compaction needs the python backend (the native ledger has
            # no state-injection/GC ABI — it still APPLIES snapshot ops,
            # so native replicas and validators stay chain-compatible)
            if ledger_backend == "native":
                raise ValueError(
                    "snapshot_interval > 0 needs the python ledger "
                    "backend (the native ledger cannot compact its log)")
            ledger_backend = "python"
        self.ledger = (resume_ledger if resume_ledger is not None
                       else make_ledger(cfg, backend=ledger_backend))
        # newest snapshot meta: {"i", "epoch", "gen", "op", "prev_head",
        # "cert", "state", "model", "final"} — the `snapshot` RPC's
        # serving surface.  A promoted standby passes the one it
        # mirrored (resume_snapshot) so joiners can state-sync from the
        # new writer immediately.
        self._latest_snapshot: Optional[dict] = (
            dict(resume_snapshot) if resume_snapshot else None)
        # last FINALIZED (certified) snapshot meta: stays servable while
        # the next emission is mid-certification — a joiner arriving in
        # that window must still get an offer for the GC'd prefix
        self._served_snapshot: Optional[dict] = None
        if wal_path:
            if not self.ledger.attach_wal(wal_path):
                raise RuntimeError(f"cannot attach WAL at {wal_path}")
        self._blobs: Dict[bytes, bytes] = dict(resume_blobs or {})
        # meshagg staging (best-effort): payload hash -> the delta's
        # flattened f32 row (meshagg.engine.flatten_delta), built at
        # admission — where the blob is decoded for the schema check
        # anyway — so the mesh-leg aggregate is one stack + one compiled
        # program with no per-leaf Python on the commit critical path.
        # A missing row (promoted-standby resupply, resumed writer) is
        # re-derived from the blob at aggregate time.
        self._staged: Dict[bytes, np.ndarray] = {}
        # model-quality health plane (obs.health): built lazily at the
        # first committed round with the plane armed (telemetry on, no
        # BFLC_HEALTH_LEGACY pin) — observability only, the certified
        # bytes never depend on it
        self._health = None
        self._model_blob = initial_model_blob
        self._model_hash = hashlib.sha256(initial_model_blob).digest()
        # {key: (shape, dtype)} of the current model — the delta admission
        # schema, rebuilt only when the model changes (not per upload)
        self._model_schema = {k: (a.shape, a.dtype) for k, a in
                              unpack_pytree(initial_model_blob).items()}
        # fail-fast on a degenerate reduce_blocks genome (REDUCTION SPEC
        # v2): the blocked partition must be well-formed over THIS
        # model's flattened param count, and the first merge is far too
        # late to find out it isn't
        from bflc_demo_tpu.ledger.base import reduce_blocks as _rblocks
        _blk = _rblocks(cfg)
        if _blk > 1:
            from bflc_demo_tpu.meshagg import spec as _spec
            _spec.block_bounds(
                sum(int(np.prod(s)) for s, _ in
                    self._model_schema.values()), _blk)
        # gas: per-sender per-epoch storage-op budget (None = auto: 50
        # model-blob-sized uploads' worth — generous for honest traffic,
        # finite for spam; 0 disables metering).  Bounds what one identity
        # can make the coordinator store/hash per epoch, the role gas plays
        # in the reference's substrate.
        self._gas_budget = (50 * (GAS_UPLOAD_BASE
                                  + len(initial_model_blob))
                            if gas_budget_per_epoch is None
                            else gas_budget_per_epoch)
        self._gas: Dict[str, Tuple[int, int]] = {}
        # quorum-ack replication (the PBFT-commit analogue, CP flavor):
        # with quorum=Q > 0 a storage mutation is only ACKNOWLEDGED to its
        # client after >= Q live subscribers confirmed applying every op up
        # to and including it.  An acknowledged op therefore survives any
        # single writer death with Q >= 1 (the promoted standby provably
        # holds it) — closing the acknowledged-op-loss window of pure
        # asynchronous streaming.  On timeout the reply is
        # REPLICATION_TIMEOUT: the op is in the local chain (an honest
        # retry gets DUPLICATE = progress once replicas catch up), but the
        # client must not yet treat it as durable.  Q=0 = async (default).
        self._quorum = quorum
        self._quorum_timeout_s = quorum_timeout_s
        self._sub_acked: Dict[object, int] = {}
        self._sub_sent: Dict[object, int] = {}
        self._sub_eligible: Dict[object, bool] = {}
        # authenticated subscribers' advertised read-fan-out endpoints:
        # republished in model replies so clients route blob/model reads
        # to replicas (comm.dataplane) instead of this accept loop
        self._sub_read_ep: Dict[object, Tuple[str, int]] = {}
        self._last_seen: Dict[str, float] = {}
        # replay rejection at the auth layer, not merely ledger idempotency
        # — the SAME ReplayGuard class AuthenticatedLedger uses, so the two
        # enforcement points cannot drift
        self._replay = ReplayGuard()
        self._last_progress = time.monotonic()
        self._rounds_completed = 0
        self._stop = threading.Event()
        # split-brain defense: set when a request arrives carrying VERIFIED
        # promotion evidence for a generation HIGHER than this ledger's —
        # someone provably promoted past us while we were partitioned.  The
        # server self-demotes: it answers that one request with
        # STALE_WRITER, then closes, so every later connect is refused and
        # clients rotate to the real writer.  A bare fence integer without
        # evidence is IGNORED (round-4 advisor: it was a one-message DoS).
        self.fenced = threading.Event()
        # index -> Ed25519 public bytes of provisioned standbys: the only
        # identities whose promotion evidence can demote this writer
        self._standby_keys: Dict[int, bytes] = dict(standby_keys or {})
        # set on a server constructed BY a promotion (comm.failover):
        # attached to every reply so clients learn the fence + its proof
        # passively and can present it to a stale writer
        self._promotion_evidence = promotion_evidence
        # --- BFT commit certificates (comm.bft): when validators are
        # provisioned, an op BINDS only once a quorum of them re-executed
        # it and co-signed; the ack carries the certificate, the op stream
        # publishes only certified ops, and an uncertifiable op answers
        # CERT_TIMEOUT (the mutation sits in the local chain, unbound —
        # honest retries are DUPLICATE = progress once the quorum heals).
        self._bft = None
        self._certs: Dict[int, dict] = dict(resume_certs or {})
        # op-hash -> certificate: the reply-binding index.  An ack (OK or
        # DUPLICATE-class) must carry the certificate of THE op the
        # request implies, or a Byzantine writer could replay any old
        # certificate on a forged ack — clients verify the binding
        # (comm.bft.expected_op_hash / verify_certificate_sigs).
        self._certs_by_ophash: Dict[str, dict] = {
            c["op_hash"]: c for c in self._certs.values()
            if isinstance(c, dict) and "op_hash" in c}
        # serialises certification (strictly sequential: each certificate
        # chains on the previous head); concurrent mutation threads take
        # turns extending the watermark — plain mutual exclusion, no
        # wakeup protocol
        self._cert_lock = threading.Lock()
        # pre-PR control-plane baseline switch (the benchmark's
        # before/after leg): sequential certification (one op per
        # validator round-trip) and no op-stream blob piggyback
        import os as _os
        self._legacy = bool(_os.environ.get("BFLC_CONTROL_PLANE_LEGACY"))
        # bounded in-flight certification window (PR 3): how many backlog
        # ops one certify_range round-trip may carry
        self._cert_batch = 1 if self._legacy else 128
        self._op_auth: Dict[int, dict] = {}
        # chain position -> originating traceparent (obs.trace): recorded
        # at append time for ops born inside a TRACED request, streamed
        # to subscribers as `tp` (standby mirror spans) and linked into
        # batched-vote spans.  Empty whenever tracing is off/unsampled —
        # the hot path pays one truthiness check.
        self._op_trace: Dict[int, str] = {}
        # upload-lag tracking for the straggler histogram: (epoch, wall
        # time of that epoch's first admitted upload)
        self._lag_epoch = -1
        self._lag_t0 = 0.0
        # hierarchical cell federation (bflc_demo_tpu.hier): when a cell
        # registry {aggregator address -> registered membership} is
        # provisioned, this server is a ROOT — uploads are cell-aggregate
        # ops (a partial-sum blob + reserved #cellmeta evidence entry,
        # `n` = admitted client count) and only registered aggregators
        # may submit them, with `n` bounded by their registered
        # membership (the anti-inflation check; hier.partial).  None =
        # the unchanged single-tier server.
        self._cell_registry: Optional[Dict[str, Tuple[int, int]]] = (
            dict(cell_registry) if cell_registry is not None else None)
        # asynchronous buffered aggregation (--async-buffer K; FedBuff on
        # the certified op stream): the writer admits staleness-tagged
        # deltas at any time (aupload), committee members score the
        # buffer with no epoch gate (ascores), and every K admissions the
        # oldest k entries aggregate with staleness-discounted weights —
        # all as ops in the certified total order, so validators/standbys
        # re-derive the same buffer and async stays no-fork by
        # construction.  False (K=0 or BFLC_ASYNC_LEGACY=1) pins the
        # synchronous round barrier byte-for-byte.
        self._async = async_enabled(cfg)
        # sparse upload deltas (--delta-density < 1, utils.serialization):
        # admission decodes through the ONE densify inverse (a malformed
        # #topk record is a schema error at the door), the decoded DENSE
        # image is what gets staged — so meshagg reduction bytes and every
        # golden hash pin are untouched by construction — and upload ops'
        # auth evidence carries the (small) sparse blob so BFT validators
        # re-execute the same decode before co-signing.  Dense fleets
        # (density 1.0 or BFLC_SPARSE_LEGACY=1) reject #topk entries as
        # the schema garbage they then are.
        self._sparse = sparse_enabled(cfg)
        # closed-loop compression (--adapt-every N, control.loop): every
        # N-th commit the writer proposes a certified genome-update op
        # (opcode 13) retuning the EFFECTIVE delta density / staleness
        # bound from the round's convergence telemetry; every validator
        # re-runs the fixed rule and refuses a transition it cannot
        # re-derive.  Off (N=0 or BFLC_ADAPT_LEGACY=1) pins the static
        # knobs byte-for-byte.
        from bflc_demo_tpu.ledger.base import adapt_enabled
        self._adapt = adapt_enabled(cfg)
        # validator re-derivation plane (bflc_demo_tpu.rederive): when
        # armed, every commit/acommit op's auth evidence carries the
        # claimed NEW model blob (hash-bound to the op) plus the current
        # read set + this writer's endpoint, and the round's consumed
        # blobs — the admitted deltas and the previous model — are
        # RETAINED one round in _rederive_blobs so a validator's
        # coordinator-fallback fetch can still be served after the
        # commit popped them from the working set.  Off (default): no
        # evidence, no retention, bytes unchanged.
        from bflc_demo_tpu.rederive import rederive_armed
        self._rederive = rederive_armed()
        self._rederive_blobs: Dict[bytes, bytes] = {}
        self._rederive_commit_pos: Optional[int] = None
        self._rederive_cell_auth: List[int] = []
        if bft_validators:
            from bflc_demo_tpu.comm.bft import CertificateAssembler
            from bflc_demo_tpu.protocol.constants import bft_quorum as _bq
            q = bft_quorum if bft_quorum is not None \
                else _bq(len(bft_validators))
            if not 0 < q <= len(bft_validators):
                raise ValueError(f"bft_quorum {q} out of range for "
                                 f"{len(bft_validators)} validators")
            self._bft = CertificateAssembler(
                bft_validators, bft_keys or {}, q,
                timeout_s=bft_timeout_s, tls=None,
                backlog_fn=self._bft_backlog)
            # a resumed (promoted) chain arrives fully certified — the
            # standby refused uncertified appends and certified its own
            # fence op before constructing this server
            self._certified_size = self.ledger.log_size()
            self._cert_head = self.ledger.log_head() \
                if self._certified_size else b"\0" * 32
            if self._certified_size and \
                    len(self._certs) < self._certified_size:
                raise ValueError(
                    f"BFT resume: {self._certified_size} chain ops but "
                    f"only {len(self._certs)} certificates")
        else:
            self._certified_size = 0
            self._cert_head = b"\0" * 32
        self._threads: List[threading.Thread] = []

        if sock is not None:
            self._sock = sock
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((host, port))
            self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()

    # ------------------------------------------------------------------ run
    def start(self) -> None:
        """Accept + monitor threads in the background (test convenience)."""
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        m = threading.Thread(target=self._monitor_loop, daemon=True)
        m.start()
        self._threads += [t, m]

    def serve_forever(self) -> None:
        self.start()
        try:
            while not self._stop.is_set():
                time.sleep(0.1)
        finally:
            self.close()

    def close(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._bft is not None:
            self._bft.close()
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()

    # ----------------------------------------------------------- connection
    def _serve_conn(self, conn: socket.socket) -> None:
        if self._tls is not None:
            import ssl as _ssl
            try:
                conn.settimeout(10.0)       # bound the handshake
                conn = self._tls.wrap_socket(conn, server_side=True)
                conn.settimeout(None)
            except (_ssl.SSLError, OSError):
                # plaintext or broken peer: reject at the transport
                try:
                    conn.close()
                except OSError:
                    pass
                return
        try:
            while not self._stop.is_set():
                msg = recv_msg(conn)
                if msg is None:
                    return
                method = msg.get("method", "")
                if method == "subscribe":
                    start = int(msg.get("from", 0))
                    eligible = ("sb" in msg and
                                self._subscriber_handshake(conn, msg,
                                                           start))
                    read_ep = None
                    if eligible and isinstance(msg.get("read_ep"),
                                               (list, tuple)):
                        # an AUTHENTICATED standby may advertise its
                        # read-fan-out endpoint; the writer republishes
                        # the live set in model replies so clients can
                        # take their blob reads off this accept loop.
                        # Anonymous subscribers never enter the set (a
                        # hostile read replica cannot serve wrong bytes
                        # — everything is hash-verified — but it could
                        # sinkhole reads for a round-trip each).
                        try:
                            ep = msg["read_ep"]
                            read_ep = (str(ep[0]), int(ep[1]))
                        except (TypeError, ValueError, IndexError):
                            read_ep = None
                    self._stream_ops(conn, start, eligible, read_ep)
                    return
                try:
                    fence = int(msg.get("fence", -1))
                except (TypeError, ValueError):
                    fence = -1
                if fence > self.ledger.generation:
                    # a higher writer generation is CLAIMED.  Demote only on
                    # verified promotion evidence (signed by a provisioned
                    # standby, chained to our own log prefix) — a bare
                    # integer from any client must not be able to kill the
                    # writer (round-4 advisor DoS).  Unverifiable claims are
                    # served normally; a genuinely stale writer still loses
                    # its clients because every reply carries `gen` and
                    # FailoverClient rejects replies behind its own fence.
                    ev = msg.get("fence_ev")
                    if isinstance(ev, dict) and verify_promotion_evidence(
                            ev, self.ledger, self._standby_keys):
                        reply = {"ok": False, "status": "STALE_WRITER",
                                 "gen": self.ledger.generation,
                                 "observed_fence": fence}
                        try:
                            send_msg(conn, reply)
                        finally:
                            obs_flight.FLIGHT.record(
                                "event", "writer_fenced",
                                gen=self.ledger.generation,
                                observed_fence=fence)
                            obs_flight.FLIGHT.flush("fenced")
                            self.fenced.set()
                            self.close()
                        return
                t_req = (time.perf_counter()
                         if obs_metrics.REGISTRY.enabled else 0.0)
                # causal span over the request's WHOLE server-side life
                # (dispatch + certification + quorum wait) — adopted
                # from the frame's `_tp` context; the null span for
                # untraced frames (obs.trace)
                try:
                    with obs_trace.server_span(msg, "serve",
                                               method=method):
                        reply = self._dispatch(method, msg)
                        post_size = reply.pop("_post_size", None)
                        if self._bft is not None and post_size is not None:
                            # BFT mode: the ack may only carry state that a
                            # validator quorum co-signed — certify the ops this
                            # request appended (and any predecessors) first
                            cert = self._ensure_certified(post_size)
                            if cert is None:
                                reply = {"ok": False, "status": "CERT_TIMEOUT",
                                         "error": "no validator quorum "
                                                  "co-signed the op"}
                                post_size = None
                            else:
                                # attach the certificate of THIS request's op
                                # (reconstructed from its own fields), not
                                # merely the newest one: for DUPLICATE-class
                                # replies the op bound earlier, and a client
                                # rightly rejects a certificate that does not
                                # bind the op it asked about
                                from bflc_demo_tpu.comm.bft import \
                                    expected_op_hash
                                oh = expected_op_hash(method, msg)
                                if oh is not None:
                                    cert = self._certs_by_ophash.get(
                                        oh.hex(), None)
                                reply["cert"] = cert
                        if (self._quorum
                                and post_size is not None
                                and not self._await_quorum(post_size)):
                            # the op is in the local chain but not provably on
                            # quorum replicas: do NOT acknowledge durability.
                            # The client's signed retry is safe (DUPLICATE =
                            # progress) once followers catch up.
                            reply = {"ok": False,
                                     "status": "REPLICATION_TIMEOUT",
                                     "error": "op not yet on quorum replicas"}
                except Exception as e:      # noqa: BLE001 — any dispatch
                    # failure (including a RuntimeError thrown by
                    # aggregation inside the scores handler) must produce an
                    # error frame: a silently-killed connection thread
                    # leaves the innocent caller blocked until its socket
                    # timeout even though its own op may have been accepted
                    reply = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                # every reply carries the writer generation so clients learn
                # the current fence passively and propagate it on requests;
                # a promoted writer also attaches the signed proof so
                # clients can demote the stale one on contact
                reply.setdefault("gen", self.ledger.generation)
                if self._promotion_evidence is not None:
                    reply.setdefault("gen_ev", self._promotion_evidence)
                if t_req:
                    _M_RPC.observe(time.perf_counter() - t_req,
                                   method=method)
                send_msg(conn, reply)
        except (WireError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # ------------------------------------------------- commit certificates
    def _bft_backlog(self, j: int):
        """(op bytes, auth evidence, certificate) for chain position j —
        the resync surface a lagging or REJOINING validator replays
        through.  Auth evidence is this process's memory; after a
        promotion it is gone for pre-promotion ops, so the certificate
        rides along (a quorum already re-verified those tags) and, for
        register ops, the self-authenticating pubkey is recovered from
        the directory so the rejoining validator's own directory stays
        complete for FRESH client traffic.

        Below a GC'd prefix the op bytes are gone: raises
        comm.bft.PrefixCompacted carrying the snapshot offer, which the
        CertificateAssembler turns into a `bft_snapshot` install on the
        lagging validator (state-sync instead of replay)."""
        with self._lock:
            base = getattr(self.ledger, "log_base", 0)
            if j < base:
                from bflc_demo_tpu.comm.bft import PrefixCompacted
                raise PrefixCompacted(
                    self._snapshot_offer(require_model=False), base)
            op = self.ledger.log_op(j)
            auth = self._op_auth.get(j)
            if auth is None and op and op[0] == 1:      # register opcode
                try:
                    (n,) = struct.unpack_from("<q", op, 1)
                    addr = op[9:9 + n].decode()
                    pub = self.directory.export_raw().get(addr)
                    if pub is not None:
                        auth = {"pubkey": pub.hex()}
                except (struct.error, UnicodeDecodeError):
                    pass
            return op, auth, self._certs.get(j)

    def _ensure_certified(self, upto: int,
                          timeout_s: Optional[float] = None,
                          ) -> Optional[dict]:
        """Drive certification of ops [certified_size, upto); returns the
        wire certificate of op upto-1 or None on quorum failure.

        Serialised on _cert_lock (certification is strictly sequential —
        each certificate chains on the previous head); concurrent
        mutation threads block here and take over the watermark in turn.
        Votes are gathered WITHOUT the ledger lock, so reads and other
        dispatches proceed meanwhile.

        Batched + pipelined (PR 3): each pass drains the WHOLE
        uncertified backlog — not just [.., upto) — in one
        `certify_range` round-trip per validator, bounded by
        `_cert_batch` ops in flight so fencing / self-demotion checks
        run between windows.  Ops appended by other dispatch threads
        while a batch's votes are in flight simply ride the next batch:
        vote-gathering overlaps writer-side accept, and a mutator
        queueing on _cert_lock usually finds its op already certified
        when it gets the lock.  Any position the fast path cannot
        certify falls through to the single-op `certify`, whose
        conflict-resync / repair / superseded machinery is untouched.
        BFLC_CONTROL_PLANE_LEGACY=1 pins `_cert_batch` to 1 — the
        pre-PR one-op-per-round-trip behaviour, kept as the benchmark
        baseline switch.
        """
        if self._bft is None:
            return None
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self._bft.timeout_s)
        with self._cert_lock:
            while self._certified_size < upto:
                if self._stop.is_set():
                    return None
                i = self._certified_size
                prev = self._cert_head
                with self._lock:
                    hi = min(max(upto, self.ledger.log_size()),
                             i + self._cert_batch)
                    entries = [(self.ledger.log_op(j),
                                self._op_auth.get(j))
                               for j in range(i, hi)]
                    # originating trace context per op in the window
                    # (obs.trace): the vote round-trip spans link to
                    # every one of them, so a batch that certifies five
                    # clients' ops shows up in five traces
                    tps = ([self._op_trace.get(j) for j in range(i, hi)]
                           if self._op_trace else None)
                if len(entries) > 1:
                    tr = tracing.PROC
                    t0 = time.perf_counter() if (
                        tr.enabled or obs_metrics.REGISTRY.enabled) \
                        else 0.0
                    certs = self._bft.certify_range(i, entries, prev,
                                                    tps=tps)
                    dt = time.perf_counter() - t0 if t0 else 0.0
                    if tr.enabled:
                        tr.charge("bft.certify_s", dt)
                    if obs_metrics.REGISTRY.enabled:
                        _M_CERTIFY.observe(dt, mode="batch")
                    installed = 0
                    for k, cert in enumerate(certs):
                        if cert is None:
                            break
                        self._install_certificate(i + k, entries[k][0],
                                                  cert.to_wire())
                        installed += 1
                    if tr.enabled and installed:
                        tr.charge("bft.certify_batched_ops", installed)
                    if obs_metrics.REGISTRY.enabled and installed:
                        _M_CERT_BATCH.observe(installed)
                    if installed:
                        with self._cv:
                            self._cv.notify_all()
                        continue        # drained some: advance / re-batch
                op, auth = entries[0]
                tr = tracing.PROC
                t0 = time.perf_counter() if (
                    tr.enabled or obs_metrics.REGISTRY.enabled) else 0.0
                cert = self._bft.certify(i, op, auth, prev,
                                         tp=(tps[0] if tps else None))
                dt = time.perf_counter() - t0 if t0 else 0.0
                if tr.enabled:
                    tr.charge("bft.certify_s", dt)
                if obs_metrics.REGISTRY.enabled:
                    _M_CERTIFY.observe(dt, mode="single")
                if cert is None:
                    if getattr(self._bft, "superseded_op", None) \
                            is not None:
                        # the validator quorum mandated a FOREIGN op at
                        # our chain position: someone else (a promoted
                        # standby) is writing the canonical chain and our
                        # suffix is provably uncertifiable.  Self-demote
                        # like the STALE_WRITER path — retrying would
                        # stall every client against a doomed chain.
                        if self.verbose:
                            print("[coordinator] certification "
                                  "superseded by a foreign proposer: "
                                  "self-demoting", flush=True)
                        obs_flight.FLIGHT.record(
                            "event", "writer_superseded", position=i)
                        obs_flight.FLIGHT.flush("superseded")
                        self.fenced.set()
                        self.close()
                        return None
                    if time.monotonic() > deadline:
                        return None
                    # transient quorum failure: retry within budget, but
                    # never hot-spin — a refused connect fails in
                    # microseconds and would otherwise hammer the
                    # validator endpoints for the whole timeout
                    time.sleep(0.2)
                    continue
                self._install_certificate(i, op, cert.to_wire())
                if tr.enabled:
                    tr.charge("bft.certify_single_ops")
                with self._cv:
                    self._cv.notify_all()   # wake gated op-stream pushers
            return self._certs.get(upto - 1)

    def _install_certificate(self, i: int, op: bytes, wire: dict) -> None:
        """Record op i's certificate and advance the certification
        watermark (caller holds _cert_lock and notifies _cv)."""
        from bflc_demo_tpu.comm.bft import next_head
        self._certs[i] = wire
        self._certs_by_ophash[wire["op_hash"]] = wire
        self._cert_head = next_head(self._cert_head, op)
        self._certified_size = i + 1

    def _stream_ops(self, conn: socket.socket, start: int,
                    quorum_eligible: bool,
                    read_ep: Optional[Tuple[str, int]] = None) -> None:
        """Push canonical op bytes from `start` onward until the peer goes
        away — the replica feed (WAL-identical bytes, ledger.cpp op codec).

        The connection is full-duplex: a dedicated reader drains the
        subscriber's `{"ack": i}` frames (sent by Standby after each
        successful apply) into `_sub_acked` — unconditionally, so an
        acking follower can never wedge on a filled send buffer — and the
        quorum waiters are notified.  quorum_eligible marks whether this
        subscriber's acks may count toward the durability quorum (it
        proved a provisioned standby identity at subscribe time — an
        anonymous peer could otherwise void the guarantee by acking
        without persisting anything).
        """
        sub_id = object()
        with self._cv:
            # clamp the claimed start to the real log: a subscriber that
            # "starts" at 10**18 must not become able to ack (and fake
            # durability for) ops it was never sent
            start = max(0, min(start, self.ledger.log_size()))
            base = getattr(self.ledger, "log_base", 0)
            if start >= base:
                # register under the SAME lock as the base check: the
                # snapshot GC's slowest-live-subscriber clamp must see
                # this subscriber the instant the check passes, or a GC
                # slipping between check and registration would compact
                # the very ops this stream is about to push
                self._sub_acked[sub_id] = -1
                self._sub_sent[sub_id] = start - 1
                self._sub_eligible[sub_id] = quorum_eligible
                if read_ep is not None:
                    self._sub_read_ep[sub_id] = read_ep
        if start < base:
            # the subscriber's resume point was GC'd behind a certified
            # snapshot: it cannot replay the prefix — answer with the
            # state-sync marker and let it install snapshot + tail
            # (comm.failover Standby / `replicate`).  Standbys normally
            # probe `info.log_base` before subscribing; this frame
            # covers the race where GC ran in between.
            try:
                send_msg(conn, {"state_sync": 1, "base": base})
            except (WireError, OSError):
                pass
            return
        reader = threading.Thread(target=self._ack_reader,
                                  args=(conn, sub_id), daemon=True)
        reader.start()
        try:
            next_i = start
            while not self._stop.is_set():
                with self._cv:
                    size = self.ledger.log_size()
                    if self._bft is not None:
                        # BFT mode: publish only CERTIFIED ops — a standby
                        # must never replicate (or ack durability for)
                        # state no validator quorum co-signed
                        size = min(size, self._certified_size)
                    ops = [self.ledger.log_op(i)
                           for i in range(next_i, min(size, next_i + 256))]
                    if not ops:
                        self._cv.wait(timeout=0.5)
                        continue
                    # advance the sent watermark BEFORE the (lock-free)
                    # send: a follower acks each op exactly once, and an
                    # ack racing the post-batch update would be clamped
                    # down and lost forever (spurious REPLICATION_TIMEOUT
                    # for an op that really replicated)
                    self._sub_sent[sub_id] = next_i + len(ops) - 1
                for i, op in enumerate(ops):
                    frame = {"i": next_i + i, "op": op.hex()}
                    if self._bft is not None:
                        frame["cert"] = self._certs.get(next_i + i)
                    if self._op_trace:
                        # originating trace context rides the push so a
                        # standby's mirror/ack lands in the op's trace
                        # (obs.trace; absent for untraced ops)
                        tp = self._op_trace.get(next_i + i)
                        if tp:
                            frame["tp"] = tp
                    blob = (None if self._legacy
                            else self._op_payload_blob(op))
                    if blob is not None:
                        # piggyback an upload op's payload blob on the
                        # push (binary frame tail): the follower's
                        # mirror-before-apply gate is satisfied without
                        # a fetch round-trip on the ack critical path —
                        # it still hash-verifies against the op, so a
                        # lying writer gains nothing (PR 3)
                        frame["blob"] = blob
                    send_msg(conn, frame)
                next_i += len(ops)
        finally:
            with self._cv:
                self._sub_acked.pop(sub_id, None)
                self._sub_sent.pop(sub_id, None)
                self._sub_eligible.pop(sub_id, None)
                self._sub_read_ep.pop(sub_id, None)
                self._cv.notify_all()

    _UPLOAD_OPCODE = 2          # ledger op codec (ledger/tool.decode_op)
    _COMMIT_OPCODE = 4
    _AUPLOAD_OPCODE = 10        # async twins (ledger.base)
    _ACOMMIT_OPCODE = 12

    def _op_payload_blob(self, op: bytes) -> Optional[bytes]:
        """The blob a streamed op references, when this writer still
        holds it: an upload op's payload (PR 3), or — data-plane fast
        path — a commit op's NEW MODEL blob, so followers are
        model-fresh the moment the commit applies and can serve the
        round's read fan-out without a fetch round-trip (None otherwise;
        a commit superseded by a later one no longer matches and ships
        nothing).  Decoded via the ONE op codec (ledger.tool.decode_op)
        so the piggyback cannot silently drift from the chain's byte
        layout."""
        if not op:
            return None
        if op[0] in (self._COMMIT_OPCODE, self._ACOMMIT_OPCODE):
            if data_plane_legacy():
                return None
            from bflc_demo_tpu.ledger.tool import decode_op
            try:
                mh = bytes.fromhex(decode_op(op)["model_hash"])
            except (KeyError, ValueError):
                return None
            with self._lock:
                return self._model_blob if self._model_hash == mh \
                    else None
        if op[0] not in (self._UPLOAD_OPCODE, self._AUPLOAD_OPCODE):
            return None
        from bflc_demo_tpu.ledger.tool import decode_op
        try:
            digest = bytes.fromhex(decode_op(op)["payload_hash"])
        except (KeyError, ValueError):
            return None
        with self._lock:
            return self._blobs.get(digest)

    def _ack_reader(self, conn: socket.socket, sub_id: object) -> None:
        try:
            while not self._stop.is_set():
                msg = recv_msg(conn)
                if msg is None:
                    return
                try:
                    i = int(msg.get("ack", -1))
                except (TypeError, ValueError):
                    continue
                with self._cv:
                    if sub_id not in self._sub_acked:
                        return
                    # clamp: a subscriber cannot ack ops it was never
                    # sent (an inflated index would fake durability)
                    i = min(i, self._sub_sent.get(sub_id, -1))
                    if i > self._sub_acked[sub_id]:
                        self._sub_acked[sub_id] = i
                        self._cv.notify_all()
        except (WireError, OSError):
            return

    def _await_quorum(self, post_size: int) -> bool:
        """Block until >= quorum ELIGIBLE subscribers acked through op
        index post_size-1 (the requester's own op, snapshotted at append
        time), or the timeout passes.  `Condition.wait` fully releases
        the (R)lock, so followers keep pulling and acking while we wait.

        Eligibility: when standby identities are provisioned, only
        subscribers that authenticated as one count — an anonymous
        subscriber acking everything must not void the durability
        guarantee.  With no standby_keys configured (closed/test setups),
        every subscriber counts.
        """
        target = post_size - 1
        deadline = time.monotonic() + self._quorum_timeout_s
        with self._cv:
            while not self._stop.is_set():
                n = sum(1 for s, a in self._sub_acked.items()
                        if a >= target and
                        (self._sub_eligible.get(s, False)
                         or not self._standby_keys))
                if n >= self._quorum:
                    return True
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return False
                self._cv.wait(rem)
        return False

    _SUB_MAGIC = b"BFLCSUB1"

    def _subscriber_handshake(self, conn: socket.socket, msg: dict,
                              start: int) -> bool:
        """Challenge-response proof of a provisioned standby identity.

        The server issues a fresh random challenge; the subscriber signs
        (magic || challenge || standby index || start) with its Ed25519
        key.  Only such subscribers' acks count toward the durability
        quorum.  A fixed signed subscribe message would be replayable on a
        plaintext link (round-5 review) — the per-connection nonce makes
        every captured handshake worthless.  On any failure the peer still
        streams, just without quorum eligibility.
        """
        import os as _os
        try:
            sb = int(msg.get("sb", -1))
        except (TypeError, ValueError):
            return False
        pub = self._standby_keys.get(sb)
        challenge = _os.urandom(16)
        try:
            send_msg(conn, {"challenge": challenge.hex()})
            conn.settimeout(10.0)
            reply = recv_msg(conn)
            conn.settimeout(None)
        except (WireError, OSError):
            return False
        if pub is None or not isinstance(reply, dict):
            return False
        try:
            sig = bytes.fromhex(reply.get("tag", ""))
        except (TypeError, ValueError):
            return False
        from bflc_demo_tpu.comm.identity import verify_signature
        return verify_signature(pub, self._SUB_MAGIC + challenge
                                + struct.pack("<Iq", sb, start), sig)

    # ------------------------------------------------------------- dispatch
    def _touch(self, addr: str) -> None:
        self._last_seen[addr] = time.monotonic()

    def _verify(self, kind: str, addr: str, epoch: int, payload: bytes,
                tag_hex: str) -> "LedgerStatus":
        """OK = fresh valid tag; DUPLICATE = valid but consumed (an honest
        retry whose reply was lost — e.g. across a failover — or a replay;
        the op is already in either way); BAD_ARG = signature failure.
        Same tri-state as AuthenticatedLedger._verify."""
        if not self.require_auth:
            return LedgerStatus.OK
        tag = bytes.fromhex(tag_hex)
        if not self.directory.verify(
                addr, _op_bytes(kind, addr, epoch, payload), tag):
            return LedgerStatus.BAD_ARG
        if self._replay.seen(epoch, tag):
            return LedgerStatus.DUPLICATE
        return LedgerStatus.OK

    def _consume_tag(self, epoch: int, tag_hex: str) -> None:
        if not self.require_auth:
            return
        # async mode prunes with the staleness floor, not the current
        # epoch: a sync-path consume here (e.g. a mid-run register)
        # must not drop the aupload tag buckets inside the staleness
        # window, or a pruned-then-replayed signed aupload would
        # re-enter the buffer as a fresh delta
        floor = (self.ledger.epoch - self.cfg.max_staleness
                 if self._async else self.ledger.epoch)
        self._replay.consume(floor, epoch, bytes.fromhex(tag_hex))

    def _charge_gas(self, addr: str, cost: int) -> bool:
        """Debit `cost` from addr's current-epoch budget; False = broke.

        Call with the lock held, and — when require_auth — only AFTER the
        request's signature verified as a fresh valid tag: gas must bind
        to a PROVEN identity, or any connected peer could drain a victim's
        budget by spoofing their address (round-5 review finding).  The
        residual pre-auth cost per request (hashing the wire payload to
        check the tag) is bounded by the wire frame cap and the serial
        per-connection loop.

        The ledger epoch advancing resets every sender's allowance (the
        reference's per-tx gas refreshes per tx; per-epoch is the
        equivalent granularity — one epoch is one round of legitimate
        storage traffic)."""
        if not self._gas_budget:
            return True
        ep = self.ledger.epoch
        last_ep, used = self._gas.get(addr, (ep, 0))
        if last_ep != ep:
            used = 0
        if used + cost > self._gas_budget:
            # no insert on the reject path: unknown addrs must not be able
            # to grow the table by going straight over budget
            if addr in self._gas:
                self._gas[addr] = (ep, used)
            return False
        if addr not in self._gas and len(self._gas) >= 8192:
            # bound the meter table against address-rotation spam: drop
            # stale-epoch entries first, then evict oldest same-epoch
            # entries until under the cap (dicts preserve insert order)
            self._gas = {a: (e, u) for a, (e, u) in self._gas.items()
                         if e == ep}
            while len(self._gas) >= 8192:
                self._gas.pop(next(iter(self._gas)))
        self._gas[addr] = (ep, used + cost)
        return True

    _OUT_OF_GAS = {"ok": False, "status": "OUT_OF_GAS",
                   "error": "per-epoch storage budget exhausted"}

    _MUTATING = ("register", "upload", "scores", "aupload", "ascores")

    def _dispatch(self, method: str, m: dict) -> dict:
        with self._lock:            # RLock: the inner re-acquires freely
            size0 = (self.ledger.log_size()
                     if obs_trace.TRACE.enabled else 0)
            reply = self._dispatch_inner(method, m)
            if obs_trace.TRACE.enabled and "_tp" in m:
                # bind every op THIS traced request appended (an upload
                # appends one; a scores request may also append
                # close/aggregate/commit ops) to its originating trace:
                # the op stream and the vote batches carry it onward
                for j in range(size0, self.ledger.log_size()):
                    self._op_trace[j] = m["_tp"]
            if method in self._MUTATING and (
                    reply.get("ok")
                    or reply.get("status") in ("DUPLICATE",
                                               "ALREADY_REGISTERED")):
                # snapshot THIS op's chain position while still holding
                # the lock: the quorum wait must target the requester's
                # own op, not whatever a concurrent writer appended after
                # (review finding: waiting on the live head misreports
                # durability under concurrency).  DUPLICATE-class replies
                # get the snapshot too — callers treat "already in" as
                # progress, so a retry after REPLICATION_TIMEOUT must not
                # skip the quorum wait and reopen the loss window (the
                # op sits at or below the current head).
                reply["_post_size"] = self.ledger.log_size()
        return reply

    def _read_set(self) -> List[Tuple[str, int]]:
        """Read-fan-out endpoints currently advertised by authenticated
        subscribers (comm.dataplane) — empty under the legacy switch."""
        if data_plane_legacy():
            return []
        with self._cv:
            return sorted(set(self._sub_read_ep.values()))

    def _blob_lookup(self, digest: bytes) -> Optional[bytes]:
        """The read-serving blob lookup: the working set, then the
        rederive plane's one-round retention (validators fetching the
        just-committed round's inputs after the commit popped them)."""
        blob = self._blobs.get(digest)
        if blob is None and self._rederive_blobs:
            blob = self._rederive_blobs.get(digest)
        return blob

    def _stash_rederive(self, new_blob: bytes,
                        round_blobs: Dict[bytes, bytes]) -> None:
        """Arm the just-appended commit op for validator re-derivation
        (caller holds the lock, BEFORE the model/blob swap): evidence on
        the op's auth record + one round of blob retention.  The
        previous model rides under its own hash — a validator that
        missed the last round's verification fetches it content-
        addressed like any delta.  The PREVIOUS commit's fat `mblob`
        evidence is dropped here (endpoints kept): retaining every
        round's full model hex would grow writer memory ~2x model size
        per round forever, and a validator replaying old certified
        commits admits them on their certificate (or degrades to the
        counted skip) — the evidence is only load-bearing until its own
        certification."""
        prev = self._rederive_commit_pos
        if prev is not None and prev in self._op_auth:
            self._op_auth[prev].pop("mblob", None)
        # ... and the same rule for the round's CELL evidence (hier
        # root): the cell uploads certified at their own acks, before
        # this commit — their fat partial blobs + member listings are
        # no longer load-bearing (backlog resync admits on the
        # certificate).  The sparse-mode "blob" evidence pre-dates the
        # plane and keeps its historical retention.
        for p in self._rederive_cell_auth:
            a = self._op_auth.get(p)
            if a is not None:
                a.pop("cell", None)
                if not self._sparse:
                    a.pop("blob", None)
        self._rederive_cell_auth = []
        pos = self.ledger.log_size() - 1
        round_blobs[self._model_hash] = self._model_blob
        self._rederive_blobs = round_blobs
        self._rederive_commit_pos = pos
        self._op_auth[pos] = {
            "mblob": new_blob.hex(),
            "rs": [list(ep) for ep in self._read_set()],
            "co": [self.host, self.port]}

    def _dispatch_inner(self, method: str, m: dict) -> dict:
        with self._lock:
            # blob / blobs / model ride the ONE shared read dispatch
            # (comm.dataplane.handle_read) — the same hash-addressed
            # protocol standby read replicas and the mesh executor serve
            read = handle_read(
                method, m, blob_lookup=self._blob_lookup,
                model_state=lambda: (self.ledger.epoch, self._model_hash,
                                     self._model_blob),
                read_set=self._read_set)
            if read is not None:
                return read
            if method == "register":
                addr = m["addr"]
                if self.require_auth:
                    pub = bytes.fromhex(m.get("pubkey", ""))
                    if self._open_enrollment:
                        # trust-on-first-use: the address must BE the key
                        if address_of(pub) != addr:
                            return {"ok": False, "status": "BAD_ARG",
                                    "error": "address/pubkey mismatch"}
                        if not self.directory.knows(addr):
                            self.directory.enroll(pub)
                    elif not self.directory.knows(addr):
                        return {"ok": False, "status": "BAD_ARG",
                                "error": "unknown identity"}
                    v = self._verify("register", addr, 0, b"",
                                     m.get("tag", ""))
                    if v != LedgerStatus.OK:
                        return {"ok": False, "status": v.name,
                                "error": "bad signature" if
                                v == LedgerStatus.BAD_ARG else
                                "replayed tag"}
                # post-auth: the signature proved the sender IS addr
                if not self._charge_gas(addr, GAS_REGISTER):
                    return dict(self._OUT_OF_GAS)
                st = self.ledger.register_node(addr)
                if st == LedgerStatus.OK:
                    self._consume_tag(0, m.get("tag", ""))
                    # auth evidence for the BFT validators: they must
                    # re-verify the client's tag against THEIR directory
                    # mirror or a hostile writer could fabricate this op
                    self._op_auth[self.ledger.log_size() - 1] = {
                        "tag": m.get("tag", ""),
                        "pubkey": m.get("pubkey", "")}
                self._touch(addr)
                self._note_progress(st)
                return {"ok": st == LedgerStatus.OK, "status": st.name,
                        "epoch": self.ledger.epoch}
            if method == "state":
                addr = m["addr"]
                self._touch(addr)
                role, epoch = self.ledger.query_state(addr)
                reply = {"ok": True, "role": role, "epoch": epoch,
                         "round_closed": self.ledger.round_closed}
                reply.update(self._state_knobs())
                return reply
            if method == "upload":
                if self._async:
                    # one protocol per chain: a client whose local
                    # BFLC_ASYNC_LEGACY disagrees with the fleet's must
                    # not interleave synchronous rounds into an async
                    # chain (it would silently inflate every buffered
                    # entry's staleness)
                    return {"ok": False, "status": "BAD_ARG",
                            "error": "async mode is on: use aupload"}
                addr = m["addr"]
                blob = blob_bytes(m["blob"])
                digest = hashlib.sha256(blob).digest()
                if digest.hex() != m["hash"]:
                    return {"ok": False, "status": "BAD_ARG",
                            "error": "blob/hash mismatch"}
                payload = digest + struct.pack("<qd", int(m["n"]),
                                               float(m["cost"]))
                v = self._verify("upload", addr, int(m["epoch"]), payload,
                                 m.get("tag", ""))
                if v != LedgerStatus.OK:
                    if v == LedgerStatus.DUPLICATE:
                        self._resupply_blob(digest, blob)
                    return {"ok": False, "status": v.name,
                            "error": "bad signature" if
                            v == LedgerStatus.BAD_ARG else "replayed tag"}
                # post-auth (fresh valid tag proved the sender): charge
                # base + payload bytes so one identity cannot stream
                # unbounded blob traffic within an epoch's allowance
                if not self._charge_gas(addr, GAS_UPLOAD_BASE + len(blob)):
                    return dict(self._OUT_OF_GAS)
                # structural admission check (post-auth so unsigned spam
                # can't buy blob decodes): a delta whose leaves don't match
                # the current model must die HERE, not later inside an
                # innocent committee member's scores dispatch when
                # aggregation walks the mismatched keys.  A hier root
                # additionally enforces the cell contract (registered
                # aggregator, #cellmeta present, claimed client count
                # within registered membership — hier.partial).
                err, aggflat = (
                    self._decode_cell_partial(addr, blob, int(m["n"]))
                    if self._cell_registry is not None
                    else self._decode_delta(blob))
                if err:
                    return {"ok": False, "status": "BAD_ARG", "error": err}
                st = self.ledger.upload_local_update(
                    addr, digest, int(m["n"]), float(m["cost"]),
                    int(m["epoch"]))
                if st == LedgerStatus.OK:
                    # stage the admission decode for the meshagg
                    # aggregate (one stack + one program at commit)
                    self._stage_delta(digest, aggflat)
                    if obs_metrics.REGISTRY.enabled:
                        # straggler evidence: admission lag behind this
                        # round's FIRST admitted upload (0 for the
                        # leader) — the heavy-tail axis the async-
                        # aggregation roadmap item needs measured
                        now = time.monotonic()
                        ep = int(m["epoch"])
                        if self._lag_epoch != ep:
                            self._lag_epoch = ep
                            self._lag_t0 = now
                        _M_UPLOAD_LAG.observe(now - self._lag_t0)
                    self._blobs[digest] = blob
                    self._consume_tag(int(m["epoch"]), m.get("tag", ""))
                    # f64 originals ride along: the op stores f32, the tag
                    # signs f64 — validators re-check both (comm.bft).
                    # The sender's (self-authenticating) pubkey rides too,
                    # so a validator with a directory hole — rejoined
                    # through a mid-registration promotion — heals on
                    # this op instead of refusing the client forever
                    auth = {"tag": m.get("tag", ""), "n": int(m["n"]),
                            "cost": float(m["cost"]),
                            "pubkey": self._sender_pubkey_hex(addr)}
                    if self._sparse:
                        # sparse mode: the (small — that's the point)
                        # blob rides the auth evidence so validators
                        # re-execute the densify admission check
                        # before co-signing (comm.bft
                        # check_sparse_upload_op) — a colluding writer
                        # cannot certify a malformed #topk blob
                        auth["blob"] = blob.hex()
                    if self._cell_registry is not None \
                            and self._rederive \
                            and isinstance(m.get("cell_ev"), dict):
                        # hier root + rederive plane: the cell's
                        # member-signed admission listing + the partial
                        # blob ride the evidence so every validator can
                        # re-derive the cell partial from member blobs
                        # (rederive.core.check_cell); the fat parts
                        # are dropped again at the round's commit
                        # (_stash_rederive) once the op certified
                        auth["cell"] = m["cell_ev"]
                        auth.setdefault("blob", blob.hex())
                        self._rederive_cell_auth.append(
                            self.ledger.log_size() - 1)
                    self._op_auth[self.ledger.log_size() - 1] = auth
                elif st == LedgerStatus.DUPLICATE:
                    # an honest retry (e.g. across a writer failover) whose
                    # original reply was lost: the record is in the ledger —
                    # re-accept the verified payload if the promoted writer
                    # never mirrored it (comm.failover known window)
                    self._resupply_blob(digest, blob)
                self._touch(addr)
                self._note_progress(st)
                return {"ok": st == LedgerStatus.OK, "status": st.name}
            if method == "updates":
                ups = self.ledger.query_all_updates()
                return {"ok": True, "updates": [
                    {"sender": u.sender, "hash": u.payload_hash.hex(),
                     "n": u.n_samples, "cost": u.avg_cost} for u in ups]}
            if method == "aupload":
                return self._dispatch_aupload(m)
            if method == "aupdates":
                # the async committee's scoring surface: every buffered
                # candidate with its admission id + staleness tag
                if not self._async:
                    return {"ok": False, "status": "BAD_ARG",
                            "error": "async mode is off"}
                return {"ok": True, "epoch": self.ledger.epoch,
                        "updates": [
                            {"aseq": e.aseq, "sender": e.sender,
                             "hash": e.payload_hash.hex(),
                             "n": e.n_samples, "cost": e.avg_cost,
                             "staleness": e.staleness}
                            for e in self.ledger.async_buffer_view()]}
            if method == "ascores":
                return self._dispatch_ascores(m)
            if method == "scores":
                if self._async:
                    return {"ok": False, "status": "BAD_ARG",
                            "error": "async mode is on: use ascores"}
                addr = m["addr"]
                scores = [float(s) for s in m["scores"]]
                payload = struct.pack(f"<{len(scores)}d", *scores)
                v = self._verify("scores", addr, int(m["epoch"]), payload,
                                 m.get("tag", ""))
                if v != LedgerStatus.OK:
                    return {"ok": False, "status": v.name,
                            "error": "bad signature" if
                            v == LedgerStatus.BAD_ARG else "replayed tag"}
                if not self._charge_gas(addr, GAS_SCORES):
                    return dict(self._OUT_OF_GAS)
                st = self.ledger.upload_scores(addr, int(m["epoch"]), scores)
                if st == LedgerStatus.OK:
                    self._consume_tag(int(m["epoch"]), m.get("tag", ""))
                    self._op_auth[self.ledger.log_size() - 1] = {
                        "tag": m.get("tag", ""), "scores": scores,
                        "pubkey": self._sender_pubkey_hex(addr)}
                self._touch(addr)
                self._note_progress(st)
                if st == LedgerStatus.OK and self.ledger.aggregate_ready():
                    self._aggregate_and_commit()
                return {"ok": st == LedgerStatus.OK, "status": st.name}
            if method == "committee":
                return {"ok": True, "committee": self.ledger.committee()}
            if method == "directory":
                # enrolled public keys (public data; addresses are
                # self-authenticating) — the standby-mirroring surface
                return {"ok": True, "keys": {
                    a: p.hex()
                    for a, p in self.directory.export_raw().items()}}
            if method == "info":
                reply = {"ok": True, "epoch": self.ledger.epoch,
                         "num_registered": self.ledger.num_registered,
                         "update_count": self.ledger.update_count,
                         "score_count": self.ledger.score_count,
                         "round_closed": self.ledger.round_closed,
                         "last_global_loss": self.ledger.last_global_loss,
                         "rounds_completed": self._rounds_completed,
                         "log_size": self.ledger.log_size(),
                         "log_head": self.ledger.log_head().hex(),
                         "gen": self.ledger.generation,
                         "writer_index": self.ledger.writer_index,
                         "log_base": getattr(self.ledger, "log_base", 0),
                         "certified_size": (self._certified_size
                                            if self._bft is not None
                                            else None)}
                if self._async:
                    reply["async_buffer_depth"] = \
                        self.ledger.async_buffer_depth
                if self._adapt:
                    reply["eff_density"] = \
                        float(self.ledger.effective_density)
                    reply["eff_staleness"] = \
                        int(self.ledger.effective_staleness)
                    ge = self.ledger.genome_epoch
                    reply["genome_epoch"] = (-1 if ge is None
                                             else int(ge))
                reply["committee"] = self.ledger.committee()
                snap = self._snapshot_offer()
                if snap is not None:
                    reply["snapshot_epoch"] = snap["epoch"]
                    reply["snapshot_i"] = snap["i"]
                if tracing.PROC.enabled:
                    # the federation benchmark's attribution surface: the
                    # sponsor reads the writer's own phase accounting
                    # (wire / crypto / validate / aggregate) off the last
                    # info poll instead of guessing from wall time
                    reply["perf"] = tracing.PROC.summary()
                return reply
            if method == "log_range":
                start, end = int(m["start"]), int(m["end"])
                size = self.ledger.log_size()
                base = getattr(self.ledger, "log_base", 0)
                end = min(end, size)
                if start < base:
                    # the requested prefix was GC'd behind a certified
                    # snapshot: the caller must state-sync (`snapshot`
                    # RPC) instead of replaying it
                    return {"ok": False, "error": "PREFIX_GC",
                            "base": base}
                if not (0 <= start <= end):
                    return {"ok": False, "error": "bad range"}
                return {"ok": True, "ops": [self.ledger.log_op(i).hex()
                                            for i in range(start, end)]}
            if method == "snapshot":
                # the state-sync serving surface (ledger.snapshot): the
                # newest finalized checkpoint — op + certificate +
                # chain position + canonical state + model blob, every
                # part verifiable by the joiner before install.  With
                # meta=1 only the bindings (op, prev_head, cert) plus
                # the advertised read set ship: the joiner then pulls
                # the fat state/model bytes from a read-fan-out replica
                # (comm.dataplane) and this accept loop serves one tiny
                # frame instead of the fattest reply on the plane.
                from bflc_demo_tpu.ledger.snapshot import offer_to_wire
                snap = self._snapshot_offer()
                if snap is None:
                    return {"ok": False,
                            "error": "no certified snapshot yet"}
                reply = offer_to_wire(snap)
                rs = self._read_set()
                if rs:
                    reply["read_set"] = [list(ep) for ep in rs]
                if m.get("meta"):
                    reply.pop("state")
                    reply.pop("model")
                return reply
            if method == "telemetry":
                # the FleetCollector scrape surface (obs.collector):
                # instantaneous state gauges are sampled HERE so a scrape
                # is always current, then the whole registry snapshot
                # (which also carries the tracer's cost categories) rides
                # back in one reply.  Served even when the registry is
                # disabled — the reply then says so instead of timing out
                # (the collector reports it as answered-but-dark).
                if obs_metrics.REGISTRY.enabled:
                    _G_ROUND.set(self.ledger.epoch)
                    _G_BACKLOG.set(self.ledger.log_size()
                                   - (self._certified_size
                                      if self._bft is not None
                                      else self.ledger.log_size()))
                    _G_SUBS.set(len(self._sub_acked))
                    _G_LOG_BASE.set(getattr(self.ledger, "log_base", 0))
                    _G_DENSITY.set(self._effective_density()
                                   if self._sparse else 1.0)
                    if self._adapt:
                        _G_EFF_STALENESS.set(
                            self.ledger.effective_staleness)
                        ge = self.ledger.genome_epoch
                        _G_GENOME_EPOCH.set(-1 if ge is None else ge)
                    if self._async:
                        _G_ABUF_DEPTH.set(
                            self.ledger.async_buffer_depth)
                    _G_COMM_SIZE.set(len(self.ledger.committee()))
                    snap = self._snapshot_offer()
                    _G_SNAP_AGE.set(self.ledger.epoch - snap["epoch"]
                                    if snap is not None else -1)
                    # device-plane memory watermark sampled at scrape
                    # time like the other instantaneous gauges — every
                    # per-round scrape then carries a CURRENT watermark
                    # and appends one device_mem record (obs.device;
                    # inert under BFLC_DEVICE_OBS=0)
                    try:
                        obs_device.sample_memory(reason="scrape")
                    except Exception:   # noqa: BLE001 — observability
                        pass
                # `epoch` stamps the writer's authoritative round
                # position into every scrape record (obs.collector):
                # health/flight records already carry their epoch but
                # periodic scrapes were wall-clock-only, forcing the
                # forensics joiner (obs.timeline) to infer round
                # membership from timestamps
                return {"ok": True,
                        "role": obs_metrics.REGISTRY.role or "writer",
                        "epoch": self.ledger.epoch,
                        "snapshot": obs_metrics.REGISTRY.snapshot()}
            if method == "wait":
                # event-driven poll: block until the log grows past the
                # caller's view (or timeout) — replaces the reference's
                # uniform(10,30)s sleep loop (main.py:231-233)
                known = int(m["log_size"])
                deadline = time.monotonic() + min(float(
                    m.get("timeout_s", 5.0)), 60.0)
                while (self.ledger.log_size() == known
                       and not self._stop.is_set()):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
                return {"ok": True, "log_size": self.ledger.log_size()}
            return {"ok": False, "error": f"unknown method {method!r}"}

    # --------------------------------------- async buffered aggregation
    def _seen_in_window(self, tag: bytes) -> bool:
        """Replay check across the staleness window: async score tags
        are bucketed by the ledger epoch AT ADMISSION (their signed
        payload carries no epoch), so a replayed tag can only hide by
        claiming a different bucket — scan the whole live window."""
        ep = self.ledger.epoch
        return any(self._replay.seen(e, tag)
                   for e in range(max(ep - self.cfg.max_staleness, 0),
                                  ep + 1))

    def _dispatch_aupload(self, m: dict) -> dict:
        """Admit a staleness-tagged delta into the async buffer — no
        epoch gate: the op carries the BASE epoch the client trained
        from, admission stamps s = epoch_now - base_epoch (capped at
        cfg.max_staleness), and the K-th admission triggers a buffered
        aggregation.  Mirrors the sync upload path's auth/gas/schema
        order exactly."""
        if not self._async:
            return {"ok": False, "status": "BAD_ARG",
                    "error": "async mode is off (--async-buffer 0 or "
                             "BFLC_ASYNC_LEGACY=1)"}
        addr = m["addr"]
        base_epoch = int(m["base_epoch"])
        blob = blob_bytes(m["blob"])
        digest = hashlib.sha256(blob).digest()
        if digest.hex() != m["hash"]:
            return {"ok": False, "status": "BAD_ARG",
                    "error": "blob/hash mismatch"}
        payload = digest + struct.pack("<qd", int(m["n"]),
                                       float(m["cost"]))
        v = self._verify("aupload", addr, base_epoch, payload,
                         m.get("tag", ""))
        if v != LedgerStatus.OK:
            if v == LedgerStatus.DUPLICATE:
                self._resupply_async_blob(digest, blob)
            return {"ok": False, "status": v.name,
                    "error": "bad signature" if
                    v == LedgerStatus.BAD_ARG else "replayed tag"}
        if not self._charge_gas(addr, GAS_UPLOAD_BASE + len(blob)):
            return dict(self._OUT_OF_GAS)
        err, aggflat = self._decode_delta(blob)
        if err:
            return {"ok": False, "status": "BAD_ARG", "error": err}
        st = self.ledger.async_upload(addr, digest, int(m["n"]),
                                      float(m["cost"]), base_epoch)
        if st == LedgerStatus.OK:
            self._blobs[digest] = blob
            self._stage_delta(digest, aggflat)
            if self.require_auth:
                # prune floor = epoch - max_staleness: a tag bucket must
                # outlive every base epoch the staleness cap still
                # admits, or a pruned-then-replayed op would re-enter
                # the buffer as a fresh delta
                self._replay.consume(
                    self.ledger.epoch - self.cfg.max_staleness,
                    base_epoch, bytes.fromhex(m.get("tag", "")))
            auth = {"tag": m.get("tag", ""), "n": int(m["n"]),
                    "cost": float(m["cost"]),
                    "pubkey": self._sender_pubkey_hex(addr)}
            if self._sparse:
                # async opcode-10 carries sparse blobs through the
                # FedBuff drain too: same validator re-execution
                # evidence as the sync path
                auth["blob"] = blob.hex()
            self._op_auth[self.ledger.log_size() - 1] = auth
            if obs_metrics.REGISTRY.enabled:
                _M_ASTALENESS.observe(
                    self.ledger.epoch - base_epoch)
        elif st == LedgerStatus.DUPLICATE:
            self._resupply_async_blob(digest, blob)
        self._touch(addr)
        self._note_progress(st)
        reply = {"ok": st == LedgerStatus.OK, "status": st.name,
                 "epoch": self.ledger.epoch}
        if st == LedgerStatus.OK and \
                self.ledger.async_buffer_depth >= self.cfg.async_buffer:
            # the K-th admission: aggregate INSIDE the request (lock
            # held) so the committed epoch rides this ack and the
            # trigger is deterministic in the op order
            self._async_aggregate_and_commit()
            reply["epoch"] = self.ledger.epoch
        return reply

    def _dispatch_ascores(self, m: dict) -> dict:
        """Committee scores over buffered candidates — (aseq, score)
        pairs, no epoch gate on submit (the admission id IS the
        binding; pairs for entries already drained are skipped
        deterministically by the ledger)."""
        if not self._async:
            return {"ok": False, "status": "BAD_ARG",
                    "error": "async mode is off"}
        addr = m["addr"]
        try:
            pairs = [(int(a), float(s)) for a, s in m["pairs"]]
        except (TypeError, ValueError):
            return {"ok": False, "status": "BAD_ARG",
                    "error": "malformed pairs"}
        from bflc_demo_tpu.ledger.base import ascores_sign_payload
        payload = ascores_sign_payload(pairs)
        if self.require_auth:
            tag = bytes.fromhex(m.get("tag", ""))
            if not self.directory.verify(
                    addr, _op_bytes("ascores", addr, 0, payload), tag):
                return {"ok": False, "status": "BAD_ARG",
                        "error": "bad signature"}
            if self._seen_in_window(tag):
                return {"ok": False, "status": "DUPLICATE",
                        "error": "replayed tag"}
        if not self._charge_gas(addr, GAS_SCORES):
            return dict(self._OUT_OF_GAS)
        st = self.ledger.async_scores(addr, pairs)
        if st == LedgerStatus.OK:
            if self.require_auth:
                self._replay.consume(
                    self.ledger.epoch - self.cfg.max_staleness,
                    self.ledger.epoch, bytes.fromhex(m.get("tag", "")))
            self._op_auth[self.ledger.log_size() - 1] = {
                "tag": m.get("tag", ""),
                "pairs": [[a, s] for a, s in pairs],
                "pubkey": self._sender_pubkey_hex(addr)}
        self._touch(addr)
        self._note_progress(st)
        return {"ok": st == LedgerStatus.OK, "status": st.name,
                "epoch": self.ledger.epoch}

    def _resupply_async_blob(self, digest: bytes, blob: bytes) -> None:
        """Async twin of _resupply_blob: re-accept a hash-verified
        payload for a BUFFERED entry this writer lacks the blob for
        (promoted-standby window)."""
        if digest in self._blobs:
            return
        if any(e.payload_hash == digest
               for e in self.ledger.async_buffer_view()):
            self._blobs[digest] = blob

    def _async_aggregate_and_commit(self) -> None:
        """Drain the oldest k buffered entries with staleness-discounted
        weights (FedBuff: n_samples / sqrt(1 + s)) and commit — the
        async analogue of _aggregate_and_commit, caller holds the
        lock."""
        k = min(self.ledger.async_buffer_depth, self.cfg.async_buffer)
        if k <= 0:
            return
        t0 = time.perf_counter() if tracing.PROC.enabled else 0.0
        with obs_trace.TRACE.span("aggregate", epoch=self.ledger.epoch,
                                  mode="async"):
            entries, selected, weights, _ = \
                self.ledger.async_selection(k)
            epoch = self.ledger.epoch
            global_flat = unpack_pytree(self._model_blob)
            rows = delta_flats = None
            # health capture BEFORE the drain drops the score map
            # (obs.health — observability only)
            health_scores = (self._async_candidate_scores(entries)
                             if obs_health.health_armed() else None)
            from bflc_demo_tpu.ledger.base import reduce_blocks
            from bflc_demo_tpu.meshagg.engine import ENGINE
            blocks = reduce_blocks(self.cfg)
            if ENGINE.choose_leg(len(entries)) == "mesh":
                # meshagg drain: the FedBuff n/sqrt(1+s) weights enter
                # as spec coefficients; same one-program reduction as
                # the sync merge, byte-identical to the host loop
                rows = [self._staged_row(e.payload_hash)
                        for e in entries]
                new_flat = ENGINE.aggregate_rows(
                    global_flat, rows, weights, list(selected),
                    self.cfg.learning_rate, blocks=blocks)
            else:
                delta_flats = [dequantize_entries(
                                   unpack_pytree(
                                       self._blobs[e.payload_hash]))
                               for e in entries]
                if self._sparse:
                    delta_flats = [densify_entries(f)
                                   for f in delta_flats]
                new_flat = _aggregate_flat(global_flat, delta_flats,
                                           weights, list(selected),
                                           self.cfg.learning_rate,
                                           blocks=blocks)
            blob = pack_entries(new_flat)
            digest = hashlib.sha256(blob).digest()
            # capture reseat due-ness BEFORE the commit advances the
            # drain counter (the ledger derives + embeds the seating
            # itself; this is observability only)
            reseat_due = self.ledger.async_reseat_due() \
                if hasattr(self.ledger, "async_reseat_due") else False
            old_seats = self.ledger.committee() if reseat_due else None
            st = self.ledger.async_commit(digest, epoch, k)
            if st != LedgerStatus.OK:
                raise RuntimeError(f"async commit rejected: {st.name}")
            self._propose_genome_if_due(global_flat, new_flat, epoch)
            if self._rederive:
                self._stash_rederive(
                    blob, {e.payload_hash: self._blobs[e.payload_hash]
                           for e in entries
                           if e.payload_hash in self._blobs})
            for e in entries:
                self._blobs.pop(e.payload_hash, None)
                self._staged.pop(e.payload_hash, None)
            self._model_blob = blob
            self._model_hash = digest
            self._model_schema = {key: (a.shape, a.dtype)
                                  for key, a in new_flat.items()}
            self._rounds_completed += 1
            self._last_progress = time.monotonic()
            if self._snap_interval and \
                    self.ledger.epoch % self._snap_interval == 0:
                self._emit_snapshot()
            self._cv.notify_all()
        if tracing.PROC.enabled:
            tracing.PROC.charge("aggregate_s",
                                time.perf_counter() - t0)
        if obs_metrics.REGISTRY.enabled:
            _M_AAGG.inc()
        if obs_health.health_armed():
            self._health_round(
                epoch=epoch, senders=[e.sender for e in entries],
                rows=rows, delta_flats=delta_flats,
                weights=weights, selected=list(selected),
                medians=None, candidate_scores=health_scores,
                staleness=[e.staleness for e in entries],
                old_flat=global_flat, new_flat=new_flat, mode="async")
        obs_flight.FLIGHT.record(
            "event", "async_round_committed", epoch=epoch, drained=k,
            max_staleness=max((e.staleness for e in entries),
                              default=0),
            loss=float(self.ledger.last_global_loss))
        if reseat_due:
            new_seats = self.ledger.committee()
            obs_flight.FLIGHT.record(
                "event", "committee_reseat", epoch=epoch,
                seats=list(new_seats),
                changed=sorted(set(new_seats)
                               - set(old_seats or [])))
            if obs_metrics.REGISTRY.enabled:
                _M_RESEAT.inc()
            if self.verbose:
                print(f"[coordinator] epoch {epoch} committee reseat: "
                      f"{old_seats} -> {new_seats}", flush=True)
        if self.verbose:
            print(f"[coordinator] epoch {epoch} async-aggregated "
                  f"({k} deltas, stalest "
                  f"{max((e.staleness for e in entries), default=0)}): "
                  f"loss={self.ledger.last_global_loss:.5f}",
                  flush=True)

    def _sender_pubkey_hex(self, addr: str) -> str:
        """The sender's enrolled public key (hex, '' when unknown) — the
        self-authenticating directory-repair evidence validators use
        (comm.bft.check_op_auth _tofu_repair)."""
        pub = self.directory.export_raw().get(addr)
        return pub.hex() if pub is not None else ""

    def _resupply_blob(self, digest: bytes, blob: bytes) -> None:
        """Store a hash-verified payload for an update the LEDGER already
        records but whose blob this writer lacks (a promoted standby inside
        the one-op mirroring window — comm.failover module docstring)."""
        if digest in self._blobs:
            return
        if any(u.payload_hash == digest
               for u in self.ledger.query_all_updates()):
            self._blobs[digest] = blob

    def _decode_delta(self, blob: bytes):
        """(reason, decoded flat entries or None): '' reason iff the
        delta blob's flat entries mirror the current global model's
        keys, shapes, AND dtypes.  Dtype equality matters as much as
        shape: a string-typed leaf with the right geometry would
        otherwise defer the failure to the float32 cast inside
        aggregation.

        With quantized deltas enabled (cfg.delta_dtype != "f32",
        opt-in) the check runs over the DEQUANTIZED image — the same
        deterministic decode scorers and the aggregator apply — so the
        admitted structure is exactly what aggregation will walk; with
        quantization off the strict check is unchanged (reduced-
        precision blobs are rejected at the door).  With sparse deltas
        armed (cfg.delta_density < 1) the image additionally runs
        through the ONE `densify_entries` inverse — a malformed #topk
        record (out-of-bounds/duplicate/unsorted indices) raises
        ValueError here and dies as a schema error, never a crash;
        with density 1.0 a #topk entry is rejected by the strict key
        check.  The decoded image is returned so admission can STAGE
        it for the meshagg aggregate instead of throwing the work away
        and re-decoding at commit."""
        try:
            t0 = (time.perf_counter()
                  if self._sparse and obs_metrics.REGISTRY.enabled
                  else 0.0)
            delta = unpack_pytree(blob)
            if self.cfg.delta_dtype != "f32":
                delta = dequantize_entries(delta)
            if self._sparse:
                delta = densify_entries(delta)
                if t0:
                    _M_SPARSE_DECODE.observe(time.perf_counter() - t0)
        except (ValueError, TypeError, struct.error) as e:
            return f"undecodable delta blob: {e}", None
        err = self._schema_error(delta)
        return err, (None if err else delta)

    def _stage_delta(self, digest: bytes,
                     flat: Optional[Dict[str, np.ndarray]]) -> None:
        """Remember an ADMITTED delta's flattened row for the mesh-leg
        aggregate (meshagg).  Best-effort: staging nothing just means
        the aggregate re-derives the row from the stored blob — and a
        geometry the compiled leg can never serve (small rounds, the
        legacy pin) stages nothing at all, keeping the flatten copy
        off the admission path."""
        if flat is None:
            return
        from bflc_demo_tpu.meshagg.engine import ENGINE, flatten_delta
        if not ENGINE.staging_worthwhile(
                max(self.cfg.needed_update_count, self.cfg.async_buffer)):
            return
        self._staged[digest] = flatten_delta(flat, sorted(flat.keys()))

    def _staged_row(self, digest: bytes) -> np.ndarray:
        """The staged row for an admitted payload, re-derived from the
        blob when staging missed (resumed/promoted writer)."""
        row = self._staged.pop(digest, None)
        if row is not None:
            return row
        from bflc_demo_tpu.hier.partial import split_cellmeta
        from bflc_demo_tpu.meshagg.engine import flatten_delta
        flat = dequantize_entries(unpack_pytree(self._blobs[digest]))
        if self._sparse:
            flat = densify_entries(flat)
        if self._cell_registry is not None:
            flat = split_cellmeta(flat)[0]
        return flatten_delta(flat, sorted(flat.keys()))

    def _decode_cell_partial(self, addr: str, blob: bytes,
                             claimed_n: int):
        """(reason, stripped partial entries or None): '' reason iff a
        cell-aggregate upload honors the cell contract (hier root
        mode) — the sender is a REGISTERED cell aggregator, the blob
        carries a well-formed #cellmeta evidence entry whose cell
        index matches the sender's registered cell (a lying aggregator
        cannot attribute its partial to another cell), whose claimed
        client count matches the op's `n` weight field, that count
        does not exceed the sender's registered membership (it cannot
        inflate its FedAvg weight either), and the partial's tensor
        entries mirror the model schema.  The #cellmeta-stripped
        partial is returned so root admission can stage it for the
        meshagg aggregate (the evidence entry rode the certified hash
        but is not a model tensor).  With sparse deltas armed the cell
        aggregator RE-SPARSIFIES its partial for the bridge hop
        (hier.partial.partial_blob): the same densify inverse decodes
        it here, before the #cellmeta split."""
        from bflc_demo_tpu.hier.partial import split_cellmeta
        ent = self._cell_registry.get(addr)
        if ent is None:
            return (f"sender {addr[:12]} is not a registered cell "
                    f"aggregator"), None
        reg_index, cap = ent
        try:
            flat = unpack_pytree(blob)
            if self._sparse:
                flat = densify_entries(flat)
            partial, meta = split_cellmeta(flat)
        except (ValueError, TypeError, struct.error) as e:
            return f"undecodable cell partial: {e}", None
        if meta is None:
            return "cell partial without a #cellmeta evidence entry", \
                None
        cell_index, n_clients, _evidence = meta
        if cell_index != reg_index:
            return (f"#cellmeta cell index {cell_index} != registered "
                    f"cell {reg_index} for sender {addr[:12]}"), None
        if n_clients != claimed_n:
            return (f"#cellmeta client count {n_clients} != op weight "
                    f"{claimed_n}"), None
        if not 0 < n_clients <= cap:
            return (f"claimed client count {n_clients} exceeds "
                    f"registered membership {cap}"), None
        err = self._schema_error(partial)
        return err, (None if err else partial)

    def _schema_error(self, delta: Dict[str, np.ndarray]) -> str:
        """'' iff flat entries mirror the current model's keys, shapes
        AND dtypes (shared by single-tier and cell admission)."""
        schema = self._model_schema
        if delta.keys() != schema.keys():
            missing = sorted(schema.keys() - delta.keys())[:3]
            extra = sorted(delta.keys() - schema.keys())[:3]
            return (f"delta structure mismatch (missing={missing}, "
                    f"extra={extra})")
        for key, arr in delta.items():
            want_shape, want_dtype = schema[key]
            if arr.shape != want_shape:
                return (f"delta leaf {key}: shape {arr.shape} != "
                        f"{want_shape}")
            if arr.dtype != want_dtype:
                return (f"delta leaf {key}: dtype {arr.dtype} != "
                        f"{want_dtype}")
        return ""

    def _note_progress(self, st: LedgerStatus) -> None:
        if st == LedgerStatus.OK:
            self._last_progress = time.monotonic()
            self._cv.notify_all()

    # ----------------------------------------- model-quality health plane
    def _sync_candidate_scores(self, k: int):
        """Per-candidate committee score columns ([[scores of slot 0],
        ...]) from the ledger's score rows (PyLedger
        `committee_score_rows`, a read-only observability surface) —
        the health plane's disagreement input.  None when the backend
        serves no rows (the native ledger) or none are complete."""
        rows_fn = getattr(self.ledger, "committee_score_rows", None)
        if rows_fn is None:
            return None
        good = rows_fn()
        if not good or any(len(r) != k for r in good):
            return None
        return [[float(r[i]) for r in good] for i in range(k)]

    def _async_candidate_scores(self, entries):
        """Async twin: committee scores per buffered entry, keyed off
        the admission id (drained entries lose their score maps —
        capture before the drain; PyLedger `async_score_rows`)."""
        rows_fn = getattr(self.ledger, "async_score_rows", None)
        if rows_fn is None:
            return None
        return rows_fn([e.aseq for e in entries])

    def _health_round(self, *, epoch, senders, rows, delta_flats,
                      weights, selected, medians, candidate_scores,
                      old_flat=None, new_flat=None, staleness=None,
                      mode="sync") -> None:
        """Feed one COMMITTED round to the health plane (obs.health):
        per-delta stats over the staged/decoded rows, convergence
        telemetry, and the streaming anomaly verdict.  Observability
        only — any failure in here is swallowed (a health bug must
        never kill a commit), and nothing it computes feeds back into
        admission or the certified bytes."""
        try:
            from bflc_demo_tpu.meshagg.engine import (_leaf_layout,
                                                      flatten_delta)
            keys = sorted(new_flat.keys())
            if rows is None:
                rows = [flatten_delta(f, keys)
                        for f in (delta_flats or [])]
            # row leaf map for the opt-in per-leaf WHERE refinement
            # (BFLC_HEALTH_PER_LEAF=1): metadata only, built per round
            # so a schema change never feeds a stale layout
            layout, _ = _leaf_layout(keys, new_flat)
            if self._health is None:
                # the protocol density feeds the monitor: honest
                # sparse deltas legitimately drive zero_frac to
                # ~1-density and must not trip the free-rider rule.
                # density 1.0 (rule off) when quantization composes:
                # i8 can zero an honest survivor set outright
                # (HealthMonitor docstring)
                self._health = obs_health.HealthMonitor(
                    role=obs_metrics.REGISTRY.role or "writer",
                    density=(self.cfg.delta_density
                             if self._sparse
                             and self.cfg.delta_dtype == "f32"
                             else 1.0))
            self._health.on_round(
                epoch=epoch, senders=list(senders), rows=rows,
                weights=[float(w) for w in weights],
                selected=list(selected), medians=medians,
                candidate_scores=candidate_scores,
                staleness=staleness,
                old_row=(flatten_delta(old_flat, keys)
                         if old_flat is not None else None),
                new_row=flatten_delta(new_flat, keys),
                leaf_layout=layout, mode=mode)
        except Exception as e:      # noqa: BLE001 — observability only
            if self.verbose:
                print(f"[coordinator] health plane error: "
                      f"{type(e).__name__}: {e}", flush=True)

    # ---------------------------------------------------- coordinator logic
    def _aggregate_and_commit(self) -> None:
        """On-coordinator aggregation — the reference's on-chain Aggregate
        (.cpp:349-456): weighted-FedAvg the ledger-selected deltas into the
        global model, commit the new model's content hash, publish blob."""
        t0 = time.perf_counter() if tracing.PROC.enabled else 0.0
        with obs_trace.TRACE.span("aggregate",
                                  epoch=self.ledger.epoch):
            self._aggregate_and_commit_inner(t0)

    def _effective_density(self) -> float:
        """The delta density in force THIS epoch: the ledger's
        effective knob when the adaptive loop is armed, the static
        genome value otherwise."""
        if self._adapt:
            return float(self.ledger.effective_density)
        return float(self.cfg.delta_density)

    def _state_knobs(self) -> dict:
        """Effective-knob section of a `state` reply: the knobs every
        honest encoder must use THIS epoch (certified chain state —
        ledger.OP_GENOME).  Clients override their genome density with
        these; the hier cell tier overrides this hook to mirror the
        ROOT's knobs downstream to its members."""
        if not self._adapt:
            return {}
        return {"eff_density": float(self.ledger.effective_density),
                "eff_staleness": int(self.ledger.effective_staleness)}

    def _propose_genome_if_due(self, old_flat, new_flat,
                               commit_epoch: int) -> None:
        """Closed-loop knob retuning at the round boundary (lock held,
        called immediately after a successful commit — no RPC can
        observe the new epoch before the knob transition lands, so the
        effective knobs are constant within every round at every chain
        position).  The ledger's propose_genome runs the exact guard
        chain every replica will re-run; a refusal here is surfaced,
        never wedged."""
        if not self._adapt or not self.ledger.genome_due():
            return
        from bflc_demo_tpu.control.loop import model_telemetry
        norm, drift = model_telemetry(old_flat, new_flat)
        old_d = float(self.ledger.effective_density)
        old_s = int(self.ledger.effective_staleness)
        disag = float(self.ledger.last_disagreement)
        st = self.ledger.propose_genome(float(norm), float(drift))
        if st != LedgerStatus.OK:
            if self.verbose:
                print(f"[coordinator] genome update refused: {st.name}",
                      flush=True)
            return
        self._cv.notify_all()
        _M_GENOME.inc()
        obs_flight.FLIGHT.record(
            "event", "genome_update", epoch=self.ledger.epoch,
            commit_epoch=commit_epoch,
            old_density=old_d,
            new_density=float(self.ledger.effective_density),
            old_staleness=old_s,
            new_staleness=int(self.ledger.effective_staleness),
            update_norm=float(norm), drift=float(drift),
            disagreement=disag)
        if self.verbose:
            print(f"[coordinator] epoch {self.ledger.epoch} genome "
                  f"update: density {old_d:g} -> "
                  f"{self.ledger.effective_density:g}, staleness "
                  f"{old_s} -> {self.ledger.effective_staleness} "
                  f"(norm={norm:g} drift={drift:g} disag={disag:g})",
                  flush=True)

    def _aggregate_and_commit_inner(self, t0: float) -> None:
        from bflc_demo_tpu.meshagg.engine import ENGINE
        pending = self.ledger.pending()
        updates = self.ledger.query_all_updates()
        epoch = self.ledger.epoch
        global_flat = unpack_pytree(self._model_blob)
        rows = delta_flats = None
        # health capture BEFORE the commit clears the score rows
        # (obs.health — two attribute checks when dark)
        health_scores = (self._sync_candidate_scores(len(updates))
                         if obs_health.health_armed() else None)
        from bflc_demo_tpu.ledger.base import reduce_blocks
        blocks = reduce_blocks(self.cfg)
        if ENGINE.choose_leg(len(updates)) == "mesh":
            # meshagg: the admitted deltas were staged as flattened
            # rows at admission — the merge is one stack + one compiled
            # program per genome block (REDUCTION SPEC v1/v2,
            # byte-identical to the host loop below; a missing row is
            # re-derived from its blob)
            rows = [self._staged_row(u.payload_hash) for u in updates]
            new_flat = ENGINE.aggregate_rows(
                global_flat, rows, [u.n_samples for u in updates],
                list(pending.selected), self.cfg.learning_rate,
                blocks=blocks)
        else:
            # host loop: densify ∘ dequantize is the ONE shared decode
            # chain (utils.serialization): an identity on plain f32
            # blobs, the deterministic inverse for opt-in f16/i8 and
            # sparse uploads — scorer, aggregator and re-validators
            # therefore agree on every delta's numeric meaning
            delta_flats = [dequantize_entries(
                               unpack_pytree(self._blobs[u.payload_hash]))
                           for u in updates]
            if self._sparse:
                delta_flats = [densify_entries(f) for f in delta_flats]
            if self._cell_registry is not None:
                # hier root: each "delta" is a cell partial whose
                # reserved #cellmeta evidence entry rode the certified
                # hash but is not a model tensor; strip it before the
                # weighted merge (the weights — u.n_samples — are the
                # admitted CLIENT counts the admission check bounded
                # against the registry)
                from bflc_demo_tpu.hier.partial import split_cellmeta
                delta_flats = [split_cellmeta(f)[0] for f in delta_flats]
            new_flat = _aggregate_flat(global_flat, delta_flats,
                                       [u.n_samples for u in updates],
                                       list(pending.selected),
                                       self.cfg.learning_rate,
                                       blocks=blocks)
        blob = pack_entries(new_flat)
        digest = hashlib.sha256(blob).digest()
        st = self.ledger.commit_model(digest, epoch)
        if st != LedgerStatus.OK:
            raise RuntimeError(f"commit rejected: {st.name}")
        self._propose_genome_if_due(global_flat, new_flat, epoch)
        if self._rederive:
            self._stash_rederive(
                blob, {u.payload_hash: self._blobs[u.payload_hash]
                       for u in updates if u.payload_hash in self._blobs})
        for u in updates:
            self._blobs.pop(u.payload_hash, None)
            self._staged.pop(u.payload_hash, None)
        self._model_blob = blob
        self._model_hash = digest
        self._model_schema = {k: (a.shape, a.dtype)
                              for k, a in new_flat.items()}
        self._rounds_completed += 1
        self._last_progress = time.monotonic()
        if self._snap_interval and \
                self.ledger.epoch % self._snap_interval == 0:
            self._emit_snapshot()
        self._cv.notify_all()
        if tracing.PROC.enabled:
            tracing.PROC.charge("aggregate_s", time.perf_counter() - t0)
        obs_flight.FLIGHT.record(
            "event", "round_committed", epoch=epoch,
            loss=float(self.ledger.last_global_loss))
        if obs_health.health_armed():
            self._health_round(
                epoch=epoch, senders=[u.sender for u in updates],
                rows=rows, delta_flats=delta_flats,
                weights=[u.n_samples for u in updates],
                selected=list(pending.selected),
                medians=pending.medians,
                candidate_scores=health_scores,
                old_flat=global_flat, new_flat=new_flat, mode="sync")
        if self.verbose:
            print(f"[coordinator] epoch {epoch} aggregated: "
                  f"loss={self.ledger.last_global_loss:.5f}", flush=True)

    def _emit_snapshot(self) -> None:
        """Append a snapshot op over the CURRENT (post-commit) state and
        stage the artifact (lock held — called from the commit path).
        Certification rides the normal machinery: the op sits in the
        uncertified backlog like any other, and finalization (artifact
        write + prefix GC) happens in the monitor loop once its
        certificate exists — never before, or a joiner could install a
        checkpoint no quorum re-derived."""
        from bflc_demo_tpu.ledger.snapshot import make_snapshot_op
        state = self.ledger.encode_state()
        pos = self.ledger.log_size()
        prev = self.ledger.log_head() if pos else b"\0" * 32
        op = make_snapshot_op(self.ledger)
        st = self.ledger.apply_op(op)
        if st != LedgerStatus.OK:       # self-application re-derives the
            # digest it just computed — only a concurrent-mutation bug
            # could trip this; surface it, don't wedge the commit
            if self.verbose:
                print(f"[coordinator] snapshot op rejected: {st.name}",
                      flush=True)
            return
        self._latest_snapshot = {
            "i": pos, "epoch": self.ledger.epoch,
            "gen": self.ledger.generation, "op": op, "prev_head": prev,
            "cert": None, "state": state, "model": self._model_blob,
            "final": False}
        obs_flight.FLIGHT.record("event", "snapshot_emitted",
                                 position=pos, epoch=self.ledger.epoch)

    def _maybe_finalize_snapshot(self) -> None:
        """Monitor-loop tail of emission: once the snapshot op is
        CERTIFIED, persist the artifact (tmp-then-rename + retention
        prune) and GC the log/WAL prefix behind it — clamped to the
        slowest live subscriber so an active stream never loses the ops
        it is mid-push on (a DEAD subscriber holds nothing back: its
        rejoin is exactly the state-sync path)."""
        meta = self._latest_snapshot
        if meta is None or meta.get("final"):
            return
        i = int(meta["i"])
        if self._bft is not None:
            cert = self._certs.get(i)
            if cert is None:
                return                  # not certified yet: wait
            meta["cert"] = cert
        self._served_snapshot = meta
        if not meta.get("artifact_written"):
            # artifact persistence (an fsync of state + FULL model
            # bytes) runs OUTSIDE the dispatch lock: the meta is
            # immutable byte snapshots, only the monitor loop calls
            # here, and a multi-MB disk sync must not stall every
            # client RPC at the snapshot boundary
            if self._snap_dir:
                from bflc_demo_tpu.ledger.snapshot import (
                    prune_snapshots, write_snapshot_file)
                try:
                    write_snapshot_file(self._snap_dir, meta)
                    prune_snapshots(self._snap_dir, self._snap_keep)
                except OSError as e:        # full disk must not kill
                    if self.verbose:        # the writer; retried next
                        print(f"[coordinator] snapshot artifact "
                              f"write failed: {e}", flush=True)
                    return
            meta["artifact_written"] = True
            if obs_metrics.REGISTRY.enabled:
                _G_SNAP_BYTES.set(len(meta["state"])
                                  + len(meta["model"]))
        with self._lock:
            gc = getattr(self.ledger, "gc_prefix", None)
            base = getattr(self.ledger, "log_base", 0)
            if gc is None or base >= i + 1:
                meta["final"] = True    # nothing (more) to reclaim
                return
            # GC exactly to the snapshot boundary, but never past the
            # slowest LIVE subscriber's send watermark (an active
            # stream must not lose the ops it is mid-push on; a dead
            # subscriber holds nothing back — its rejoin is the
            # state-sync path)
            floor = i + 1
            for sent in self._sub_sent.values():
                floor = min(floor, sent + 1)
            if floor < i + 1:
                return                  # a live stream is behind: retry
            dropped = gc(i + 1, meta["state"])
            meta["final"] = True
            if dropped:
                # the per-op sideband below the base goes with the
                # prefix — auth evidence and certificates for GC'd ops
                # can never be served again (the snapshot op's own cert
                # stays: it is the offer's chain-link evidence).  This
                # is what actually bounds writer MEMORY alongside the
                # on-disk log/WAL bound.
                self._op_auth = {k: v for k, v in self._op_auth.items()
                                 if k >= i}
                self._op_trace = {k: v
                                  for k, v in self._op_trace.items()
                                  if k >= i}
                kept = {k: v for k, v in self._certs.items() if k >= i}
                kept_hashes = {w.get("op_hash") for w in kept.values()}
                self._certs = kept
                self._certs_by_ophash = {
                    h: w for h, w in self._certs_by_ophash.items()
                    if h in kept_hashes}
            if obs_metrics.REGISTRY.enabled and dropped:
                _M_GC_OPS.inc(dropped)
            obs_flight.FLIGHT.record("event", "ledger_gc", base=i + 1,
                                     dropped=dropped)
            if self.verbose and dropped:
                print(f"[coordinator] GC: dropped {dropped} log ops "
                      f"behind snapshot@{i}", flush=True)

    def _snapshot_offer(self, require_model: bool = True) \
            -> Optional[dict]:
        """The newest FINALIZED (certified when BFT) snapshot meta, or
        None — what the `snapshot` RPC and the validator-resync path
        hand out.  require_model=False serves a model-less meta too: a
        validator installs ledger STATE only (`bft_snapshot`), so a
        promotion-resumed meta whose model mirror was stale at snapshot
        time must still unblock validator catch-up."""
        for meta in (self._latest_snapshot, self._served_snapshot):
            if meta is None or \
                    (require_model and meta.get("model") is None):
                # a promotion-resumed meta can lack the model blob (the
                # standby's mirror was stale at snapshot time): nothing
                # to offer a JOINER until this writer emits its own
                # snapshot
                continue
            if self._bft is not None and meta.get("cert") is None:
                # newest emission still mid-certification: fall back to
                # the last finalized offer (the GC'd prefix must always
                # have a servable account)
                continue
            return meta
        return None

    def _monitor_loop(self) -> None:
        """Failure detector: when a round stalls (dead client processes),
        drive the recovery ops.  Mirrors client/threaded.py's detector, but
        liveness comes from request recency, not shared memory."""
        while not self._stop.is_set():
            time.sleep(min(self.stall_timeout_s / 4, 1.0))
            if self._bft is not None \
                    and self._certified_size < self.ledger.log_size():
                # sweep ops appended outside a client request (recovery
                # ops below; a request thread that died mid-certify):
                # certification is the publication gate for the op
                # stream, so nothing may linger uncertified.  Guarded and
                # with a tick-sized budget so an unreachable quorum costs
                # this thread one bounded attempt per tick instead of the
                # full bft timeout — stall RECOVERY below must keep its
                # stall_timeout_s/4 cadence regardless of validator
                # health (review finding: the unbounded sweep starved it)
                self._ensure_certified(
                    self.ledger.log_size(),
                    timeout_s=min(self.stall_timeout_s / 4, 1.0))
            if self._snap_interval or self._latest_snapshot is not None:
                try:
                    self._maybe_finalize_snapshot()
                except Exception as e:  # noqa: BLE001 — snapshot
                    # finalization must never kill the failure detector
                    if self.verbose:
                        print(f"[coordinator] snapshot finalize failed: "
                              f"{type(e).__name__}: {e}", flush=True)
            with self._lock:
                if self.ledger.epoch < 0:
                    continue
                stalled = (time.monotonic() - self._last_progress
                           > self.stall_timeout_s)
                if not stalled:
                    continue
                try:
                    self._recover()
                except Exception as e:      # noqa: BLE001 — the detector
                    # must survive anything recovery throws (hostile blob
                    # structure, commit race): a dead monitor thread would
                    # silently disable stall recovery for the whole run
                    if self.verbose:
                        print(f"[coordinator] recovery failed: "
                              f"{type(e).__name__}: {e}", flush=True)
                self._last_progress = time.monotonic()

    def _recover(self) -> None:
        led = self.ledger
        if self._async:
            # async stall: the buffer sat below K for stall_timeout_s
            # (e.g. the fleet's tail as clients exit) — drain what's
            # there so buffered work is never stranded (the async
            # analogue of close_round + force_aggregate)
            if led.async_buffer_depth > 0:
                if self.verbose:
                    print(f"[coordinator] recovery: async partial "
                          f"aggregate of {led.async_buffer_depth} "
                          f"buffered deltas@{led.epoch}", flush=True)
                self._async_aggregate_and_commit()
            return
        if led.aggregate_ready():
            self._aggregate_and_commit()
            return
        if 0 < led.update_count < self.cfg.needed_update_count \
                and not led.round_closed:
            if led.close_round() == LedgerStatus.OK:
                if self.verbose:
                    print(f"[coordinator] recovery: close_round@{led.epoch}",
                          flush=True)
                self._cv.notify_all()
                return
        # scoring stuck — committee presumed dead: seat recently-seen
        # clients (prefer non-uploaders so nobody scores their own update)
        if led.update_count > 0 and led.score_count < self.cfg.comm_count:
            uploaders = {u.sender for u in led.query_all_updates()}
            fresh_cut = time.monotonic() - self.stall_timeout_s
            live = [a for a, t in sorted(self._last_seen.items(),
                                         key=lambda kv: -kv[1])
                    if t >= fresh_cut]
            committee = set(led.committee())
            dead_committee = not any(a in committee for a in live)
            if dead_committee:
                pool = ([a for a in live if a not in uploaders] or live)
                seats = pool[: self.cfg.comm_count]
                if seats and led.reseat_committee(seats) == LedgerStatus.OK:
                    if self.verbose:
                        print(f"[coordinator] recovery: reseat@{led.epoch}",
                              flush=True)
                    self._cv.notify_all()
                    return
        if led.score_count > 0:
            if led.force_aggregate() == LedgerStatus.OK:
                if self.verbose:
                    print(f"[coordinator] recovery: "
                          f"force_aggregate@{led.epoch}", flush=True)
                if led.aggregate_ready():
                    self._aggregate_and_commit()


# --------------------------------------------------------------- client side
class CoordinatorClient:
    """Client-side proxy: one socket, blocking request/reply.

    Thin by design — signing and tensor codec live in the caller
    (client/process_runtime.py); this class only frames messages.
    """

    def __init__(self, host: str, port: int, timeout_s: float = 30.0,
                 tls=None):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout_s)
        if tls is not None:                 # comm.tls.client_context
            self.sock = tls.wrap_socket(self.sock, server_hostname=host)

    def request(self, method: str, **fields) -> dict:
        send_msg(self.sock, {"method": method, **fields})
        reply = recv_msg(self.sock)
        if reply is None:
            raise ConnectionError("coordinator closed the connection")
        return reply

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def replicate(host: str, port: int, cfg: ProtocolConfig,
              ledger_backend: str = "auto", until_ops: int = 0,
              timeout_s: float = 60.0, tls=None):
    """Live replica: subscribe to the writer's op stream, replay every op
    into a fresh local ledger, and verify chained-head equality against the
    writer at the end — the multi-node replication consistency contract
    (reference: identical state on all 4 PBFT nodes, imgs/runtime.jpg).

    Returns the replica ledger once its log reaches `until_ops` ops (or
    raises on divergence/timeout).  Against a writer whose log prefix was
    GC'd behind a certified snapshot (ledger.snapshot) the replica
    STATE-SYNCS first — installs the hash-verified snapshot and replays
    only the tail — which is exactly the joiner path this module's
    Standby uses.
    """
    def _install_from(probe):
        from bflc_demo_tpu.ledger.snapshot import (
            restore_snapshot, snapshot_base_head, verify_snapshot_meta)
        offer = probe.request("snapshot")
        if not offer.get("ok"):
            raise RuntimeError(
                f"writer GC'd its prefix but serves no snapshot: "
                f"{offer.get('error')}")
        meta = {"i": offer["i"], "op": offer["op"],
                "prev_head": offer["prev_head"],
                "state": blob_bytes(offer["state"]),
                "model": blob_bytes(offer["model"]),
                "cert": offer.get("cert"),
                "gen": offer.get("gen", 0)}
        err = verify_snapshot_meta(meta)
        if err:
            raise RuntimeError(f"refusing offered snapshot: {err}")
        return restore_snapshot(meta["state"], cfg,
                                int(meta["i"]) + 1,
                                snapshot_base_head(meta))

    probe0 = CoordinatorClient(host, port, timeout_s=timeout_s, tls=tls)
    try:
        base = int(probe0.request("info").get("log_base", 0) or 0)
        replica = (_install_from(probe0) if base > 0
                   else make_ledger(cfg, backend=ledger_backend))
    finally:
        probe0.close()
    deadline = time.monotonic() + timeout_s
    for _ in range(3):
        resync = False
        sub = CoordinatorClient(host, port, timeout_s=timeout_s, tls=tls)
        try:
            send_msg(sub.sock, {"method": "subscribe",
                                "from": replica.log_size()})
            while replica.log_size() < until_ops:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"replica saw {replica.log_size()}/{until_ops} "
                        f"ops in {timeout_s}s")
                msg = recv_msg(sub.sock)
                if msg is None:
                    raise ConnectionError("writer closed the op stream")
                if msg.get("state_sync"):
                    # GC passed our resume point between the probe and
                    # the subscribe (the by-design race the marker
                    # exists for): install the NEWER snapshot and
                    # re-subscribe from its tail
                    resync = True
                    break
                if "op" not in msg:
                    raise RuntimeError(f"unexpected stream frame: {msg}")
                st = replica.apply_op(bytes.fromhex(msg["op"]))
                if st != LedgerStatus.OK:
                    raise RuntimeError(
                        f"replica rejected op {msg['i']}: {st.name}")
        finally:
            sub.close()
        if not resync:
            break
        p = CoordinatorClient(host, port, timeout_s=timeout_s, tls=tls)
        try:
            replica = _install_from(p)
        finally:
            p.close()
    else:
        raise RuntimeError("subscribe kept racing snapshot GC")
    if not replica.verify_log():
        raise RuntimeError("replica chain verification failed")
    probe = CoordinatorClient(host, port, tls=tls)
    try:
        info = probe.request("info")
        # when the writer hasn't moved past our view, the chained head must
        # match byte-for-byte (the replicas-agree-by-construction contract);
        # if it has moved on, callers re-run with the larger until_ops
        if info["log_size"] == replica.log_size() and \
                info["log_head"] != replica.log_head().hex():
            raise RuntimeError("replica/writer head digest divergence")
    finally:
        probe.close()
    return replica
