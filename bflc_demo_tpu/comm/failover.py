"""Writer failover: hot-standby replicas that promote when the writer dies.

The reference keeps serving through chain-node loss because all 4 PBFT nodes
execute every op (README.md:162-183) — no single node is a failure domain.
Round 2's networked coordinator replicated its op log to live verifiers but
they could not take over: the writer was the one unprotected failure domain.
This module closes it:

- a `Standby` follows the writer LIVE: it subscribes to the op stream
  (byte-identical, chained, verified on apply), and mirrors the sideband
  state ops only reference by hash — update payload blobs, the current
  global model blob (content-hash-checked against the replayed ledger), and
  the public-key directory (addresses are self-authenticating, so the
  mirror is integrity-checked);
- death detection is connection-driven with a probe fallback: a broken or
  idle op stream triggers an `info` probe of the writer; refused/timed-out
  probes mean dead;
- promotion is deterministic, lease-free: endpoints are an ordered priority
  list (the reference's fixed 4-node topology); standby k promotes only
  when the writer AND every higher-priority standby are dead
  (connection-refused — a bound-but-following standby accepts the TCP
  connect, which distinguishes "alive, not yet serving" from "gone").
  Highest live priority wins; everyone else re-follows the winner;
- promotion is FENCED: the promoting standby appends a promote_writer op
  (generation N+1) to the replicated chain itself before serving, and —
  when provisioned with an identity (`wallet`) — mints SIGNED promotion
  evidence binding (generation, op position, pre-promotion chain head,
  standby index) under Ed25519.  The promoted writer attaches the evidence
  to every reply; clients carry the highest generation they have seen plus
  its proof on every request (FailoverClient.gen / .gen_ev).  A
  pre-partition writer still at generation N self-demotes (answers
  STALE_WRITER, closes) only on VERIFIED evidence — signature by a
  provisioned standby key AND chain-prefix binding against its own log —
  never on a bare integer (that was a one-message DoS, ADVICE r4).
  Fencing is enforced from BOTH sides: clients additionally reject any
  reply whose generation is behind their fence, so a stale writer that
  never receives evidence still cannot retain fenced clients.  A standby
  never follows a writer whose generation is behind its own chain.  An
  asymmetric partition can still let the old writer accept ops while
  isolated, but on heal exactly one chain survives: the fenced one — the
  old writer's divergent suffix is abandoned and its honest clients'
  signed ops replay idempotently against the promoted writer.  (The
  reference gets no-fork from PBFT quorums; this is the
  fail-stop-plus-fencing equivalent without a quorum round per op.);
- the standby binds its serving socket AT START, so clients that fail over
  early sit in the listen backlog until promotion finishes — no
  connection-refused window;
- clients use `FailoverClient`: same request surface, rotates through the
  endpoint list on connection failure.  Retried mutations are safe end to
  end: ops are Ed25519-tagged and the ledger + replay-guard answer
  DUPLICATE ("already in") for an op whose reply was lost, which callers
  treat as progress.

Upload payloads are mirrored BEFORE the op applies (round 7): a streamed
upload op binds on the standby only once its payload blob landed (fetched
per-op, bypassing the QueryAllUpdates round gate), so in EVERY mode —
async included — a promoted standby never holds an update record without
its payload.  If the writer dies mid-fetch the op simply never applied
here: the promoted chain lacks the record entirely and the uploader's
signed retry re-supplies both record and blob.  The one deliberate
exception: when the writer authoritatively answers "unknown blob" (the
round already aggregated and the blob was consumed), the op applies as
historical record with its ack clamped until the replayed chain's epoch
moves past it — a blob that no longer exists writer-side cannot gate
replication forever.  Quorum mode keeps its stronger property on top: an
acknowledged upload provably survives writer death with its blob
(regression-tested in tests/test_failover.py).
"""

from __future__ import annotations

import hashlib
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from bflc_demo_tpu.comm.dataplane import ReadFanoutServer, data_plane_legacy
from bflc_demo_tpu.comm.identity import PublicDirectory, address_of
from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                               LedgerServer)
from bflc_demo_tpu.comm.wire import (blob_bytes, send_msg, recv_msg,
                                     WireError)
from bflc_demo_tpu.ledger import make_ledger, LedgerStatus
from bflc_demo_tpu.obs import flight as obs_flight
from bflc_demo_tpu.obs import metrics as obs_metrics
from bflc_demo_tpu.obs import trace as obs_trace
from bflc_demo_tpu.protocol.constants import ProtocolConfig

Endpoint = Tuple[str, int]

# --- standby-side telemetry (obs.metrics; no-ops unless enabled).  A
# pre-promotion standby serves no socket, so these reach the collector
# through the file snapshots obs.install_process_telemetry publishes.
_M_MIRROR = obs_metrics.REGISTRY.histogram(
    "standby_mirror_latency_seconds",
    "per-blob payload mirror fetch (the mirror-before-apply gate)")
_G_APPLIED = obs_metrics.REGISTRY.gauge(
    "standby_applied_ops", "ops applied from the writer's stream")
_G_ACK_LAG = obs_metrics.REGISTRY.gauge(
    "standby_ack_lag_ops",
    "applied ops not yet ack-eligible (pending-payload clamp depth)")
_M_PROMOTIONS = obs_metrics.REGISTRY.counter(
    "standby_promotions_total", "promotions by outcome", ("outcome",))
# --- snapshot state-sync (ledger.snapshot): how long installing a
# certified checkpoint + model took vs the replay-from-genesis it
# replaced, and how often rejoins took the snapshot path at all.
_M_SYNC_S = obs_metrics.REGISTRY.histogram(
    "state_sync_seconds",
    "snapshot fetch + verify + install wall time on rejoin")
_M_SYNCS = obs_metrics.REGISTRY.counter(
    "state_syncs_total", "snapshot state-syncs by outcome", ("outcome",))
_M_GC_OPS = obs_metrics.REGISTRY.counter(
    "standby_gc_ops_total",
    "mirrored log ops reclaimed behind streamed certified snapshots")


class WriterDead(Exception):
    """The followed writer is unreachable."""


class PromotionSuperseded(Exception):
    """This standby's fence op lost the promotion race: a validator
    quorum mandated a FOREIGN op at its chain position (another proposer
    won).  The standby has already rolled its fence op back; it must
    re-follow the winner instead of serving."""


class FailoverClient:
    """CoordinatorClient over an ordered endpoint list.

    On any connection-level failure the current socket is dropped and the
    next endpoint is tried; a full silent cycle backs off briefly.  Request
    retry across endpoints is safe because every mutation is signed and
    idempotent at the ledger (DUPLICATE for already-applied ops).

    SECURITY — standby_keys (ADVICE r5): without provisioned standby
    public keys the client accepts promotion evidence on structural match
    alone, so ONE hostile or compromised endpoint replying
    ``{gen: 999, gen_ev: {gen: 999, ...}}`` permanently poisons the fence
    and makes the client reject the legitimate writer — the exact
    one-message DoS the evidence scheme closes when keys exist.  Any
    deployment with more than one endpoint (i.e. anywhere failover is
    real) should provision `standby_keys`; constructing one without them
    emits a RuntimeWarning rather than silently running forgeable.

    bft_keys / bft_quorum (round 6): when the deployment runs BFT commit
    certificates (comm.bft), provisioning the validator public keys makes
    the client REJECT any mutating ack that does not carry a certificate
    with `bft_quorum` authentic validator signatures — a writer that
    dropped, forged, or forked the op cannot fake the ack (it does not
    hold the validators' keys), so the reply is treated as a connection
    failure and the client rotates/raises instead of trusting it.
    """

    _BFT_ACKED = ("register", "upload", "scores", "aupload", "ascores")

    def __init__(self, endpoints: List[Endpoint], timeout_s: float = 30.0,
                 max_cycles: int = 6, tls=None,
                 standby_keys: Optional[Dict[int, bytes]] = None,
                 bft_keys: Optional[Dict[int, bytes]] = None,
                 bft_quorum: Optional[int] = None):
        if not endpoints:
            raise ValueError("need at least one endpoint")
        if len(endpoints) > 1 and not standby_keys:
            import warnings
            warnings.warn(
                "FailoverClient with multiple endpoints but no "
                "standby_keys: promotion evidence is accepted on "
                "structural match alone, so one hostile endpoint can "
                "poison this client's fence (one-message DoS) — provision "
                "the standby public keys", RuntimeWarning, stacklevel=2)
        self._eps = list(endpoints)
        self._timeout_s = timeout_s
        self._max_cycles = max_cycles
        self._tls = tls
        self._cur = 0
        self._client: Optional[CoordinatorClient] = None
        self._bft_keys = dict(bft_keys or {})
        if self._bft_keys and bft_quorum is None:
            from bflc_demo_tpu.protocol.constants import bft_quorum as _bq
            bft_quorum = _bq(len(self._bft_keys))
        self._bft_quorum = bft_quorum or 0
        # provisioned standby pubkeys: with these the client VERIFIES the
        # Ed25519 signature on promotion evidence before moving its fence
        # (a forged {gen, gen_ev} dict from a hostile endpoint must not
        # poison us into rejecting the legitimate writer); without them
        # only the structural check applies (wallet-less deployments)
        self._standby_keys = dict(standby_keys or {})
        # highest writer generation observed in any reply; sent back as the
        # `fence` on every request — with the promoted writer's SIGNED
        # promotion evidence (`gen_ev`) when we hold it, so a
        # partitioned-then-healed stale writer self-demotes the moment any
        # client that saw the promotion talks to it (comm.ledger_service
        # verifies the evidence; a bare integer no longer demotes anyone).
        # The client also enforces the fence itself: a reply whose `gen` is
        # BEHIND ours comes from a stale writer and is rejected like a
        # connection failure — split-brain protection that needs no
        # cooperation from the stale side.
        self.gen = 0
        self.gen_ev: Optional[dict] = None

    @property
    def current_endpoint(self) -> Endpoint:
        return self._eps[self._cur]

    def request(self, method: str, **fields) -> dict:
        last: Optional[Exception] = None
        attempts = self._max_cycles * len(self._eps)
        fields.setdefault("fence", self.gen)
        if self.gen_ev is not None:
            fields.setdefault("fence_ev", self.gen_ev)
        for attempt in range(attempts):
            try:
                if self._client is None:
                    host, port = self._eps[self._cur]
                    self._client = CoordinatorClient(
                        host, port, timeout_s=self._timeout_s,
                        tls=self._tls)
                reply = self._client.request(method, **fields)
                g = reply.get("gen")
                ev = reply.get("gen_ev")
                ev_gen = -1
                if isinstance(ev, dict):
                    try:
                        ev_gen = int(ev.get("gen", -1))
                    except (TypeError, ValueError):
                        ev = None      # malformed evidence from a broken
                                       # or hostile peer: ignore, don't die
                if isinstance(ev, dict) and self._standby_keys:
                    from bflc_demo_tpu.comm.ledger_service import \
                        verify_promotion_signature
                    if not verify_promotion_signature(ev,
                                                      self._standby_keys):
                        ev = None      # forged/unsigned: never moves us
                # Raise our fence only on a reply that CARRIES the signed
                # promotion evidence for that generation.  A bare integer
                # must not poison the client (round-5 review: one hostile
                # reply with gen=999 would otherwise make us reject the
                # legitimate writer forever).  With provisioned standby
                # keys the signature is VERIFIED above; without them the
                # structural match is the (documented, weaker) bar — and
                # the old writer always verifies cryptographically before
                # demoting.
                if isinstance(g, int) and g > self.gen \
                        and isinstance(ev, dict) and ev_gen == g:
                    self.gen = g
                    self.gen_ev = ev
                    fields["fence"] = self.gen
                    fields["fence_ev"] = self.gen_ev
                elif (isinstance(ev, dict) and self.gen_ev is None
                      and ev_gen == self.gen):
                    self.gen_ev = ev       # learn the proof retroactively
                    fields.setdefault("fence_ev", self.gen_ev)
                if reply.get("status") == "STALE_WRITER":
                    # the endpoint just demoted itself on our fence — it is
                    # not the writer; rotate like a connection failure
                    last = ConnectionError("stale writer demoted")
                    self.close()
                    self._cur = (self._cur + 1) % len(self._eps)
                    continue
                if isinstance(g, int) and g < self.gen:
                    # CLIENT-SIDE fencing: this endpoint is a pre-partition
                    # writer that has not (or cannot — no evidence reached
                    # it) demoted itself.  Never accept its reply: ops
                    # accepted on its divergent suffix are abandoned on
                    # heal.  Rotate to the promoted writer.
                    last = ConnectionError(
                        f"stale writer: reply gen {g} < fence {self.gen}")
                    self.close()
                    self._cur = (self._cur + 1) % len(self._eps)
                    continue
                if (self._bft_keys and method in self._BFT_ACKED
                        and (reply.get("ok")
                             or reply.get("status") in
                             ("DUPLICATE", "ALREADY_REGISTERED"))):
                    # BFT acceptance: a mutating ack must carry a commit
                    # certificate with a quorum of authentic validator
                    # signatures binding THE op this request implies
                    # (expected_op_hash reconstructs its canonical bytes
                    # from our own fields).  A hostile writer that
                    # silently dropped the op cannot mint one, and
                    # replaying a certificate it earned for a DIFFERENT
                    # op fails the op binding — reject either like a dead
                    # endpoint.  DUPLICATE-class replies are acks too
                    # (callers treat "already in" as progress and never
                    # retry), so they get the same bar, or a Byzantine
                    # writer would just spell its forged ack "DUPLICATE"
                    # instead of "OK".
                    from bflc_demo_tpu.comm.bft import (
                        expected_op_hash, verify_certificate_sigs)
                    if not verify_certificate_sigs(
                            reply.get("cert"), self._bft_quorum,
                            self._bft_keys,
                            op_hash=expected_op_hash(method, fields)):
                        last = ConnectionError(
                            f"{method}: ack without a valid commit "
                            f"certificate for this op (uncertified or "
                            f"replayed-certificate state rejected)")
                        self.close()
                        self._cur = (self._cur + 1) % len(self._eps)
                        continue
                return reply
            except (ConnectionError, WireError, OSError) as e:
                last = e
                self.close()
                self._cur = (self._cur + 1) % len(self._eps)
                if self._cur == 0:          # full cycle without an answer
                    time.sleep(min(0.25 * (attempt + 1), 2.0))
        raise ConnectionError(
            f"all coordinator endpoints failed after {attempts} attempts: "
            f"{type(last).__name__}: {last}")

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None


class Standby:
    """A promotable live replica (see module docstring for the protocol).

    endpoints[0] is the initial writer; this standby is endpoints[index].
    The serving socket binds in __init__ — advertise `port` to clients
    before starting.  `run()` blocks: it follows the live writer until the
    writer dies, promotes (or re-follows the winning standby), and — once
    promoted — serves until `stop()`.
    """

    def __init__(self, cfg: ProtocolConfig, endpoints: List[Endpoint],
                 index: int, *, host: str = "127.0.0.1", port: int = 0,
                 ledger_backend: str = "auto",
                 heartbeat_s: float = 1.0,
                 require_auth: bool = True,
                 stall_timeout_s: float = 10.0,
                 tls_client=None, tls_server=None,
                 wal_path: str = "",
                 wallet=None,
                 standby_keys: Optional[Dict[int, bytes]] = None,
                 quorum: int = 0,
                 quorum_timeout_s: float = 5.0,
                 bft_validators: Optional[List[Endpoint]] = None,
                 bft_keys: Optional[Dict[int, bytes]] = None,
                 bft_quorum: Optional[int] = None,
                 bft_timeout_s: float = 10.0,
                 snapshot_interval: int = 0,
                 snapshot_dir: str = "",
                 verbose: bool = False):
        if not 1 <= index < len(endpoints):
            raise ValueError(f"standby index {index} out of range for "
                             f"{len(endpoints)} endpoints")
        cfg.validate()
        self.cfg = cfg
        # --- certified snapshots (ledger.snapshot): when the deployment
        # runs snapshots, this standby (a) STATE-SYNCS from the writer's
        # newest certified snapshot whenever its resume point was GC'd
        # (fresh start, or rejoin after a long death), (b) mirrors each
        # streamed snapshot op's meta and GCs its own replica behind it
        # (bounded memory fleet-wide), and (c) carries the mirrored
        # snapshot into the LedgerServer it becomes at promotion so
        # joiners can state-sync from the new writer immediately.
        # Compaction needs the python ledger backend (make_ledger below).
        from bflc_demo_tpu.ledger.snapshot import snapshot_legacy
        self.snapshot_interval = (0 if snapshot_legacy()
                                  else max(int(snapshot_interval), 0))
        self.snapshot_dir = snapshot_dir
        self._latest_snapshot: Optional[dict] = None
        if self.snapshot_interval and ledger_backend != "python":
            ledger_backend = "python"
        self.endpoints = list(endpoints)
        self.index = index
        self.heartbeat_s = heartbeat_s
        self.require_auth = require_auth
        self.stall_timeout_s = stall_timeout_s
        self.tls_client = tls_client        # for following the writer
        self.tls_server = tls_server        # for serving after promotion
        # attached at PROMOTION: attach_wal journals the full replayed op
        # log first (pyledger.py:76-87 / ledger.cpp), so the promoted
        # writer's WAL holds the complete chain, not a mid-stream suffix
        self.wal_path = wal_path
        # identity for SIGNED promotion evidence (comm.identity.Wallet):
        # without it a promotion still serves failed-over clients, but the
        # deployment loses ALL split-brain protection (ADVICE r5): the
        # pre-partition writer cannot be made to self-demote on heal, AND
        # clients never raise their fence either — FailoverClient only
        # moves its fence on replies that carry promotion evidence, which
        # a wallet-less promotion cannot mint.  A healed stale writer
        # keeps serving its divergent chain to any client that reaches it.
        self.wallet = wallet
        if wallet is None:
            import warnings
            warnings.warn(
                f"Standby(index={index}) constructed WITHOUT a wallet: "
                f"promotions will carry no signed evidence, so a healed "
                f"pre-partition writer is never fenced and client-side "
                f"reply-gen fencing never activates — this deployment "
                f"has no split-brain protection", RuntimeWarning,
                stacklevel=2)
        # index -> Ed25519 pub of ALL provisioned standbys, handed to the
        # LedgerServer this standby becomes, so a LATER promotion can fence
        # it in turn
        self.standby_keys: Dict[int, bytes] = dict(standby_keys or {})
        # carried into the LedgerServer this standby becomes: a promoted
        # writer must keep the deployment's quorum-ack durability contract
        # (losing it exactly after a failover would reopen the
        # acknowledged-op-loss window in the regime it exists for)
        self.quorum = quorum
        self.quorum_timeout_s = quorum_timeout_s
        # --- BFT commit certificates (comm.bft): with validator keys
        # provisioned this standby REJECTS any streamed op that does not
        # carry a certificate quorum-signed over ITS OWN chain prefix (a
        # Byzantine writer cannot make honest replicas replicate forged
        # state), mirrors the certificate map, and on promotion certifies
        # its own fence op with the validator quorum before serving.
        self.bft_validators = list(bft_validators or [])
        self.bft_keys: Dict[int, bytes] = dict(bft_keys or {})
        if self.bft_keys and bft_quorum is None:
            from bflc_demo_tpu.protocol.constants import bft_quorum as _bq
            bft_quorum = _bq(len(self.bft_keys))
        self.bft_quorum = bft_quorum or 0
        self.bft_timeout_s = bft_timeout_s
        self._certs: Dict[int, dict] = {}
        self.verbose = verbose
        self._ledger_backend = ledger_backend
        self.ledger = make_ledger(cfg, backend=ledger_backend)
        self._blobs: Dict[bytes, bytes] = {}
        # upload ops applied WITHOUT their payload blob, by chain index.
        # Since round 7 the follow loop mirrors a payload BEFORE applying
        # its op, so this holds only the one sanctioned exception: the
        # writer authoritatively answered "unknown blob" (the round
        # already aggregated it away).  Outgoing acks stay CLAMPED below
        # the lowest pending index (acks are cumulative watermarks
        # upstream) until the replayed epoch moves past the record.
        self._pending_payload: Dict[int, bytes] = {}
        # set by _mirror_upload_payload when the writer ANSWERED the blob
        # fetch negatively (vs a transport failure) — reset per attempt
        self._blob_unknown = False
        self._model_blob: Optional[bytes] = None
        self._directory = PublicDirectory() if require_auth else None
        # sync gating: only hit the writer's sideband endpoints when the
        # replayed ledger shows the relevant state actually changed
        self._synced_registered = -1
        self._synced_update_count = -1
        self._stop = threading.Event()
        self.promoted = threading.Event()
        self.server: Optional[LedgerServer] = None
        # bind now: failed-over clients queue in the backlog until serving
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        # --- read fan-out (comm.dataplane): this standby already mirrors
        # every payload blob before acking and the current model blob —
        # serve them read-only on a side port, advertised to the writer
        # at subscribe time, so clients take the O(N) model broadcast and
        # the committee's delta fetches off the writer's accept loop.
        # Everything served is hash-verified client-side, so a stale or
        # confused replica costs a fallback round-trip, never wrong
        # bytes.  Closed at promotion (the promoted LedgerServer serves
        # everything on the real port).
        self.read_server: Optional[ReadFanoutServer] = None
        if not data_plane_legacy():
            self.read_server = ReadFanoutServer(
                self._blobs.get, self._read_model_state, host=host,
                tls=tls_server,
                snapshot_state=self._read_snapshot_state)
            self.read_server.start()

    def _read_snapshot_state(self):
        """The mirrored snapshot meta the read fan-out may serve to
        state-syncing joiners, or None — only a checkpoint whose model
        blob is present and hash-consistent is offered (a joiner would
        refuse anything less, so declining is cheaper)."""
        meta = self._latest_snapshot
        if meta is None or meta.get("model") is None:
            return None
        return meta

    def _read_model_state(self):
        """(epoch, hash, blob) of the mirrored model, or None before the
        first mirror — the read fan-out server's model provider."""
        blob = self._model_blob
        if blob is None:
            return None
        return (self.ledger.epoch, hashlib.sha256(blob).digest(), blob)

    # ------------------------------------------------------------------ api
    def stop(self) -> None:
        self._stop.set()
        if self.server is not None:
            self.server.close()
        if self.read_server is not None:
            self.read_server.close()
        try:
            self._sock.close()
        except OSError:
            pass

    def run(self) -> None:
        """Follow -> (writer dies) -> promote or re-follow -> serve."""
        writer = 0                      # index of the endpoint we follow
        while not self._stop.is_set():
            if 0 <= writer < len(self.endpoints):
                try:
                    self._follow(self.endpoints[writer])
                except WriterDead:
                    if self.verbose:
                        print(f"[standby {self.index}] writer "
                              f"{self.endpoints[writer]} dead", flush=True)
            if self._stop.is_set():
                return
            winner = self._elect()
            if winner == self.index:
                if self._model_blob is None:
                    # a freshly (re)started standby can win the priority
                    # election before it ever mirrored state — it has
                    # nothing to serve.  Follow ANY serving peer
                    # (regardless of priority index) to rebuild state
                    # first; only then is promotion meaningful.
                    writer = self._any_serving_peer()
                    time.sleep(self.heartbeat_s)
                    continue
                try:
                    self._promote_and_serve()
                    return
                except PromotionSuperseded:
                    # another proposer's fence op is canonically bound at
                    # our position: we lost the race (fence op already
                    # rolled back) — re-follow the winner
                    _M_PROMOTIONS.inc(outcome="superseded")
                    obs_flight.FLIGHT.record(
                        "event", "promotion_superseded", index=self.index)
                    if self.verbose:
                        print(f"[standby {self.index}] promotion "
                              f"superseded; re-following", flush=True)
                    writer = self._any_serving_peer()
                    time.sleep(self.heartbeat_s)
                    continue
                except Exception:
                    # a failed promotion must not leave the bound socket
                    # accepting connects while nothing serves: peers would
                    # keep electing this dead winner forever.  Close it so
                    # their election sees connection-refused, and surface
                    # the error instead of dying silently.
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    raise
            elif winner < 0:
                time.sleep(self.heartbeat_s)   # nobody promotable yet
            else:
                writer = winner
                # give the winner time to finish promotion first
                time.sleep(self.heartbeat_s)

    # ------------------------------------------------------------ following
    def _follow(self, writer: Endpoint) -> None:
        """Apply the writer's op stream live; mirror blobs/model/directory.

        Raises WriterDead when the stream breaks and a probe fails.
        """
        host, port = writer
        try:
            ctl = CoordinatorClient(host, port, timeout_s=10.0,
                                    tls=self.tls_client)
        except (ConnectionError, WireError, OSError) as e:
            raise WriterDead(str(e))
        try:
            # fence check: never follow a writer whose generation is behind
            # our replayed chain — that's a stale pre-partition writer whose
            # ops would fork us off the promoted chain
            inf = ctl.request("info")
            if int(inf.get("gen", 0)) < self.ledger.generation:
                raise WriterDead(
                    f"stale writer: gen {inf.get('gen')} < "
                    f"ours {self.ledger.generation}")
            # snapshot state-sync (ledger.snapshot): the writer GC'd its
            # log past our resume point — replaying the prefix is
            # impossible, so install the newest certified snapshot +
            # model and follow only the tail (a refusal of a corrupt or
            # forged offer raises out of _state_sync, never installs)
            if self.ledger.log_size() < int(inf.get("log_base", 0) or 0):
                self._state_sync(ctl)
            sub = self._open_subscription(writer)
        except (ConnectionError, WireError, OSError) as e:
            ctl.close()
            raise WriterDead(str(e))
        except (WriterDead, RuntimeError):
            ctl.close()
            raise
        try:
            self._sync_state(ctl)
            last_applied = self.ledger.log_size() - 1
            while not self._stop.is_set():
                try:
                    msg = recv_msg(sub.sock)
                except (TimeoutError, socket.timeout):
                    if not self._writer_alive(writer):
                        raise WriterDead("probe failed")
                    # idle stream: keep retrying any record whose blob the
                    # writer once reported consumed (the only way an
                    # unmirrored upload can be applied here) and drop the
                    # moot ones, so the ack clamp lifts without waiting
                    # for the next op
                    if self._pending_payload:
                        self._retry_pending_payloads(ctl)
                        self._send_ack(sub, last_applied)
                    continue
                except (WireError, OSError) as e:
                    raise WriterDead(str(e))
                if msg is None:
                    raise WriterDead("op stream closed")
                if "op" not in msg:
                    if not msg.get("state_sync"):
                        continue        # unknown control frame: ignore
                    # the writer GC'd past our subscribe point BETWEEN
                    # the info probe and the subscribe (the race the
                    # stream marker exists for): install the snapshot
                    # and resubscribe at the post-install position
                    sub.close()
                    try:
                        self._state_sync(ctl)
                        sub = self._open_subscription(writer)
                    except (ConnectionError, WireError, OSError) as e:
                        raise WriterDead(str(e))
                    last_applied = self.ledger.log_size() - 1
                    continue
                op_bytes = bytes.fromhex(msg["op"])
                op_index = self.ledger.log_size()
                # causal mirror span in the op's originating trace (the
                # stream frame's `tp`, obs.trace; null for untraced
                # ops): certificate check + payload mirror + apply —
                # the edge the writer's quorum-ack wait blocks on
                with obs_trace.TRACE.span_from(msg.get("tp"),
                                               "standby.mirror",
                                               i=op_index):
                    if self.bft_keys:
                        # BFT mode: an append binds here only with a
                        # commit certificate quorum-signed over OUR
                        # chain prefix — a Byzantine writer streaming
                        # forged/forked/uncertified state is refused,
                        # not replicated
                        self._require_certificate(msg, op_index,
                                                  op_bytes)
                    # a pushed upload op may carry its payload blob
                    # inline (binary frame piggyback, PR 3): hash-verify
                    # against the op and mirror it without the fetch
                    # round-trip the mirror-before-apply gate would
                    # otherwise spend on the ack critical path.  A
                    # wrong-hash blob is ignored — the gate below then
                    # fetches/fails exactly as before, so a lying writer
                    # gains nothing.
                    self._harvest_pushed_blob(msg, op_bytes)
                    # mirror-BEFORE-apply: an upload op binds here only
                    # once its payload blob landed, so this replica can
                    # never hold an update record without its payload —
                    # in async mode just as in quorum mode.  If the
                    # writer dies mid-fetch the op never applied: the
                    # promoted chain lacks the record entirely and the
                    # uploader's signed retry re-supplies it.  Returns
                    # False only on an authoritative "unknown blob"
                    # (round already aggregated it away): the op then
                    # applies as historical record with its ack clamped
                    # until the replayed epoch moves past it.
                    if not self._await_upload_payload(op_bytes, ctl,
                                                      writer):
                        self._pending_payload[op_index] = op_bytes
                    st = self.ledger.apply_op(op_bytes)
                    if st != LedgerStatus.OK:
                        raise RuntimeError(
                            f"standby rejected op {msg['i']}: {st.name} "
                            f"— writer/replica divergence, refusing to "
                            f"continue")
                    last_applied = op_index
                    if op_bytes and op_bytes[0] == self._SNAPSHOT_OPCODE:
                        # the apply above already re-derived the
                        # snapshot's state digest from OUR replica
                        # (pyledger OP_SNAPSHOT refuses a mismatch) —
                        # mirror the meta and GC this replica behind the
                        # certified checkpoint
                        self._note_snapshot_op(op_index, op_bytes,
                                               msg.get("cert"))
                self._drop_moot_payloads()
                try:
                    self._sync_state(ctl)
                except (ConnectionError, WireError, OSError):
                    if not self._writer_alive(writer):
                        raise WriterDead("state sync failed")
                    continue            # sideband incomplete: no ack yet
                # confirm apply + mirror upstream: the writer's quorum-ack
                # mode counts these before acknowledging mutations
                # (best-effort — a lost ack only delays, never corrupts)
                with obs_trace.TRACE.span_from(msg.get("tp"),
                                               "standby.ack",
                                               i=last_applied):
                    self._send_ack(sub, last_applied)
        finally:
            sub.close()
            ctl.close()

    def _open_subscription(self, writer: Endpoint) -> CoordinatorClient:
        """Open the op-stream subscription at our current resume point,
        proving the provisioned standby identity via the challenge
        handshake (the nonce makes captured handshakes unreplayable)
        so this subscription's acks count toward the writer's
        durability quorum."""
        host, port = writer
        sub = CoordinatorClient(host, port, timeout_s=self.heartbeat_s,
                                tls=self.tls_client)
        sub_msg = {"method": "subscribe",
                   "from": self.ledger.log_size()}
        if self.wallet is not None:
            sub_msg["sb"] = self.index
            if self.read_server is not None:
                # advertise the read fan-out endpoint; the writer
                # republishes it only if the handshake below proves
                # our provisioned identity (comm.ledger_service)
                sub_msg["read_ep"] = list(self.read_server.endpoint)
        try:
            send_msg(sub.sock, sub_msg)
            if self.wallet is not None:
                import struct as _struct
                sub.sock.settimeout(10.0)  # handshake, not heartbeat
                ch = recv_msg(sub.sock)
                sub.sock.settimeout(self.heartbeat_s)
                if not isinstance(ch, dict) or "challenge" not in ch:
                    raise WriterDead("subscriber handshake: no challenge")
                sig = self.wallet.sign(
                    LedgerServer._SUB_MAGIC + bytes.fromhex(ch["challenge"])
                    + _struct.pack("<Iq", self.index, sub_msg["from"]))
                send_msg(sub.sock, {"tag": sig.hex()})
        except BaseException:
            sub.close()
            raise
        return sub

    _SNAPSHOT_OPCODE = 9        # ledger op codec (ledger/tool.decode_op)

    def _state_sync(self, ctl: CoordinatorClient) -> None:
        """Install the writer's newest certified snapshot in place of a
        GC'd prefix this replica can no longer replay (ledger.snapshot).

        Trust: `verify_snapshot_meta` re-derives every binding — state
        bytes must hash to the op's embedded digest, the model blob to
        the state's model hash, the commit certificate (BFT mode) must
        quorum-bind (i, prev_head, op) under OUR provisioned validator
        keys, and the generation must not regress below our replayed
        fence.  A forged/stale/torn offer raises RuntimeError (explicit
        refusal, same semantics as an uncertified append) and nothing
        installs; transport failures raise WriterDead (retry later)."""
        from bflc_demo_tpu.ledger.snapshot import (restore_snapshot,
                                                   snapshot_base_head,
                                                   verify_snapshot_meta)
        t0 = time.perf_counter()
        try:
            offer = ctl.request("snapshot", meta=1)
        except (ConnectionError, WireError, OSError) as e:
            raise WriterDead(str(e))
        if not offer.get("ok"):
            _M_SYNCS.inc(outcome="no_offer")
            raise WriterDead(
                f"writer GC'd past our resume point but serves no "
                f"snapshot: {offer.get('error')}")
        try:
            meta = {"i": int(offer["i"]), "epoch": int(offer["epoch"]),
                    "gen": int(offer.get("gen", 0)), "op": offer["op"],
                    "prev_head": offer["prev_head"],
                    "cert": offer.get("cert")}
        except (KeyError, TypeError, ValueError) as e:
            _M_SYNCS.inc(outcome="refused")
            raise RuntimeError(
                f"standby {self.index}: malformed snapshot offer: {e}")
        meta["state"], meta["model"] = self._fetch_snapshot_body(
            ctl, offer)
        err = verify_snapshot_meta(
            meta, bft_quorum=self.bft_quorum,
            bft_keys=self.bft_keys or None,
            min_generation=self.ledger.generation)
        if err:
            _M_SYNCS.inc(outcome="refused")
            raise RuntimeError(
                f"standby {self.index}: refusing offered snapshot: "
                f"{err}")
        base = int(meta["i"]) + 1
        self.ledger = restore_snapshot(meta["state"], self.cfg, base,
                                       snapshot_base_head(meta))
        self._ledger_backend = "python"     # restored replicas compact
        self._model_blob = bytes(meta["model"])
        self._certs = ({int(meta["i"]): meta["cert"]}
                       if meta.get("cert") else {})
        self._pending_payload.clear()
        self._blob_unknown = False
        self._synced_registered = -1        # force a full sideband
        self._synced_update_count = -1      # resync against the tail
        self._latest_snapshot = {**meta, "final": True}
        dt = time.perf_counter() - t0
        if obs_metrics.REGISTRY.enabled:
            _M_SYNC_S.observe(dt)
            _M_SYNCS.inc(outcome="installed")
        obs_flight.FLIGHT.record(
            "event", "state_sync", i=int(meta["i"]),
            epoch=int(meta["epoch"]), seconds=round(dt, 3))
        if self.verbose:
            print(f"[standby {self.index}] state-synced from certified "
                  f"snapshot@{meta['i']} (epoch {meta['epoch']}, "
                  f"{dt * 1e3:.0f} ms)", flush=True)

    def _fetch_snapshot_body(self, ctl: CoordinatorClient,
                             offer: dict) -> Tuple[bytes, bytes]:
        """(state, model) bytes for the writer-asserted snapshot offer:
        advertised read-fan-out replicas first (comm.dataplane — the
        fattest fetch on the plane comes off the writer's accept loop),
        the writer itself as the always-correct fallback.  Replica bytes
        are pre-checked against the offer's own digests, so a stale or
        lying replica costs one round-trip, never a refused install."""
        from bflc_demo_tpu.ledger.snapshot import (decode_state,
                                                   parse_snapshot_op)
        op = offer.get("op", "")
        op_b = bytes.fromhex(op) if isinstance(op, str) else bytes(op)
        parsed = parse_snapshot_op(op_b)
        want_digest = parsed[1] if parsed else None
        for ep in offer.get("read_set") or []:
            try:
                host, port = str(ep[0]), int(ep[1])
            except (TypeError, ValueError, IndexError):
                continue
            try:
                c = CoordinatorClient(host, port, timeout_s=10.0,
                                      tls=self.tls_client)
            except (ConnectionError, OSError):
                continue
            try:
                r = c.request("snapshot", want_i=int(offer["i"]))
            except (ConnectionError, WireError, OSError):
                continue
            finally:
                c.close()
            if not r.get("ok"):
                continue
            try:
                state = blob_bytes(r.get("state", b""))
                model = blob_bytes(r.get("model", b""))
                mh = bytes(decode_state(state)["model_hash"])
            except ValueError:
                continue
            if want_digest is not None \
                    and hashlib.sha256(state).digest() == want_digest \
                    and hashlib.sha256(model).digest() == mh:
                return state, model
        try:
            r = ctl.request("snapshot")
        except (ConnectionError, WireError, OSError) as e:
            raise WriterDead(str(e))
        if not r.get("ok"):
            raise WriterDead(
                f"snapshot body fetch failed: {r.get('error')}")
        return blob_bytes(r["state"]), blob_bytes(r["model"])

    def _note_snapshot_op(self, i: int, op: bytes, cert_wire) -> None:
        """Mirror a streamed snapshot op's full meta and GC this replica
        behind the certified checkpoint: bounded replica memory
        fleet-wide, the meta served to state-syncing joiners through the
        read fan-out, and carried into the LedgerServer this standby
        becomes at promotion (joiners state-sync from the new writer
        immediately).  The caller already applied the op, which IS the
        verification — apply re-derives the state digest locally."""
        from bflc_demo_tpu.ledger.snapshot import (parse_snapshot_op,
                                                   prune_snapshots,
                                                   write_snapshot_file)
        parsed = parse_snapshot_op(op)
        if parsed is None:
            return
        epoch, _digest = parsed
        state = self.ledger.encode_state()
        head_at = getattr(self.ledger, "head_at", None)
        prev = head_at(i) if head_at is not None else b""
        model = self._model_blob
        want_mh, _ = self.ledger.query_global_model()
        if model is None or hashlib.sha256(model).digest() != want_mh:
            # stale mirror: never serve/persist a model blob that fails
            # the snapshot's own hash check (a joiner would refuse the
            # whole offer) — the meta still rides without it
            model = None
        meta = {"i": i, "epoch": epoch, "gen": self.ledger.generation,
                "op": op, "prev_head": prev or b"\0" * 32,
                "cert": cert_wire, "state": state, "model": model,
                "final": True}
        self._latest_snapshot = meta
        if self.snapshot_dir and model is not None:
            try:
                write_snapshot_file(self.snapshot_dir, meta)
                prune_snapshots(self.snapshot_dir, 2)
            except OSError:
                pass                    # a full disk must not stop the
                #                         follow loop; retried next snap
        gc = getattr(self.ledger, "gc_prefix", None)
        if gc is not None:
            dropped = gc(i + 1, state)
            if dropped:
                # mirrored certificates below the base go with the
                # prefix (the snapshot op's own cert stays: it is the
                # offer's chain-link evidence)
                self._certs = {k: v for k, v in self._certs.items()
                               if k >= i}
                if obs_metrics.REGISTRY.enabled:
                    _M_GC_OPS.inc(dropped)
                if self.verbose:
                    print(f"[standby {self.index}] GC: dropped {dropped} "
                          f"mirrored ops behind snapshot@{i}", flush=True)

    def _await_upload_payload(self, op_bytes: bytes,
                              ctl: CoordinatorClient,
                              writer: Endpoint) -> bool:
        """Block until the op's payload blob is mirrored (True), the
        writer authoritatively reports it unknown (False — apply with a
        clamped ack), or the writer dies (raises WriterDead — the op must
        NOT apply, or a promoted chain would hold a blob-less record)."""
        if not op_bytes or op_bytes[0] not in self._PAYLOAD_OPCODES:
            return True
        while not self._stop.is_set():
            self._blob_unknown = False
            if self._mirror_upload_payload(op_bytes, ctl):
                return True
            if self._blob_unknown:
                return False
            if not self._writer_alive(writer):
                raise WriterDead("writer died before the payload of a "
                                 "streamed upload could be mirrored")
            time.sleep(min(self.heartbeat_s, 0.25))
        raise WriterDead("standby stopping")

    def _drop_moot_payloads(self) -> None:
        """Unblock acks for blob-less records the chain has moved past:
        once the replayed epoch advances beyond an upload's epoch, its
        round is settled (aggregated or recovered over) and the missing
        blob can never be needed again."""
        if not self._pending_payload:
            return
        from bflc_demo_tpu.ledger.tool import decode_op
        buffered = None
        for i in list(self._pending_payload):
            op = self._pending_payload[i]
            if op and op[0] == 10:      # async upload (ledger.base)
                # moot once the entry drained from the admission buffer
                # (its base epoch says nothing — buffered entries
                # legitimately outlive epochs)
                if buffered is None:
                    view = getattr(self.ledger, "async_buffer_view",
                                   lambda: [])()
                    buffered = {e.payload_hash for e in view}
                try:
                    ph = bytes.fromhex(decode_op(op)["payload_hash"])
                except (KeyError, ValueError):
                    ph = None
                if ph is None or ph not in buffered:
                    del self._pending_payload[i]
                continue
            try:
                ep = int(decode_op(op)["epoch"])
            except (KeyError, ValueError):
                ep = None
            if ep is None or ep < self.ledger.epoch:
                del self._pending_payload[i]

    def _retry_pending_payloads(self, ctl: CoordinatorClient) -> None:
        """Re-attempt the blob fetch for every pending upload op, lowest
        index first (the ack clamp lifts exactly as the holes fill)."""
        self._drop_moot_payloads()
        for i in sorted(self._pending_payload):
            if self._mirror_upload_payload(self._pending_payload[i], ctl):
                del self._pending_payload[i]
            else:
                break                   # still missing: later retries moot

    def _send_ack(self, sub: CoordinatorClient, last_applied: int) -> None:
        """Ack the highest op this replica DURABLY holds: the latest
        applied op, clamped below any upload whose payload blob is still
        unmirrored (cumulative-watermark semantics upstream)."""
        ack = last_applied
        if self._pending_payload:
            ack = min(ack, min(self._pending_payload) - 1)
        if obs_metrics.REGISTRY.enabled:
            _G_APPLIED.set(last_applied + 1)
            _G_ACK_LAG.set(last_applied - ack)
        if ack < 0:
            return
        try:
            send_msg(sub.sock, {"ack": int(ack)})
        except (WireError, OSError):
            pass

    def _require_certificate(self, msg: dict, op_index: int,
                             op_bytes: bytes) -> None:
        """Verify + mirror the streamed op's commit certificate; raises
        RuntimeError (refusal, not failover) when it is absent/invalid."""
        from bflc_demo_tpu.comm.bft import verify_certificate
        from bflc_demo_tpu.protocol.types import CommitCertificate
        cert_wire = msg.get("cert")
        cert = None
        if isinstance(cert_wire, dict):
            try:
                cert = CommitCertificate.from_wire(cert_wire)
            except ValueError:
                cert = None
        prev = (self.ledger.log_head() if self.ledger.log_size()
                else b"\0" * 32)
        if cert is None or not verify_certificate(
                cert, index=op_index, prev_head=prev, op=op_bytes,
                quorum=self.bft_quorum, validator_keys=self.bft_keys):
            raise RuntimeError(
                f"standby {self.index}: op {msg.get('i')} arrived without "
                f"a valid commit certificate — Byzantine or misconfigured "
                f"writer, refusing to replicate uncertified state")
        self._certs[op_index] = cert_wire

    _UPLOAD_OPCODE = 2          # ledger op codec (ledger/tool.decode_op)
    _COMMIT_OPCODE = 4
    # async buffered aggregation (ledger.base): the payload/model blob
    # mirroring paths treat the async twins exactly like their sync
    # originals — an aupload references a payload blob, an acommit a new
    # model blob
    _PAYLOAD_OPCODES = (2, 10)
    _MODEL_OPCODES = (4, 12)

    def _harvest_pushed_blob(self, msg: dict, op_bytes: bytes) -> None:
        """Mirror an op-stream frame's piggybacked blob iff it hashes to
        the digest the op itself records (see _follow): an upload op's
        payload, or a commit op's new MODEL blob (data-plane fast path —
        the standby is then model-fresh the moment the commit applies,
        with no fetch round-trip, and its read fan-out can serve the
        round immediately)."""
        blob_field = msg.get("blob")
        if blob_field is None or not op_bytes:
            return
        from bflc_demo_tpu.ledger.tool import decode_op
        if op_bytes[0] in self._MODEL_OPCODES:
            try:
                blob = blob_bytes(blob_field)
                mh = bytes.fromhex(decode_op(op_bytes)["model_hash"])
            except (KeyError, ValueError):
                return
            if hashlib.sha256(blob).digest() == mh:
                self._model_blob = blob
            return
        if op_bytes[0] not in self._PAYLOAD_OPCODES:
            return
        try:
            blob = blob_bytes(blob_field)
            ph = bytes.fromhex(decode_op(op_bytes)["payload_hash"])
        except (KeyError, ValueError):
            return
        if ph not in self._blobs and hashlib.sha256(blob).digest() == ph:
            self._blobs[ph] = blob

    def _mirror_upload_payload(self, op_bytes: bytes,
                               ctl: CoordinatorClient) -> bool:
        """Fetch an upload op's payload blob by hash, bypassing the
        QueryAllUpdates round gate (which hides mid-round updates from
        `_sync_state`'s scan).  True = nothing to do or blob mirrored;
        False = this op's payload is still missing (caller withholds the
        quorum ack).  Non-upload ops always return True."""
        if not op_bytes or op_bytes[0] not in self._PAYLOAD_OPCODES:
            return True
        from bflc_demo_tpu.ledger.tool import decode_op
        try:
            ph = bytes.fromhex(decode_op(op_bytes)["payload_hash"])
        except (KeyError, ValueError):
            return True                 # undecodable: not a payload op
        if ph in self._blobs:
            return True
        try:
            with _M_MIRROR.time():
                r = ctl.request("blob", hash=ph.hex())
        except (ConnectionError, WireError, OSError):
            return False
        if r.get("ok"):
            try:
                blob = blob_bytes(r.get("blob", ""))
            except ValueError:
                blob = b""
            if hashlib.sha256(blob).digest() == ph:
                self._blobs[ph] = blob
                return True
            # the writer ANSWERED with bytes that do not hash to the
            # op's payload digest: a Byzantine or corrupt writer.  This
            # gets the same explicit refusal as an uncertified append —
            # never a silent retry wedge (review: the mirror-before-
            # apply loop would otherwise spin on it forever)
            raise RuntimeError(
                f"standby {self.index}: writer served a corrupt payload "
                f"blob for {ph.hex()[:12]} — Byzantine or corrupt "
                f"writer, refusing to replicate")
        # the writer ANSWERED and does not hold the blob: the round
        # already aggregated it away (blobs are dropped at commit) —
        # an authoritative negative, not a transport failure, so the
        # caller must not block replication on it forever
        self._blob_unknown = True
        return False

    def _sync_state(self, ctl: CoordinatorClient) -> None:
        """Mirror hash-referenced sideband state from the writer.

        Everything fetched is verified against the replayed ledger: update
        blobs by content hash, the model blob by the committed model hash,
        directory entries by address self-authentication — a lying or
        confused writer cannot poison the standby.

        Each mirror is gated on the replayed ledger's OWN counters, so a
        streamed op costs at most the RPCs its state change implies —
        never a full directory refetch or update rescan per op.
        """
        if self.ledger.update_count != self._synced_update_count:
            missing = [u.payload_hash
                       for u in self.ledger.query_all_updates()
                       if u.payload_hash not in self._blobs]
            if len(missing) > 1:
                # batched mirror (one round-trip; hash-verified per
                # part inside split_blob_parts); per-hash fallback below
                # covers whatever the writer omitted or a pre-batch peer
                from bflc_demo_tpu.comm.wire import split_blob_parts
                r = ctl.request("blobs",
                                hashes=[h.hex() for h in missing])
                if r.get("ok"):
                    for h, part in split_blob_parts(r).items():
                        self._blobs[bytes.fromhex(h)] = part
            all_stored = True
            for u in self.ledger.query_all_updates():
                if u.payload_hash not in self._blobs:
                    r = ctl.request("blob", hash=u.payload_hash.hex())
                    if r.get("ok"):
                        blob = blob_bytes(r["blob"])
                        if hashlib.sha256(blob).digest() == u.payload_hash:
                            self._blobs[u.payload_hash] = blob
                    if u.payload_hash not in self._blobs:
                        all_stored = False
            # only record the sync point when every wanted blob landed — a
            # transiently missed fetch must be retried on the next pass,
            # not silently deferred until update_count changes again
            if all_stored:
                self._synced_update_count = self.ledger.update_count
        want_hash, _ = self.ledger.query_global_model()
        have = (hashlib.sha256(self._model_blob).digest()
                if self._model_blob is not None else b"")
        if want_hash != have and want_hash != b"\0" * 32:
            r = ctl.request("model")
            if r.get("ok"):
                blob = blob_bytes(r["blob"])
                if hashlib.sha256(blob).digest() == want_hash:
                    self._model_blob = blob
        elif self._model_blob is None:
            # genesis window: until the first commit the ledger's model
            # hash is the zero digest, but the writer DOES hold the initial
            # model blob — mirror it now (hash-unverifiable by design at
            # genesis; every later commit re-checks), or a writer death
            # before round 0 commits would make promotion impossible
            r = ctl.request("model")
            if r.get("ok"):
                self._model_blob = blob_bytes(r["blob"])
        if self._directory is not None and \
                self.ledger.num_registered != self._synced_registered:
            r = ctl.request("directory")
            if r.get("ok"):
                for addr, pub_hex in r["keys"].items():
                    pub = bytes.fromhex(pub_hex)
                    if address_of(pub) == addr and \
                            not self._directory.knows(addr):
                        self._directory.enroll(pub)
                self._synced_registered = self.ledger.num_registered

    def _writer_info(self, ep: Endpoint) -> Optional[dict]:
        """The endpoint's `info` reply, or None when unreachable/broken."""
        try:
            probe = CoordinatorClient(ep[0], ep[1], timeout_s=2.0,
                                      tls=self.tls_client)
            try:
                inf = probe.request("info")
                return inf if inf.get("ok") else None
            finally:
                probe.close()
        except (ConnectionError, WireError, OSError):
            return None

    def _writer_alive(self, ep: Endpoint) -> bool:
        return self._writer_info(ep) is not None

    def _any_serving_peer(self) -> int:
        """Index of ANY endpoint currently serving at a generation not
        behind ours (ignores the priority order — used when this standby
        cannot or must not promote), or -1."""
        for j, ep in enumerate(self.endpoints):
            if j == self.index:
                continue
            inf = self._writer_info(ep)
            if inf is not None and \
                    int(inf.get("gen", 0)) >= self.ledger.generation:
                return j
        return -1

    # ------------------------------------------------------------- election
    def _elect(self) -> int:
        """Deterministic, lease-free: the LIVE endpoint with the highest
        priority (lowest index) wins.  'Live' for a peer standby means its
        port accepts a TCP connect (bound-in-backlog counts — it will
        promote or follow); a dead process refuses.  Returns the winning
        index, self.index when this standby should promote, or -1 when
        nothing is reachable (retry later)."""
        for j, ep in enumerate(self.endpoints):
            if j == self.index:
                return self.index
            if j == 0:
                inf = self._writer_info(ep)
                # a returned writer only wins if its fence is current: a
                # stale pre-partition writer (lower generation) must not
                # reclaim followers (split-brain defense)
                if inf is not None and \
                        int(inf.get("gen", 0)) >= self.ledger.generation:
                    return 0            # writer came back; keep following
                continue
            try:
                s = socket.create_connection(ep, timeout=1.0)
                s.close()
                return j                # higher-priority standby is alive
            except OSError:
                continue
        return -1

    # ------------------------------------------------------------ promotion
    def _rollback_last_op(self) -> None:
        """Drop the chain's final op (our failed fence) by replaying the
        prefix into a fresh ledger — quorum evidence just proved a
        foreign op is bound at that position."""
        from bflc_demo_tpu.ledger import clone_prefix
        upto = self.ledger.log_size() - 1
        self.ledger = clone_prefix(self.ledger, upto, self.cfg,
                                   backend=self._ledger_backend)
        self._certs.pop(upto, None)

    _PROMOTE_OPCODE = 8         # ledger op codec (ledger/tool.decode_op)

    def _certify_promotion(self) -> None:
        """Gather a validator quorum certificate for the just-appended
        promote op; a promotion that cannot certify must NOT serve (BFT
        clients would reject every ack, and rightly so).  This doubles as
        leader arbitration: validators sign one op per chain position and
        attempt, so two standbys racing to promote at the same index
        cannot both win — the loser's repair round MANDATES the winner's
        fence op and this raises PromotionSuperseded (fence op rolled
        back; the caller re-follows the winner).

        A mandated foreign op that is NOT a fence belongs to a DEAD
        proposer (the old writer's stranded-but-possibly-certified last
        op — its voters survive, its process did not): re-following
        would spin on a ghost, so the standby ADOPTS it — certifies it
        at this position (holders re-sign idempotently; no client auth
        is needed for a re-sign), splices it under the fence, and
        re-fences at the next position.  The record's payload blob, if
        any, arrives through the uploader's signed retry (the certified
        DUPLICATE-ack path).  An unreachable quorum (partition, crashed
        validators) is retried until it heals or the standby is
        stopped: certification unavailability must degrade to delay,
        never to a dead failover ladder.
        """
        from bflc_demo_tpu.comm.bft import (CertificateAssembler,
                                            PrefixCompacted)
        from bflc_demo_tpu.comm.ledger_service import chain_head_at

        def _backlog(j: int):
            # a validator that lagged the dead writer resyncs from this
            # standby's mirrored certificates (auth evidence died with
            # the writer; the certs carry the quorum's admission).
            # Below this replica's GC'd base the op bytes are gone: hand
            # the assembler the mirrored snapshot offer so the lagging
            # validator state-syncs (`bft_snapshot`) instead of the
            # vote thread dying on the raw IndexError
            base = getattr(self.ledger, "log_base", 0)
            if j < base:
                raise PrefixCompacted(self._latest_snapshot, base)
            return (self.ledger.log_op(j), None, self._certs.get(j))

        assembler = CertificateAssembler(
            self.bft_validators, self.bft_keys, self.bft_quorum,
            timeout_s=self.bft_timeout_s, tls=None,
            backlog_fn=_backlog)
        try:
            while not self._stop.is_set():
                ix = self.ledger.log_size() - 1
                op = self.ledger.log_op(ix)
                prev = chain_head_at(self.ledger, ix) or b"\0" * 32
                cert = assembler.certify(ix, op, None, prev)
                if cert is not None:
                    self._certs[ix] = cert.to_wire()
                    return
                mop = assembler.superseded_op
                if mop is not None:
                    if mop[:1] == bytes([self._PROMOTE_OPCODE]):
                        # a LIVE rival's fence won the position
                        self._rollback_last_op()
                        raise PromotionSuperseded(
                            f"standby {self.index}: a foreign fence op "
                            f"is bound at position {ix}")
                    mcert = assembler.certify(ix, mop, None, prev)
                    if mcert is not None:
                        self._rollback_last_op()    # drop our fence
                        st = self.ledger.apply_op(mop)
                        if st != LedgerStatus.OK:
                            raise RuntimeError(
                                f"standby {self.index}: mandated op at "
                                f"{ix} does not apply: {st.name}")
                        self._certs[ix] = mcert.to_wire()
                        st = self.ledger.promote_writer(
                            self.ledger.generation + 1, self.index)
                        if st != LedgerStatus.OK:
                            raise RuntimeError(
                                f"re-fence rejected: {st.name}")
                        if self.verbose:
                            print(f"[standby {self.index}] adopted the "
                                  f"dead writer's stranded op at {ix}; "
                                  f"re-fencing at {ix + 1}", flush=True)
                        continue
                if self.verbose:
                    print(f"[standby {self.index}] promotion fence op "
                          f"gathered no validator quorum yet; retrying",
                          flush=True)
                time.sleep(max(self.heartbeat_s, 0.5))
        finally:
            assembler.close()
        raise RuntimeError(
            f"standby {self.index}: stopped before the promotion fence "
            f"op certified")

    def _promote_and_serve(self) -> None:
        if self._model_blob is None:
            raise RuntimeError("cannot promote: no model blob mirrored yet")
        if self.read_server is not None:
            # the promoted LedgerServer copies the blob store, so this
            # side port would serve a frozen snapshot — close it; the
            # writer's read set drops the endpoint when the subscription
            # dies and clients fall back to the (new) coordinator
            self.read_server.close()
            self.read_server = None
        # the promotion FENCE: an op in the replicated chain itself.  Every
        # replica that replays this log knows generation N+1's writer; a
        # pre-partition writer still serving generation N self-demotes the
        # moment any fence-carrying request reaches it (ledger_service).
        st = self.ledger.promote_writer(self.ledger.generation + 1,
                                        self.index)
        if st != LedgerStatus.OK:
            raise RuntimeError(f"promotion fence rejected: {st.name}")
        if self.bft_keys:
            self._certify_promotion()
        evidence = None
        if self.wallet is not None:
            from bflc_demo_tpu.comm.ledger_service import \
                make_promotion_evidence
            evidence = make_promotion_evidence(self.ledger, self.wallet,
                                               self.index)
            if self.bft_keys:
                # the evidence CITES the highest certified op — which in
                # BFT mode is the promote op itself (this standby refused
                # every uncertified append and just certified its fence),
                # so a verifier knows the promotion extends quorum-signed
                # history, not a private fork
                evidence["cert_ix"] = self.ledger.log_size() - 1
        missing = [u.payload_hash.hex()[:12]
                   for u in self.ledger.query_all_updates()
                   if u.payload_hash not in self._blobs]
        if missing and self.verbose:
            print(f"[standby {self.index}] promoting with {len(missing)} "
                  f"unmirrored update blobs {missing} — relying on "
                  f"uploader retries / stall recovery", flush=True)
        self.server = LedgerServer(
            self.cfg, self._model_blob,
            directory=self._directory,
            require_auth=self.require_auth,
            stall_timeout_s=self.stall_timeout_s,
            resume_ledger=self.ledger,
            resume_blobs=self._blobs,
            sock=self._sock,
            tls=self.tls_server,
            wal_path=self.wal_path,
            standby_keys=self.standby_keys,
            promotion_evidence=evidence,
            quorum=self.quorum,
            quorum_timeout_s=self.quorum_timeout_s,
            bft_validators=self.bft_validators or None,
            bft_keys=self.bft_keys or None,
            bft_quorum=self.bft_quorum or None,
            bft_timeout_s=self.bft_timeout_s,
            resume_certs=dict(self._certs) if self.bft_keys else None,
            snapshot_interval=self.snapshot_interval,
            snapshot_dir=self.snapshot_dir,
            resume_snapshot=self._latest_snapshot,
            verbose=self.verbose)
        # open enrollment on the promoted writer: a client the directory
        # missed re-presents its (self-authenticating) pubkey on register
        self.server._open_enrollment = True
        _M_PROMOTIONS.inc(outcome="promoted")
        obs_flight.FLIGHT.record(
            "event", "standby_promoted", index=self.index,
            gen=self.ledger.generation, epoch=self.ledger.epoch,
            log_size=self.ledger.log_size())
        obs_flight.FLIGHT.flush("promoted")
        self.promoted.set()
        if self.verbose:
            print(f"[standby {self.index}] promoted: serving on "
                  f"{self.host}:{self.port} at epoch {self.ledger.epoch}",
                  flush=True)
        self.server.serve_forever()
