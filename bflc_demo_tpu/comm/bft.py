"""Byzantine no-fork commits: quorum-validated, co-signed ledger binding.

The reference's substrate is a 4-node PBFT chain: every `Aggregate` /
`UploadLocalUpdate` executes on ALL nodes and a 2f+1 quorum must agree
before the result binds, so one arbitrarily faulty node can neither fork
history nor fabricate state (README.md:162-183; every
`sendRawTransactionGetReceipt` in python-sdk/main.py:160,219 is a consensus
boundary).  Rounds 2-5 reproduced replication, failover, fencing and
quorum-ACK durability — all fail-stop properties.  This module reproduces
the *Byzantine* property for the writer itself:

- a fleet of **validators** (`ValidatorNode`) each holds its own replica
  of the chain.  Before an op binds, the writer must collect a **commit
  certificate**: `bft_quorum(n)` validators independently re-execute the
  op against their replicas — the full guard set (epoch / role / cap /
  duplicate, `ledger.validate_op`) PLUS the client's Ed25519 op tag for
  client-originated ops — and co-sign `(index, chain_prefix_digest,
  op_digest, resulting_head)` with their comm.identity wallets;
- a validator signs **at most one op per chain position** and refuses
  client ops whose tag does not verify against its own mirrored key
  directory, so a writer that fabricates a score row, drops a client's
  op, or equivocates (different ops to different validators) can never
  gather a quorum: any two quorums intersect in an honest validator;
- the writer may only ACK — and clients (`FailoverClient(bft_keys=...)`)
  and standbys (`Standby(bft_keys=...)`) only accept — state that carries
  a valid certificate.  At the reference's 4-validator geometry this
  tolerates f=1 crashed OR lying validators (protocol.constants.bft_*).

Liveness (round 7): certification no longer stalls permanently when
validator replicas diverge at the chain tip (a writer that died
mid-certify, a promotion racing the old writer's last op, or an outright
equivocating writer).  Two repair paths restore progress:

- **resync-and-retry**: a validator that bound a different op at the tip
  accepts a quorum CERTIFICATE for the competing op as proof that the
  canonical chain holds that op, rolls its replica back to the certified
  prefix, re-applies, and re-votes (`ValidatorNode._admit_certified`);
- **re-proposal**: when no certificate exists at all (votes split below
  quorum), the proposer runs an abandon round at a higher ATTEMPT number:
  each validator returns a signed statement of what it holds at the
  position and promises to reject lower attempts; 2f+1 statements form a
  repair proof whose MANDATE rule (any op reported by >= f+1 statements
  must be re-proposed; at most one op can reach f+1 in a 2f+1 set) makes
  re-votes safe — an op that could have certified is always the mandated
  one.  Votes and certificates are attempt-tagged so old-attempt and
  new-attempt signatures can never mix into a thin quorum
  (`CertificateAssembler.certify` drives the loop; a proposer whose own
  op loses the mandate learns the canonical op via
  `CertificateAssembler.superseded_op` — a stale writer self-demotes, a
  racing standby re-follows the winner).

The last writer-trust axis — the commit op's MODEL HASH, historically
taken on writer authority — is closed by the opt-in re-derivation plane
(bflc_demo_tpu.rederive, `--rederive {shard,full}`): an armed validator
fetches the round's admitted deltas through the data-plane read path
(hash-verified against upload ops it already co-signed), re-runs the
deterministic decode + REDUCTION SPEC v1 merge on its own replica's
selection, and REFUSES (status ``REDERIVE``) a commit whose hash it
cannot reproduce — with unavailability degrading to the historical
guard-check as a counted, WARNed skip (never a wedge), and
certified-backlog/rejoin ops admitting on their certificate.

Deliberate non-goals, documented rather than implied (PARITY.md):
reads are not certified; client-originated ops still require auth
evidence (or an existing certificate) on the repair path — a repair
proof authorizes the ROLLBACK, never an auth bypass; and the repair
mandate's f+1 threshold protects any possibly-certified op against f
lying validators OR an arbitrarily equivocating writer, but not both
colluding at once (the same compound fault PBFT needs its second phase
for — documented in PARITY.md).

Deployment note: validator ports belong on the coordinator-side network
segment (like standby subscriptions).  The drill in tests/test_bft.py is
the module's specification: a hostile writer forging a score row, dropping
an acknowledged upload, and forking history fails certification while one
crashed-or-lying validator is tolerated.
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from bflc_demo_tpu.comm.identity import (PublicDirectory, _op_bytes,
                                         address_of, verify_signature,
                                         verify_signatures_batch)
from bflc_demo_tpu.obs import flight as obs_flight
from bflc_demo_tpu.obs import metrics as obs_metrics
from bflc_demo_tpu.obs import trace as obs_trace
from bflc_demo_tpu.utils import tracing
from bflc_demo_tpu.comm.wire import WireError, recv_msg, send_msg
from bflc_demo_tpu.ledger import LedgerStatus, make_ledger
from bflc_demo_tpu.ledger.base import (encode_register_op,
                                       encode_scores_op, encode_upload_op)
from bflc_demo_tpu.protocol.constants import ProtocolConfig, bft_quorum
from bflc_demo_tpu.protocol.types import CommitCertificate

Endpoint = Tuple[str, int]


class PrefixCompacted(Exception):
    """A backlog position below the writer's GC'd snapshot base was
    requested: the op bytes are gone (ledger.snapshot).  Carries the
    writer's snapshot OFFER so the assembler can state-sync the lagging
    validator (`bft_snapshot`) instead of replaying the prefix."""

    def __init__(self, offer, base: int):
        super().__init__(f"log prefix compacted below {base}")
        self.offer = offer              # snapshot meta dict or None
        self.base = base


_CERT_MAGIC = b"BFLCCERT1"
_EMPTY_HEAD = b"\0" * 32        # head digest of the empty chain (log_head())

# ledger op codec (must match pyledger/ledger.cpp opcode table);
# 10/11 are the async buffered-aggregation client ops (ledger.base),
# 4/12 the sync/async COMMIT ops the re-derivation plane judges
_OP_REGISTER, _OP_UPLOAD, _OP_SCORES = 1, 2, 3
_OP_AUPLOAD, _OP_ASCORES = 10, 11
_OP_COMMIT, _OP_ACOMMIT = 4, 12

# --- validator-side telemetry (obs.metrics; no-ops unless the process
# registry is enabled): vote latency by transport shape, refusals by
# status, and the liveness-repair event counters a chaos post-mortem
# correlates with fault windows.
_M_VOTE = obs_metrics.REGISTRY.histogram(
    "vote_latency_seconds",
    "validator-side validate+sign time per request", ("kind",))
_M_REFUSE = obs_metrics.REGISTRY.counter(
    "vote_refusals_total", "refused vote requests by status", ("status",))
_M_REPAIR = obs_metrics.REGISTRY.counter(
    "repair_events_total",
    "quorum-evidence rollbacks applied (certificate resync or "
    "repair-proof re-proposal)", ("kind",))
_M_ABANDON = obs_metrics.REGISTRY.counter(
    "abandon_events_total", "signed abandon statements issued")
_G_VLOG = obs_metrics.REGISTRY.gauge(
    "validator_log_size", "replica chain length at last scrape")
_M_RL_XCHECK = obs_metrics.REGISTRY.counter(
    "rederive_crosscheck_total",
    "assembler-side per-leaf digest cross-checks over the commit "
    "votes forming a certificate (rederive plane)", ("result",))


def cert_payload_digest(index: int, prev_head: bytes, op_digest: bytes,
                        new_head: bytes, attempt: int = 0) -> bytes:
    """THE byte layout a validator signs — the one encoder every signing
    and verification site shares, so the layout cannot desynchronize.
    The ATTEMPT number is part of the payload: one certificate's quorum
    must all have signed at the same attempt, or a repair round could mix
    pre- and post-repair votes for different ops into a thin quorum."""
    return (_CERT_MAGIC + struct.pack("<q", index)
            + (prev_head or _EMPTY_HEAD) + op_digest + new_head
            + struct.pack("<q", attempt))


def cert_payload(index: int, prev_head: bytes, op: bytes,
                 new_head: bytes, attempt: int = 0) -> bytes:
    """The byte string a validator signs: position + chain prefix + op
    digest + resulting head (+ attempt).  Binding the PREFIX digest (not
    just the op) is what makes certificates fork-proof — a signature
    minted on one history is meaningless on any other."""
    return cert_payload_digest(index, prev_head,
                               hashlib.sha256(op).digest(), new_head,
                               attempt)


def next_head(prev_head: bytes, op: bytes) -> bytes:
    """The chain rule (ledger.cpp append_log / pyledger._append_log):
    head' = SHA-256(head || op), with the empty chain contributing no
    prefix bytes."""
    d = hashlib.sha256()
    if prev_head and prev_head != _EMPTY_HEAD:
        d.update(prev_head)
    d.update(op)
    return d.digest()


def verify_certificate(cert: CommitCertificate, *, index: int,
                       prev_head: bytes, op: bytes, quorum: int,
                       validator_keys: Dict[int, bytes]) -> bool:
    """Full verification for a party that HOLDS the chain (standby /
    promoted writer): the certificate must bind exactly (index, our
    prefix head, this op, the implied next head) and carry >= quorum
    valid signatures by DISTINCT provisioned validators."""
    new_head = next_head(prev_head, op)
    if (cert.index != index
            or (cert.prev_head or _EMPTY_HEAD) != (prev_head or _EMPTY_HEAD)
            or cert.op_hash != hashlib.sha256(op).digest()
            or cert.new_head != new_head):
        return False
    return count_valid_sigs(cert, validator_keys) >= quorum


def count_valid_sigs(cert: CommitCertificate,
                     validator_keys: Dict[int, bytes]) -> int:
    """Signatures by distinct PROVISIONED validators that verify over the
    certificate's own payload (including its claimed attempt).  Shared by
    full verification and the client-side structural check.

    Fast path (PR 3): all provisioned sigs are checked in ONE batch
    verification (comm.identity.verify_signatures_batch) — the common
    all-honest certificate pays one shared multiscalar mul instead of a
    ladder per signature; any batch failure falls back to the per-sig
    loop, so the count is always attributable."""
    payload = cert_payload_digest(cert.index, cert.prev_head,
                                  cert.op_hash, cert.new_head,
                                  cert.attempt)
    items = [(pub, payload, sig) for vidx, sig in cert.sigs.items()
             if (pub := validator_keys.get(vidx)) is not None]
    if items and verify_signatures_batch(items):
        return len(items)
    return sum(1 for pub, msg, sig in items
               if verify_signature(pub, msg, sig))


def verify_certificate_sigs(cert_wire, quorum: int,
                            validator_keys: Dict[int, bytes],
                            op_hash: Optional[bytes] = None) -> bool:
    """Client-side acceptance check (no chain held): the certificate's
    quorum signatures are authentic over its OWN claimed binding, and —
    when the caller supplies `op_hash` — the certificate binds THAT op.

    Always pass op_hash when checking the ack for your own mutation
    (`expected_op_hash` reconstructs it from the request fields): without
    it, a Byzantine writer that once certified ANY op honestly could
    replay that old certificate on a forged ack for a dropped or
    fabricated op.  A hostile writer cannot forge the signatures (only
    validators hold the keys, and they sign only ops their replicas
    accepted), so sigs + op binding together prove a quorum bound this
    exact op.  Never raises on malformed input."""
    try:
        cert = (cert_wire if isinstance(cert_wire, CommitCertificate)
                else CommitCertificate.from_wire(cert_wire))
    except (ValueError, TypeError):
        return False
    if op_hash is not None and cert.op_hash != op_hash:
        return False
    return count_valid_sigs(cert, validator_keys) >= quorum


# ------------------------------------------------ canonical op encoding
# The encoders are shared with PyLedger's append sites (ledger.base — one
# definition) so a party holding only the REQUEST fields can reconstruct
# the op bytes the writer must have appended — the request->certificate
# binding both the server (attaching the right cert to a DUPLICATE-class
# reply) and the client (rejecting replayed certificates) depend on.

def expected_op_hash(method: str, fields: dict) -> Optional[bytes]:
    """sha256 of the op the writer must append for this request — None
    when the method is not a client mutation or the fields are
    malformed (callers then skip the binding check)."""
    try:
        if method == "register":
            op = encode_register_op(fields["addr"])
        elif method == "upload":
            op = encode_upload_op(fields["addr"],
                                  bytes.fromhex(fields["hash"]),
                                  int(fields["n"]), float(fields["cost"]),
                                  int(fields["epoch"]))
        elif method == "scores":
            op = encode_scores_op(fields["addr"], int(fields["epoch"]),
                                  [float(s) for s in fields["scores"]])
        elif method == "aupload":
            from bflc_demo_tpu.ledger.base import encode_aupload_op
            op = encode_aupload_op(fields["addr"],
                                   bytes.fromhex(fields["hash"]),
                                   int(fields["n"]),
                                   float(fields["cost"]),
                                   int(fields["base_epoch"]))
        elif method == "ascores":
            from bflc_demo_tpu.ledger.base import encode_ascores_op
            op = encode_ascores_op(
                fields["addr"],
                [(int(a), float(s)) for a, s in fields["pairs"]])
        else:
            return None
        return hashlib.sha256(op).digest()
    except (KeyError, TypeError, ValueError):
        return None


# --------------------------------------------------------------- op auth
def check_op_auth(op: bytes, auth: Optional[dict],
                  directory: PublicDirectory) -> str:
    """'' when `op` is admissible w.r.t. origin authentication; a reason
    string otherwise.

    Client-originated ops (register/upload/scores) must carry the
    client's Ed25519 tag in `auth`, verified against the validator's OWN
    directory mirror — this is precisely what stops a Byzantine writer
    from fabricating a score row: it cannot produce a committee member's
    signature.  The float fields need care: tags sign the client's f64
    payload while ops store f32, so `auth` carries the original f64
    values and this check pins op bytes == exact f32 quantisation of the
    signed values.  Coordinator-authority ops (commit/close/force/
    reseat/promote) carry no tag — their admissibility is the replica
    re-execution (`validate_op`), the same authority split the
    AuthenticatedLedger applies.
    """
    if not op or op[0] not in (_OP_REGISTER, _OP_UPLOAD, _OP_SCORES,
                               _OP_AUPLOAD, _OP_ASCORES):
        return ""
    if not isinstance(auth, dict):
        return "client op without auth evidence"
    body = op[1:]

    def _tofu_repair(sender: str) -> None:
        """Self-authenticating directory repair: auth evidence for every
        client op carries the sender's pubkey, so a validator whose
        directory mirror has a hole (rejoined through a writer that
        itself promoted mid-registration — the chain stores addresses,
        not keys) heals on the next fresh op instead of refusing that
        client forever.  Safe by construction: the address IS the key's
        hash, and the op tag must still verify under it."""
        if directory.knows(sender):
            return
        try:
            pub = bytes.fromhex(auth.get("pubkey", ""))
        except (TypeError, ValueError):
            return
        if pub and address_of(pub) == sender:
            directory.enroll(pub)

    def _str_at(off):
        (n,) = struct.unpack_from("<q", body, off)
        if n < 0 or off + 8 + n > len(body):
            raise ValueError("string past end of op")
        return body[off + 8:off + 8 + n].decode(), off + 8 + n

    try:
        tag = bytes.fromhex(auth["tag"])
        if op[0] == _OP_REGISTER:
            addr, _ = _str_at(0)
            pub = bytes.fromhex(auth.get("pubkey", ""))
            if not directory.knows(addr):
                if address_of(pub) != addr:
                    return "register: address/pubkey mismatch"
                directory.enroll(pub)
            if not directory.verify(addr, _op_bytes("register", addr, 0,
                                                    b""), tag):
                return "register: bad tag"
            return ""
        if op[0] in (_OP_UPLOAD, _OP_AUPLOAD):
            # async upload shares the upload layout; the trailing epoch
            # is the BASE epoch the tag binds (kind "aupload")
            kind = "upload" if op[0] == _OP_UPLOAD else "aupload"
            sender, off = _str_at(0)
            payload_hash = body[off:off + 32]
            ns, = struct.unpack_from("<q", body, off + 32)
            cost_f32, = struct.unpack_from("<f", body, off + 40)
            epoch, = struct.unpack_from("<q", body, off + 44)
            n, cost = int(auth["n"]), float(auth["cost"])
            if n != ns:
                return f"{kind}: n_samples mismatch"
            if struct.pack("<f", np.float32(cost)) != \
                    struct.pack("<f", cost_f32):
                return f"{kind}: cost not the f32 image of the signed value"
            payload = payload_hash + struct.pack("<qd", n, cost)
            _tofu_repair(sender)
            if not directory.verify(sender, _op_bytes(kind, sender,
                                                      epoch, payload), tag):
                return (f"{kind}: bad tag (sender {sender[:12]}, "
                        f"epoch {epoch}, "
                        f"known={directory.knows(sender)})")
            return ""
        if op[0] == _OP_ASCORES:
            from bflc_demo_tpu.ledger.base import ascores_sign_payload
            sender, off = _str_at(0)
            cnt, = struct.unpack_from("<q", body, off)
            if cnt <= 0 or off + 8 + 12 * cnt > len(body):
                return "ascores: malformed op"
            pairs = [(int(a), float(s)) for a, s in auth["pairs"]]
            if len(pairs) != cnt:
                return "ascores: pair count mismatch"
            p = off + 8
            for aseq, claimed in pairs:
                got_a, = struct.unpack_from("<q", body, p)
                got_s, = struct.unpack_from("<f", body, p + 8)
                if got_a != aseq or struct.pack(
                        "<f", np.float32(claimed)) != \
                        struct.pack("<f", got_s):
                    return ("ascores: pairs not the f32 image of the "
                            "signed values")
                p += 12
            _tofu_repair(sender)
            if not directory.verify(
                    sender, _op_bytes("ascores", sender, 0,
                                      ascores_sign_payload(pairs)), tag):
                return (f"ascores: bad tag (sender {sender[:12]}, "
                        f"known={directory.knows(sender)})")
            return ""
        # _OP_SCORES
        sender, off = _str_at(0)
        epoch, = struct.unpack_from("<q", body, off)
        cnt, = struct.unpack_from("<q", body, off + 8)
        if cnt < 0 or off + 16 + 4 * cnt > len(body):
            return "scores: malformed op"
        row_f32 = struct.unpack_from(f"<{cnt}f", body, off + 16)
        scores = [float(s) for s in auth["scores"]]
        if len(scores) != cnt:
            return "scores: row length mismatch"
        for got, claimed in zip(row_f32, scores):
            if struct.pack("<f", np.float32(claimed)) != \
                    struct.pack("<f", got):
                return "scores: row not the f32 image of the signed values"
        payload = struct.pack(f"<{len(scores)}d", *scores)
        _tofu_repair(sender)
        if not directory.verify(sender, _op_bytes("scores", sender, epoch,
                                                  payload), tag):
            return (f"scores: bad tag (sender {sender[:12]}, "
                    f"epoch {epoch}, known={directory.knows(sender)})")
        return ""
    except (KeyError, TypeError, ValueError, struct.error,
            UnicodeDecodeError) as e:
        return f"undecodable op/auth: {type(e).__name__}: {e}"


def check_sparse_upload_op(op: bytes, auth: Optional[dict]) -> str:
    """'' when a sparse-mode upload/aupload op's payload blob decodes
    through the ONE densify inverse; a reason string otherwise.

    The validator half of sparse admission re-execution (the writer
    half is `ledger_service._decode_delta`): with the fleet density-
    armed, upload auth evidence must carry the (small — that is the
    point of sparsification) blob whose sha256 equals the op's payload
    hash, and `densify_entries(dequantize_entries(...))` must accept
    it — so a colluding writer can no more certify a malformed `#topk`
    or `#sketch` blob than it can forge a client tag.  Validators hold
    no model schema (that stays writer-side admission); what they pin
    is the content binding plus the structural sparse contract —
    in-bounds, strictly ascending, count-consistent indices for top-k
    records; sane geometry, matching table size and bounded claimed
    extent for count-sketch records (the records are self-describing,
    so BOTH codecs re-execute through the one decode chain with no
    codec switch here).  Only call in sparse mode — dense fleets carry
    no blob evidence and must not start."""
    if not op or op[0] not in (_OP_UPLOAD, _OP_AUPLOAD):
        return ""
    body = op[1:]
    try:
        (slen,) = struct.unpack_from("<q", body, 0)
        if slen < 0 or 8 + slen + 32 > len(body):
            return "sparse: malformed upload body"
        payload_hash = body[8 + slen:8 + slen + 32]
    except struct.error as e:
        return f"sparse: undecodable op ({e})"
    if not isinstance(auth, dict) or "blob" not in auth:
        return ("sparse: upload op without blob evidence (density-"
                "armed quorum requires it)")
    try:
        blob = bytes.fromhex(auth["blob"])
    except (TypeError, ValueError):
        return "sparse: unparseable blob evidence"
    if hashlib.sha256(blob).digest() != payload_hash:
        return "sparse: blob evidence does not match the op's payload hash"
    from bflc_demo_tpu.utils.serialization import (densify_entries,
                                                   dequantize_entries,
                                                   unpack_pytree)
    try:
        densify_entries(dequantize_entries(unpack_pytree(blob)))
    except (ValueError, TypeError, struct.error) as e:
        return f"sparse: blob refused by densify ({e})"
    return ""


# ------------------------------------------------- repair (liveness) layer
_ABANDON_MAGIC = b"BFLCABDN1"


def abandon_stmt_payload(index: int, attempt: int, validator: int,
                         has_vote: bool, voted_attempt: int,
                         op_digest: bytes) -> bytes:
    """The byte layout of one signed abandon statement: 'at repair attempt
    `attempt` for chain position `index`, I hold `op_digest` (voted at
    `voted_attempt`) — or nothing — and I promise to refuse votes below
    `attempt` here.'  Binding the attempt makes old proofs unreplayable
    at later repair rounds."""
    return (_ABANDON_MAGIC
            + struct.pack("<qqII", index, attempt, validator,
                          1 if has_vote else 0)
            + struct.pack("<q", voted_attempt)
            + (op_digest or b"\0" * 32))


def verify_repair_proof(proof, index: int, attempt: int, quorum: int,
                        validator_keys: Dict[int, bytes],
                        ) -> Tuple[bool, Optional[bytes], Optional[bytes]]:
    """Check a repair proof for (index, attempt): >= quorum signed abandon
    statements by distinct provisioned validators, exactly at this
    position and attempt.

    Returns (ok, mandated_op_hash, mandated_op_bytes).  The MANDATE rule
    is evidence-exact: an op is mandated iff it COULD have gathered a
    certificate given what the statements rule out — reports(op) +
    (n - statements) >= quorum.  If a certificate exists (>= quorum
    voters), any statement set keeps it above the bar (honest voters
    report truthfully), so the mandate always protects a
    possibly-certified op; with every validator reporting, the counts
    are exact and a merely-STRANDED op (a dead proposer's partial votes,
    below quorum) is correctly left unmandated — the proposer is free,
    which is what keeps a crashed writer's leftovers from wedging its
    successor.  Two ops can never both clear the bar (they would need
    more reports than statements exist), so the mandate is unique; and
    f lying validators alone cannot reach it (the bar is always
    >= f+1).  No mandate (None) means no op can have certified: the
    proposer may re-propose freely.  Never raises on malformed input."""
    try:
        stmts = list(proof["stmts"])
    except (KeyError, TypeError):
        return False, None, None
    seen: Dict[int, Tuple[bytes, bytes]] = {}   # validator -> (hash, op)
    distinct = set()
    for s in stmts:
        try:
            v = int(s["validator"])
            has_vote = bool(s.get("has_vote"))
            voted_t = int(s.get("voted_t", 0))
            oh = bytes.fromhex(s["op_hash"]) if has_vote else b""
            ob = bytes.fromhex(s.get("op", "")) if has_vote else b""
            sig = bytes.fromhex(s["sig"])
        except (KeyError, TypeError, ValueError):
            continue
        pub = validator_keys.get(v)
        if pub is None or v in distinct:
            continue
        payload = abandon_stmt_payload(index, attempt, v, has_vote,
                                       voted_t, oh)
        if not verify_signature(pub, payload, sig):
            continue
        distinct.add(v)
        # op bytes ride unsigned next to the signed digest: check them
        if has_vote and oh and hashlib.sha256(ob).digest() == oh:
            seen[v] = (oh, ob)
    if len(distinct) < quorum:
        return False, None, None
    counts: Dict[bytes, int] = {}
    for oh, _ in seen.values():
        counts[oh] = counts.get(oh, 0) + 1
    # evidence-exact bar: non-reporting validators might all have voted
    # the op, so it could have certified iff reports + missing >= quorum
    bar = quorum - (len(validator_keys) - len(distinct))
    mandated = [oh for oh, c in counts.items() if c >= max(bar, 1)]
    if len(mandated) != 1:
        # zero ops clear the bar (nothing can have certified) — or, out
        # of an abundance of caution, several do (unreachable by the
        # counting argument: two ops clearing it need more reports than
        # statements): the proposer chooses freely
        return True, None, None
    oh = mandated[0]
    ob = next(b for h, b in seen.values() if h == oh)
    return True, oh, ob


# --------------------------------------------------------------- validator
class ValidatorNode:
    """One member of the commit quorum: replica + wallet + vote server.

    Serves four methods over comm.wire frames:
    - ``bft_validate {i, op, auth?, t?, cert?, repair?}``: validate op for
      chain position i at attempt t.  At most one vote per (position,
      attempt); ops arrive strictly in order (``OUT_OF_ORDER`` + our log
      size tells a lagging writer what to resend); re-requests for an
      already-applied identical op re-sign idempotently (a writer
      retrying after a lost reply must not wedge).  A DIFFERENT op at a
      bound tip position is re-voted only on quorum evidence: an
      existing commit certificate for it (resync-and-retry) or a valid
      repair proof whose mandate admits it (re-proposal) — the replica
      rolls back to the certified prefix, re-applies, and re-signs.
    - ``bft_vote_batch {i, ops, auths?, t?}`` (PR 3): validate + co-sign
      a CONTIGUOUS op range [i, i+len(ops)) in one round-trip — the
      per-op certificates are byte-identical to the single-op path
      (same cert_payload layout, each op chain-linked via its own
      prev-head), only the transport is amortized.  The fast path stops
      at the first op it cannot sign outright (conflict, auth failure,
      promise) and returns the refusal alongside the votes already
      minted; the writer falls back to ``bft_validate`` for that
      position, where the full certificate/repair evidence machinery
      lives untouched.
    - ``bft_abandon {i, t}``: issue a signed abandon statement for the
      position (what we hold there, if anything) and promise to refuse
      votes below attempt t — the repair round's raw material.
    - ``info``: replica position (log_size/log_head/epoch; pass ``at`` for
      the head at an earlier index), the resync + invariant-monitor
      surface.

    The node APPLIES an op the moment it votes for it: its vote is a
    promise that this op IS position i of its chain, which is exactly
    what makes a second, different op at i unsignable ("CONFLICT")
    without quorum evidence.
    """

    def __init__(self, cfg: ProtocolConfig, wallet, index: int, *,
                 host: str = "127.0.0.1", port: int = 0,
                 ledger_backend: str = "python",
                 require_auth: bool = True,
                 directory: Optional[PublicDirectory] = None,
                 validator_keys: Optional[Dict[int, bytes]] = None,
                 quorum: Optional[int] = None,
                 cell_registry: Optional[Dict[str, Tuple[int, int]]] = None,
                 rederive: Optional[str] = None,
                 initial_model_blob: Optional[bytes] = None,
                 verbose: bool = False):
        cfg.validate()
        self.cfg = cfg
        self.wallet = wallet
        self.index = index
        self.require_auth = require_auth
        self._ledger_backend = ledger_backend
        # peer validator public keys: with these provisioned, a backlog op
        # carrying an existing quorum CERTIFICATE is admitted without
        # client auth evidence — the quorum already re-verified the tag,
        # and auth evidence is writer-process-local, so a validator that
        # restarts after a failover could otherwise never resync past
        # historical client ops (the f-tolerance must cover validator
        # crash + rejoin, not just crash)
        self.validator_keys: Dict[int, bytes] = dict(validator_keys or {})
        if self.validator_keys and quorum is None:
            quorum = bft_quorum(len(self.validator_keys))
        self.quorum = quorum or 0
        self.verbose = verbose
        # python backend by default: validate_op is O(1) snapshot/restore
        # there, O(chain) through the native mirror fallback
        self.ledger = make_ledger(cfg, backend=ledger_backend)
        self.directory = directory if directory is not None \
            else PublicDirectory()
        # hierarchical cell federation (bflc_demo_tpu.hier): on a ROOT
        # quorum, every upload op is a cell-aggregate whose `n` field is
        # the cell's claimed client count — a validator holding the
        # registry refuses to co-sign an op from an unregistered sender
        # or one whose count exceeds that cell's registered membership,
        # so even a colluding root writer cannot certify an inflated
        # weight (hier.partial.check_cell_upload_op; the registry is
        # derived from configuration, like the validator keys)
        self._cell_registry: Optional[Dict[str, Tuple[int, int]]] = (
            dict(cell_registry) if cell_registry is not None else None)
        # sparse upload deltas (--delta-density, utils.serialization):
        # on a density-armed quorum every upload/aupload op must carry
        # its blob as auth evidence and survive the densify inverse —
        # the validator re-execution of sparse admission, so a
        # colluding writer cannot certify a malformed #topk blob
        from bflc_demo_tpu.utils.serialization import sparse_enabled
        self._sparse = sparse_enabled(cfg)
        # validator re-derivation plane (bflc_demo_tpu.rederive): with a
        # mode armed — explicit `rederive` or BFLC_REDERIVE, legacy pin
        # wins — this validator re-derives every commit op's model hash
        # from the admitted deltas (fetched through the data-plane read
        # path, hash-verified against upload ops it already co-signed)
        # and REFUSES to co-sign one it cannot reproduce.  Python
        # backend only: the re-derivation reads the replica's pending
        # selection / async buffer surfaces.
        from bflc_demo_tpu.rederive import (REDERIVE_MODES,
                                            rederive_legacy,
                                            rederive_mode)
        if rederive is None:
            mode = rederive_mode()
        else:
            mode = (rederive if rederive in REDERIVE_MODES
                    and not rederive_legacy() else "off")
        self._rederiver = None
        if mode != "off" and ledger_backend == "python":
            from bflc_demo_tpu.rederive.core import Rederiver
            self._rederiver = Rederiver(
                mode, index, len(self.validator_keys) or 1, cfg,
                initial_model_blob=initial_model_blob,
                cell_registry=self._cell_registry)
        self._lock = threading.Lock()
        # index -> (attempt, op digest) of our current vote there
        self._voted: Dict[int, Tuple[int, bytes]] = {}
        # index -> lowest attempt we will still vote at (abandon promises)
        self._promised: Dict[int, int] = {}
        self._heads: List[bytes] = []           # head after each op
        # state-synced replica offset (ledger.snapshot): _heads[k] is the
        # head after chain position _head_base + k; _base_head is the
        # head AT _head_base (after the certified snapshot op this
        # replica installed).  0/_EMPTY for a from-genesis replica.
        self._head_base = 0
        self._base_head = _EMPTY_HEAD
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(32)
        self.host, self.port = self._sock.getsockname()

    # ------------------------------------------------------------- server
    def start(self) -> None:
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def serve_forever(self) -> None:
        self.start()
        self._stop.wait()

    def close(self) -> None:
        self._stop.set()
        if self._rederiver is not None:
            self._rederiver.close()
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                msg = recv_msg(conn)
                if msg is None:
                    return
                method = msg.get("method", "")
                if method == "info":
                    with self._lock:
                        reply = {"ok": True, "validator": self.index,
                                 "log_size": self.ledger.log_size(),
                                 "log_head": self.ledger.log_head().hex(),
                                 "log_base": self._head_base,
                                 "epoch": self.ledger.epoch}
                        # head at an earlier index: the chaos invariant
                        # monitor's certified-prefix-agreement probe.
                        # Heads below a state-synced replica's base are
                        # gone with the prefix — the key is simply
                        # omitted and the prober skips this replica.
                        try:
                            at = int(msg.get("at", -1))
                        except (TypeError, ValueError):
                            at = -1
                        if at == 0:
                            reply["head_at"] = _EMPTY_HEAD.hex()
                        elif (self._head_base <= at
                              <= self._head_base + len(self._heads)):
                            reply["head_at"] = (
                                self._base_head.hex()
                                if at == self._head_base
                                else self._heads[
                                    at - self._head_base - 1].hex())
                elif method == "telemetry":
                    # FleetCollector scrape surface (obs.collector) —
                    # same shape as the ledger server's reply
                    _G_VLOG.set(self.ledger.log_size())
                    reply = {"ok": True,
                             "role": (obs_metrics.REGISTRY.role
                                      or f"validator-{self.index}"),
                             "snapshot":
                                 obs_metrics.REGISTRY.snapshot()}
                elif method == "bft_validate":
                    reply = self._validate(msg)
                elif method == "bft_vote_batch":
                    reply = self._vote_batch(msg)
                elif method == "bft_abandon":
                    reply = self._abandon(msg)
                elif method == "bft_snapshot":
                    reply = self._snapshot_install(msg)
                else:
                    reply = {"ok": False,
                             "error": f"unknown method {method!r}"}
                send_msg(conn, reply)
        except (WireError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # --------------------------------------------------------------- vote
    def _refuse(self, status: str, detail: str = "", **extra) -> dict:
        _M_REFUSE.inc(status=status)
        if self.verbose:
            print(f"[validator {self.index}] refuse: {status} {detail}",
                  flush=True)
        return {"ok": False, "status": status, "detail": detail,
                "log_size": self.ledger.log_size(), **extra}

    def _prev_head(self, i: int) -> bytes:
        """Chain head BEFORE position i on this replica (the base head
        for a state-synced replica's first position)."""
        if i <= 0:
            return _EMPTY_HEAD
        if i == self._head_base:
            return self._base_head
        return self._heads[i - self._head_base - 1]

    def _sign_position(self, i: int, op: bytes, attempt: int) -> dict:
        prev = self._prev_head(i)
        head = self._heads[i - self._head_base]
        sig = self.wallet.sign(cert_payload(i, prev, op, head, attempt))
        return {"ok": True, "i": i, "validator": self.index, "t": attempt,
                "head": head.hex(), "sig": sig.hex()}

    def _enroll_register_pubkey(self, op: bytes, auth) -> None:
        """Recover a register op's self-authenticating pubkey into our
        directory mirror (certificate-admitted ops carry no verified tag,
        but later FRESH ops from that client must still verify here)."""
        if not (op and op[0] == _OP_REGISTER and isinstance(auth, dict)):
            return
        try:
            pub = bytes.fromhex(auth.get("pubkey", ""))
            body = op[1:]
            (n,) = struct.unpack_from("<q", body, 0)
            addr = body[8:8 + n].decode()
            if pub and address_of(pub) == addr \
                    and not self.directory.knows(addr):
                self.directory.enroll(pub)
        except (ValueError, UnicodeDecodeError, struct.error):
            pass

    def _peer_certificate(self, msg: dict, i: int,
                          op: bytes) -> Optional[CommitCertificate]:
        """The request's certificate, iff it verifies as a quorum binding
        of exactly (i, OUR prefix head, op); else None."""
        if not self.validator_keys:
            return None
        cert_wire = msg.get("cert")
        if not isinstance(cert_wire, dict):
            return None
        try:
            cert = CommitCertificate.from_wire(cert_wire)
        except ValueError:
            return None
        if i < self._head_base:
            # below our state-synced base the prefix heads are gone:
            # the binding cannot be checked, so the certificate proves
            # nothing here (and certified history below a certified
            # snapshot is never rolled back anyway)
            return None
        prev = self._prev_head(i)
        if not verify_certificate(cert, index=i, prev_head=prev, op=op,
                                  quorum=self.quorum,
                                  validator_keys=self.validator_keys):
            return None
        return cert

    def _rollback_to(self, i: int) -> None:
        """Rebuild the replica from the certified prefix ops[0..i) —
        quorum evidence just proved our tip vote lost, so the suffix is
        provably uncertifiable history."""
        from bflc_demo_tpu.ledger import clone_prefix
        self.ledger = clone_prefix(self.ledger, i, self.cfg,
                                   backend=self._ledger_backend)
        del self._heads[i - self._head_base:]
        for j in [k for k in self._voted if k >= i]:
            del self._voted[j]

    def _apply_and_sign(self, i: int, op: bytes, op_hash: bytes,
                        attempt: int) -> dict:
        st = self.ledger.validate_op(op)
        if st != LedgerStatus.OK:
            # the replica's own re-execution of the decision procedure
            # (epoch/role/cap/duplicate guards) rejected the op
            return self._refuse(st.name)
        st = self.ledger.apply_op(op)
        if st != LedgerStatus.OK:       # unreachable: validate just passed
            return self._refuse(st.name, "apply after validate")
        self._voted[i] = (attempt, op_hash)
        self._heads.append(self.ledger.log_head())
        return self._sign_position(i, op, attempt)

    def _vote_locked(self, i: int, op: bytes, auth, attempt: int,
                     sparse_err: str = "", cell_err: str = "") -> dict:
        """The evidence-free voting core (lock held): idempotent re-sign
        of an op we already hold, strict ordering, abandon promises, auth
        check, re-derivation, apply + sign.  Anything needing QUORUM
        EVIDENCE (a peer certificate or a repair proof) refuses here —
        `_validate` layers that handling on top; the batch fast path
        refuses outright and lets the writer fall back to the single-op
        method.

        `sparse_err` is the PRECOMPUTED `check_sparse_upload_op` verdict
        ('' = fine): the full blob decode is a pure function of
        (op, auth) and must run OUTSIDE this lock — on a density-armed
        quorum it materializes the whole dense model per upload, and
        serializing that behind the validator's one lock would put
        N x decode latency on the BFT critical path per round.
        `cell_err` is the precomputed `Rederiver.check_cell` verdict for
        root-tier cell uploads — pure function of (op, auth) + the
        cell's read surface, likewise computed outside the lock.  The
        COMMIT re-derivation itself runs here: it reads this replica's
        pending/async state (only valid under the lock) and commits are
        one or two ops a round, so the bounded fetch sits where the
        round's certification round-trip already does."""
        op_hash = hashlib.sha256(op).digest()
        size = self.ledger.log_size()
        promised = self._promised.get(i, 0)
        if i < size:
            voted_t, voted_hash = self._voted.get(i, (0, None))
            if voted_hash == op_hash:
                # idempotent re-sign of the op we hold; the attempt
                # upgrades freely (same op can never fork) but never
                # below an outstanding abandon promise
                t = max(attempt, voted_t)
                if t < promised:
                    return self._refuse(
                        "PROMISED", f"promised attempt {promised}",
                        promised=promised, voted_t=voted_t)
                self._voted[i] = (t, op_hash)
                return self._sign_position(i, op, t)
            return self._refuse(
                "CONFLICT",
                f"position {i} already holds a different op",
                voted_t=voted_t, promised=promised)
        if i > size:
            # strict ordering: we cannot judge op i without the prefix
            return self._refuse("OUT_OF_ORDER",
                                f"replica at {size}, asked for {i}")
        if attempt < promised:
            return self._refuse("PROMISED",
                                f"promised attempt {promised}",
                                promised=promised, voted_t=0)
        if self._cell_registry is not None:
            from bflc_demo_tpu.hier.partial import check_cell_upload_op
            err = check_cell_upload_op(op, self._cell_registry)
            if err:
                return self._refuse("CELL", err)
        if self._sparse and sparse_err:
            return self._refuse("SPARSE", sparse_err)
        if self.require_auth:
            err = check_op_auth(op, auth, self.directory)
            if err:
                return self._refuse("AUTH", err)
        rl = None
        if self._rederiver is not None:
            if cell_err:
                # root-tier cell partial that is not the FedAvg of its
                # member-signed deltas (precomputed outside the lock)
                return self._refuse("REDERIVE", cell_err)
            if op[0] in (_OP_COMMIT, _OP_ACOMMIT):
                err, rl = self._rederiver.check(self.ledger, op, auth)
                if err:
                    return self._refuse("REDERIVE", err)
        r = self._apply_and_sign(i, op, op_hash, attempt)
        if r.get("ok") and rl is not None:
            # per-leaf digest vector of the successful re-derivation:
            # vote metadata the assembler cross-checks across
            # overlapping shards (rederive.core.crosscheck_rl)
            r["rl"] = rl["leaves"]
            r["rmode"] = rl["mode"]
        return r

    def _validate(self, msg: dict) -> dict:
        try:
            i = int(msg["i"])
            op = bytes.fromhex(msg["op"])
            attempt = int(msg.get("t", 0))
        except (KeyError, TypeError, ValueError):
            return self._refuse("BAD_REQUEST")
        op_hash = hashlib.sha256(op).digest()
        tr = tracing.PROC
        with obs_trace.server_span(msg, "vote", links_key="tps", i=i):
            if tr.enabled or obs_metrics.REGISTRY.enabled:
                t0 = time.perf_counter()
                try:
                    return self._validate_inner(i, op, op_hash, attempt,
                                                msg)
                finally:
                    dt = time.perf_counter() - t0
                    if tr.enabled:
                        tr.charge("bft.validate_s", dt)
                        tr.charge("bft.validate_n")
                    _M_VOTE.observe(dt, kind="single")
            return self._validate_inner(i, op, op_hash, attempt, msg)

    def _validate_inner(self, i: int, op: bytes, op_hash: bytes,
                        attempt: int, msg: dict) -> dict:
        # the sparse blob re-execution is a pure function of (op, auth):
        # run it before taking the lock (see _vote_locked docstring) —
        # the cell-partial re-derivation likewise (op + evidence + the
        # cell's read surface, no replica state)
        sparse_err = (check_sparse_upload_op(op, msg.get("auth"))
                      if self._sparse else "")
        cell_err = self._cell_rederive_err(op, msg.get("auth"))
        with self._lock:
            r = self._vote_locked(i, op, msg.get("auth"), attempt,
                                  sparse_err=sparse_err,
                                  cell_err=cell_err)
            status = r.get("status")
            if r.get("ok") or status not in ("CONFLICT", "AUTH",
                                             "SPARSE", "REDERIVE"):
                return r
            if status == "CONFLICT":
                # a DIFFERENT op at a bound position: only quorum evidence
                # may move us.  (1) resync-and-retry — an existing commit
                # certificate proves the canonical chain holds `op` here;
                # since the certificate binds OUR OWN prefix head, our
                # whole suffix from i provably lost (rollback depth is
                # arbitrary: a validator that kept voting on a stale fork
                # may have diverged several ops deep).
                size = self.ledger.log_size()
                voted_t, _vh = self._voted.get(i, (0, None))
                promised = self._promised.get(i, 0)
                cert = self._peer_certificate(msg, i, op)
                repair_ok = False
                if cert is None and i == size - 1 \
                        and attempt > voted_t and attempt >= promised:
                    # (2) re-proposal — a repair proof at this attempt
                    # whose mandate admits `op` (or mandates nothing)
                    ok, mandated, _ = verify_repair_proof(
                        msg.get("repair"), i, attempt, self.quorum,
                        self.validator_keys)
                    repair_ok = ok and (mandated is None
                                        or mandated == op_hash)
                if cert is None and not repair_ok:
                    return r
                # the repair proof authorizes the ROLLBACK, never an auth
                # bypass: client-originated ops still need their tag (or
                # an existing certificate, which embeds a quorum's
                # re-verification of it)
                if cert is None and self.require_auth:
                    err = check_op_auth(op, msg.get("auth"),
                                        self.directory)
                    if err:
                        return self._refuse("AUTH", err)
                if cert is None and self._sparse and sparse_err:
                    # ... and never a sparse bypass either: a
                    # re-proposed upload still needs its blob evidence
                    return self._refuse("SPARSE", sparse_err)
                if cert is None and cell_err:
                    # ... nor a cell re-derivation bypass
                    return self._refuse("REDERIVE", cell_err)
                self._enroll_register_pubkey(op, msg.get("auth"))
                _M_REPAIR.inc(kind=("cert_resync" if cert is not None
                                    else "re_proposal"))
                self._rollback_to(i)
                rl = None
                if cert is None and self._rederiver is not None \
                        and op and op[0] in (_OP_COMMIT, _OP_ACOMMIT):
                    # re-proposed commit without a certificate: the
                    # rollback restored the pre-commit state, so the
                    # re-derivation judges it like a fresh vote
                    err, rl = self._rederiver.check(self.ledger, op,
                                                    msg.get("auth"))
                    if err:
                        return self._refuse("REDERIVE", err)
                t = max(attempt, cert.attempt if cert else 0)
                r2 = self._apply_and_sign(i, op, op_hash, t)
                if r2.get("ok") and rl is not None:
                    # the contested re-proposal is exactly where the
                    # forensic cross-check wants digest vectors most
                    r2["rl"] = rl["leaves"]
                    r2["rmode"] = rl["mode"]
                return r2
            # AUTH/SPARSE/REDERIVE refusal at the fresh tip: certified
            # backlog — the quorum already re-verified the client tag
            # (and the sparse blob / the commit re-derivation) once;
            # admit on the certificate.  This keeps validator REJOIN
            # live: ops certified before a promotion lose their
            # writer-process-local auth evidence (blob included), and
            # refusing them here would wedge resync forever.
            if self._peer_certificate(msg, i, op) is None:
                return r
            self._enroll_register_pubkey(op, msg.get("auth"))
            return self._apply_and_sign(i, op, op_hash, attempt)

    def _cell_rederive_err(self, op: bytes, auth) -> str:
        """Precomputed root-tier cell-partial re-derivation verdict
        ('' = fine / not applicable) — pure function of (op, auth) +
        the cell's read surface, run OUTSIDE the validator lock (see
        _vote_locked docstring)."""
        if self._rederiver is None or self._cell_registry is None \
                or not op or op[0] != _OP_UPLOAD:
            return ""
        # the effective density at this replica's chain position rides
        # along: with the closed loop armed, cell partials re-encode at
        # the knob a certified genome-update op set, not the static
        # genome value (a plain float read — no lock needed, and the
        # genome op only moves it at round boundaries).  Static fleets
        # pass None: the rederiver falls back to the genome knob.
        from bflc_demo_tpu.ledger.base import adapt_enabled
        eff = (float(self.ledger.effective_density)
               if adapt_enabled(self.cfg) else None)
        return self._rederiver.check_cell(op, auth, density=eff)

    def _snapshot_install(self, msg: dict) -> dict:
        """State-sync a REJOINING replica that lags below the writer's
        GC'd prefix: install a certified snapshot instead of replaying
        ops that no longer exist (ledger.snapshot).

        Trust: the offer must carry a commit certificate quorum-signed
        by this validator's PROVISIONED peers binding exactly (i,
        prev_head, snapshot op), and the state bytes must hash to the
        op's embedded digest — a lying writer cannot fabricate either.
        Installation is refused when this replica already holds the
        position (its own chain is never rolled back by an offer; the
        certificate-resync path handles genuine divergence)."""
        from bflc_demo_tpu.comm.wire import blob_bytes
        from bflc_demo_tpu.ledger.snapshot import (restore_snapshot,
                                                   verify_snapshot_meta)
        try:
            i = int(msg["i"])
            op = bytes.fromhex(msg["op"])
            prev = bytes.fromhex(msg["prev_head"])
            state = blob_bytes(msg["state"])
        except (KeyError, TypeError, ValueError):
            return self._refuse("BAD_REQUEST")
        with self._lock:
            if self.ledger.log_size() >= i + 1:
                return self._refuse(
                    "CONFLICT",
                    f"replica at {self.ledger.log_size()} already "
                    f"holds position {i}")
            meta = {"i": i, "op": op, "prev_head": prev, "state": state,
                    "cert": msg.get("cert"), "gen": 0}
            err = verify_snapshot_meta(
                meta, bft_quorum=self.quorum,
                bft_keys=self.validator_keys or None)
            if err:
                return self._refuse("SNAPSHOT", err)
            if not self.validator_keys:
                # without peer keys the certificate cannot be checked —
                # an unverifiable install would let any connected peer
                # rewrite this replica; refuse rather than trust
                return self._refuse(
                    "SNAPSHOT", "no provisioned peer keys to verify "
                                "the snapshot certificate against")
            base_head = next_head(prev, op)
            self.ledger = restore_snapshot(state, self.cfg, i + 1,
                                           base_head)
            self._heads = []
            self._head_base = i + 1
            self._base_head = base_head
            self._voted = {k: v for k, v in self._voted.items()
                           if k > i}
            _M_REPAIR.inc(kind="snapshot_install")
            if self.verbose:
                print(f"[validator {self.index}] state-synced from "
                      f"snapshot@{i} (epoch "
                      f"{self.ledger.epoch})", flush=True)
            return {"ok": True, "log_size": self.ledger.log_size()}

    _VOTE_BATCH_MAX = 256

    def _vote_batch(self, msg: dict) -> dict:
        """One round-trip, many votes (see class docstring).  Reply:
        {ok: True, votes: [per-op vote dicts], stopped: refusal|None,
        log_size} — `votes` covers the longest signable prefix; `stopped`
        is the first refusal (OUT_OF_ORDER lets the writer resync the
        backlog and re-ask; CONFLICT/AUTH/PROMISED route that position to
        the evidence-carrying single-op path)."""
        try:
            start = int(msg["i"])
            ops = [bytes.fromhex(o) for o in msg["ops"]]
            auths = msg.get("auths") or [None] * len(ops)
            attempt = int(msg.get("t", 0))
        except (KeyError, TypeError, ValueError):
            return self._refuse("BAD_REQUEST")
        if len(auths) != len(ops) or len(ops) > self._VOTE_BATCH_MAX:
            return self._refuse("BAD_REQUEST",
                                f"batch of {len(ops)} ops rejected")
        votes: List[dict] = []
        stopped = None
        t0 = time.perf_counter() if (
            tracing.PROC.enabled or obs_metrics.REGISTRY.enabled) else 0.0
        # sparse blob re-execution per op, OUTSIDE the lock (pure
        # function of (op, auth); see _vote_locked docstring) — other
        # vote/abandon traffic proceeds while this batch decodes
        sparse_errs = ([check_sparse_upload_op(op, auths[k])
                        for k, op in enumerate(ops)]
                       if self._sparse else [""] * len(ops))
        cell_errs = [self._cell_rederive_err(op, auths[k])
                     for k, op in enumerate(ops)]
        # causal span linked to EVERY op in the batch (obs.trace): one
        # vote round-trip serves several clients' traces at once
        with obs_trace.server_span(msg, "vote_batch", links_key="tps",
                                   i=start, n_ops=len(ops)), self._lock:
            for k, op in enumerate(ops):
                r = self._vote_locked(start + k, op, auths[k], attempt,
                                      sparse_err=sparse_errs[k],
                                      cell_err=cell_errs[k])
                if not r.get("ok"):
                    stopped = r
                    break
                votes.append(r)
            size = self.ledger.log_size()
        if tracing.PROC.enabled:
            tracing.PROC.charge("bft.validate_s",
                                time.perf_counter() - t0)
            tracing.PROC.charge("bft.validate_n", len(votes))
        if obs_metrics.REGISTRY.enabled and t0:
            _M_VOTE.observe(time.perf_counter() - t0, kind="batch")
        return {"ok": True, "votes": votes, "stopped": stopped,
                "log_size": size}

    def _abandon(self, msg: dict) -> dict:
        """Issue a signed abandon statement for (i, t): report what we
        hold at position i and promise to refuse votes below attempt t.
        The statement set (2f+1 of them) is the repair proof that makes a
        re-proposal safe."""
        try:
            i = int(msg["i"])
            t = int(msg["t"])
        except (KeyError, TypeError, ValueError):
            return self._refuse("BAD_REQUEST")
        with self._lock:
            size = self.ledger.log_size()
            if i < size - 1:
                # below the tip sits certified history — it is never
                # abandonable (rollback depth is at most one op)
                return self._refuse("CONFLICT",
                                    f"position {i} is certified history")
            voted_t, voted_hash = self._voted.get(i, (0, None))
            promised = self._promised.get(i, 0)
            if t < promised or (voted_hash is not None and t <= voted_t):
                return self._refuse("STALE_ATTEMPT",
                                    f"promised {promised}, voted at "
                                    f"{voted_t}",
                                    promised=promised, voted_t=voted_t)
            self._promised[i] = t
            _M_ABANDON.inc()
            has_vote = voted_hash is not None
            op = self.ledger.log_op(i) if has_vote else b""
            sig = self.wallet.sign(abandon_stmt_payload(
                i, t, self.index, has_vote, voted_t,
                voted_hash or b"\0" * 32))
            return {"ok": True, "i": i, "t": t, "validator": self.index,
                    "has_vote": has_vote,
                    "op_hash": (voted_hash or b"").hex(),
                    "op": op.hex(), "voted_t": voted_t,
                    "sig": sig.hex()}


class ValidatorClient:
    """Writer-side connection to one validator; reconnects lazily."""

    def __init__(self, endpoint: Endpoint, timeout_s: float = 10.0,
                 tls=None):
        self.endpoint = endpoint
        self.timeout_s = timeout_s
        self._tls = tls
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self.endpoint,
                                         timeout=self.timeout_s)
            if self._tls is not None:
                s = self._tls.wrap_socket(s,
                                          server_hostname=self.endpoint[0])
            self._sock = s
        return self._sock

    def request(self, method: str, **fields) -> dict:
        send_msg(self._connect(), {"method": method, **fields})
        reply = recv_msg(self._sock)
        if reply is None:
            raise ConnectionError("validator closed the connection")
        return reply

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class CertificateAssembler:
    """Collects a quorum of validator votes for consecutive ops.

    Owned by the writer (comm.ledger_service.LedgerServer) and by a
    promoting standby (for its fence op).  `certify(i, op, auth,
    prev_head)` contacts every validator in parallel, resyncing lagging
    replicas from `backlog_fn(j) -> (op, auth, cert_wire)` (a rejoining
    validator admits certified backlog ops on the certificate when the
    writer-local auth evidence is gone — see ValidatorNode), verifies
    each vote signature against the provisioned keys (a lying
    validator's garbage does not count), and returns the certificate
    once >= quorum distinct valid signatures agree — or None.

    Liveness repair (round 7): when votes split below quorum because
    validators hold a DIFFERENT op at the position (a dead writer's
    stranded proposal, a promotion race, an equivocation), certify runs
    abandon rounds at rising attempt numbers: 2f+1 signed statements
    form a repair proof, the mandate rule picks the only safely
    re-proposable op, and diverged validators roll back and re-vote —
    so the stall degrades to delay.  A proposer whose own op LOSES the
    mandate (a foreign op is canonically bound at its position) gets
    None back with `superseded_op` set to the canonical op bytes — its
    chain suffix is doomed and it must step aside (self-fence / re-follow).
    """

    def __init__(self, endpoints: List[Endpoint],
                 validator_keys: Dict[int, bytes], quorum: int, *,
                 timeout_s: float = 10.0, tls=None, backlog_fn=None,
                 max_repair_rounds: int = 3):
        self.endpoints = list(endpoints)
        self.keys = dict(validator_keys)
        self.quorum = quorum
        self.timeout_s = timeout_s
        self.backlog_fn = backlog_fn
        self.max_repair_rounds = max_repair_rounds
        # set (instead of a certificate) when a repair round proved a
        # FOREIGN op is the only safely bindable one at the position
        self.superseded_op: Optional[bytes] = None
        self._clients = [ValidatorClient(ep, timeout_s=timeout_s, tls=tls)
                         for ep in endpoints]

    def close(self) -> None:
        for c in self._clients:
            c.close()

    def _vote_one(self, client: ValidatorClient, i: int, op: bytes,
                  auth: Optional[dict], attempt: int,
                  repair: Optional[dict],
                  tp: Optional[str] = None) -> Optional[dict]:
        """One validator's reply for (i, op, attempt), resyncing its
        replica from the backlog when it reports OUT_OF_ORDER.  Returns
        the final reply dict (ok or refusal); None = transport failure.
        `tp` is the op's originating traceparent (obs.trace), carried so
        the validator's vote span links into the op's trace."""
        extra = {"tps": [tp]} if tp else {}
        for retry in (0, 1):            # one reconnect per certify call
            try:
                r = client.request("bft_validate", i=i, op=op.hex(),
                                   auth=auth, t=attempt, repair=repair,
                                   **extra)
                resyncs = 0
                while (not r.get("ok")
                       and r.get("status") == "OUT_OF_ORDER"
                       and self.backlog_fn is not None):
                    behind = int(r.get("log_size", -1))
                    if not 0 <= behind < i:
                        break
                    for j in range(behind, i):
                        try:
                            entry = self.backlog_fn(j)
                        except PrefixCompacted as e:
                            # the backlog below the GC base is gone:
                            # state-sync the replica from the certified
                            # snapshot, then re-ask — it reports its new
                            # (post-install) position and the replay
                            # continues from there
                            if not self._offer_snapshot(client, e):
                                return None
                            break
                        bop, bauth = entry[0], entry[1]
                        bcert = entry[2] if len(entry) > 2 else None
                        rj = client.request("bft_validate", i=j,
                                            op=bop.hex(), auth=bauth,
                                            cert=bcert)
                        if not rj.get("ok"):
                            # the replica may hold a diverged SUFFIX
                            # below j (it voted an op that later lost a
                            # repair round while it was behind — the
                            # canonical op then mis-applies onto its
                            # fork): certificate resync walks back to
                            # the true divergence point and heals it,
                            # after which the backlog replay restarts
                            resyncs += 1
                            if resyncs > 2 or \
                                    not self._resync_diverged(client, j):
                                return None
                            break
                    r = client.request("bft_validate", i=i, op=op.hex(),
                                       auth=auth, t=attempt,
                                       repair=repair, **extra)
                return r
            except (ConnectionError, WireError, OSError):
                client.close()
                if retry:
                    return None
        return None

    def _catch_up(self, client: ValidatorClient, behind: int,
                  upto: int) -> bool:
        """Replay certified backlog ops [behind, upto) into a lagging
        replica (certificates ride along so client auth evidence is not
        needed; `_resync_diverged` heals a stale-fork suffix mid-replay).
        True when the replica provably reached `upto`.  Batch-path
        counterpart of the inline resync in `_vote_one` — kept separate
        so the single-op path's repair semantics stay untouched."""
        if self.backlog_fn is None or not 0 <= behind < upto:
            return False
        resyncs = 0
        j = behind
        while j < upto:
            try:
                entry = self.backlog_fn(j)
            except PrefixCompacted as e:
                # replay target below the GC base: install the certified
                # snapshot and continue from the post-install position
                if not self._offer_snapshot(client, e) \
                        or e.base <= j:
                    return False
                j = e.base
                continue
            bop, bauth = entry[0], entry[1]
            bcert = entry[2] if len(entry) > 2 else None
            try:
                rj = client.request("bft_validate", i=j, op=bop.hex(),
                                    auth=bauth, cert=bcert)
            except (ConnectionError, WireError, OSError):
                client.close()
                return False
            if rj.get("ok"):
                j += 1
                continue
            # the replica may hold a diverged suffix below j: certificate
            # resync walks back to the divergence point and heals it,
            # after which the replay restarts from wherever it stands
            resyncs += 1
            if resyncs > 2 or not self._resync_diverged(client, j):
                return False
            try:
                inf = client.request("info")
                j = max(0, min(int(inf.get("log_size", j)), j))
            except (ConnectionError, WireError, OSError,
                    TypeError, ValueError):
                client.close()
                return False
        return True

    def _vote_batch_one(self, client: ValidatorClient, start: int,
                        entries,
                        tps: Optional[list] = None
                        ) -> Optional[List[dict]]:
        """One validator's vote list for the contiguous ops `entries` at
        positions [start, start+len(entries)) — one `bft_vote_batch`
        round-trip, with a certified-backlog replay + one re-ask when the
        replica reports OUT_OF_ORDER below `start`.  None on transport
        failure or a validator that does not speak the batch method (an
        old-version peer): the caller falls back to single-op voting.
        `tps` (originating traceparents per op, obs.trace) rides along
        so the validator's vote span links into every covered trace."""
        ops_hex = [op.hex() for op, _ in entries]
        auths = [a for _, a in entries]
        extra = {"tps": tps} if tps and any(tps) else {}
        for retry in (0, 1):            # one reconnect per call
            try:
                r = client.request("bft_vote_batch", i=start, ops=ops_hex,
                                    auths=auths, **extra)
                if not r.get("ok"):
                    return None         # old peer / malformed: fall back
                stopped = r.get("stopped")
                if not r.get("votes") and isinstance(stopped, dict) \
                        and stopped.get("status") == "OUT_OF_ORDER":
                    try:
                        behind = int(stopped.get("log_size", -1))
                    except (TypeError, ValueError):
                        behind = -1
                    if self._catch_up(client, behind, start):
                        r = client.request("bft_vote_batch", i=start,
                                           ops=ops_hex, auths=auths,
                                           **extra)
                        if not r.get("ok"):
                            return None
                return r.get("votes") or []
            except (ConnectionError, WireError, OSError):
                client.close()
                if retry:
                    return None
        return None

    def certify_range(self, start: int, entries, prev_head: bytes,
                      tps: Optional[list] = None
                      ) -> List[Optional[CommitCertificate]]:
        """Batched fast path (PR 3): certify the contiguous ops
        `entries` = [(op, auth), ...] at positions [start, ...) in ONE
        vote round-trip per validator instead of one per op.  Votes are
        verified before counting — in bulk (batch verification) with a
        per-sig fallback, so a lying validator still contributes nothing
        — and certificates come out byte-identical to the single-op
        path: per-position, chain-linked via each op's own prev-head,
        accepted by the unchanged `verify_certificate`.

        Returns a certificate list aligned with `entries`; the first
        None (and everything after it — certificates install strictly in
        chain order) marks where the fast path stopped.  The caller
        routes that position through `certify`, whose conflict-resync,
        abandon/repair and superseded-proposer machinery is deliberately
        untouched."""
        n = len(entries)
        prevs: List[bytes] = []
        heads: List[bytes] = []
        h = prev_head or _EMPTY_HEAD
        for op, _ in entries:
            prevs.append(h)
            h = next_head(h, op)
            heads.append(h)
        # position -> attempt -> {validator: sig}; raw first, verify bulk
        raw: List[List[Tuple[int, int, bytes]]] = [[] for _ in range(n)]
        # position -> {validator: per-leaf digest vector} — rederive
        # vote metadata, cross-checked after the certificates mint
        rl_by_pos: List[Dict[int, dict]] = [{} for _ in range(n)]
        lock = threading.Lock()
        # one causal span per vote ROUND-TRIP, linked to every op in the
        # batch (obs.trace): the ambient context is captured here — the
        # ask threads have none of their own — and activated inside each
        # span so the vote request frames carry it onward
        amb = (obs_trace.TRACE.current_traceparent()
               if obs_trace.TRACE.enabled else None)
        links = [t for t in (tps or ()) if t] or None

        def ask(client, vidx):
            with obs_trace.TRACE.span_from(
                    amb or (links[0] if links else None), "bft.vote_rtt",
                    links=links, validator=vidx, n_ops=n):
                vs = self._vote_batch_one(client, start, entries,
                                          tps=tps)
            if not vs:
                return
            for v in vs:
                try:
                    k = int(v["i"]) - start
                    vidx = int(v["validator"])
                    vt = int(v.get("t", 0))
                    sig = bytes.fromhex(v["sig"])
                except (KeyError, TypeError, ValueError):
                    continue
                if 0 <= k < n and vidx in self.keys:
                    with lock:
                        raw[k].append((vidx, vt, sig))
                        if isinstance(v.get("rl"), dict):
                            rl_by_pos[k][vidx] = v["rl"]

        threads = [threading.Thread(target=ask, args=(c, ci),
                                    daemon=True)
                   for ci, c in enumerate(self._clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.timeout_s + 5.0)

        # bulk signature verification across every collected vote; on a
        # batch miss (>= 1 bad or torsion-defective sig) re-verify each —
        # `verify_signature` — so garbage is attributed and dropped
        items = []
        flat = []
        for k, lst in enumerate(raw):
            for vidx, vt, sig in lst:
                payload = cert_payload(start + k, prevs[k],
                                       entries[k][0], heads[k], vt)
                items.append((self.keys[vidx], payload, sig))
                flat.append((k, vidx, vt, sig))
        all_ok = verify_signatures_batch(items) if items else True
        votes: List[Dict[int, Dict[int, bytes]]] = [{} for _ in range(n)]
        for (k, vidx, vt, sig), (pub, payload, _s) in zip(flat, items):
            if all_ok or verify_signature(pub, payload, sig):
                votes[k].setdefault(vt, {})[vidx] = sig

        certs: List[Optional[CommitCertificate]] = []
        for k in range(n):
            got = None
            for vt, sigs in sorted(votes[k].items()):
                if len(sigs) >= self.quorum:
                    got = CommitCertificate(
                        index=start + k, prev_head=prevs[k],
                        op_hash=hashlib.sha256(entries[k][0]).digest(),
                        new_head=heads[k], attempt=vt, sigs=dict(sigs))
                    break
            if got is not None and all_ok \
                    and len(got.sigs) == self.quorum:
                # exactly-quorum certificate whose sigs were accepted on
                # batch verification ALONE (cofactored): belt-and-braces
                # re-check each under the stricter cofactorless rule, so
                # a torsion-defective signature is never the one holding
                # a quorum together — every downstream verifier counts
                # deterministically either way (the batch equation is
                # cofactored on purpose), this just refuses to MINT a
                # zero-slack certificate leaning on a defective sig
                payload = cert_payload(start + k, prevs[k],
                                       entries[k][0], heads[k],
                                       got.attempt)
                if sum(1 for vidx, sig in got.sigs.items()
                       if verify_signature(self.keys[vidx], payload,
                                           sig)) < self.quorum:
                    got = None
            certs.append(got)
            if got is None:
                break
        certs += [None] * (n - len(certs))
        for k, rls in enumerate(rl_by_pos):
            if len(rls) >= 2:
                self._crosscheck(start + k, rls)
        return certs

    @staticmethod
    def _crosscheck(position: int, rls: Dict[int, dict]) -> None:
        """Cross-check the per-leaf digest vectors that rode a commit
        op's votes (rederive plane).  Honest vectors can never disagree
        — each digests leaves that matched the one claimed blob — so a
        disagreement fingerprints a lying or buggy validator for the
        forensic record (safety rests on the shard-coverage refusal
        arithmetic, not on this check)."""
        from bflc_demo_tpu.rederive.core import crosscheck_rl
        bad = crosscheck_rl(rls)
        _M_RL_XCHECK.inc(result="disagree" if bad else "ok")
        if bad:
            obs_flight.FLIGHT.record(
                "event", "rederive_crosscheck_disagreement",
                position=position, leaves=bad[:8],
                validators=sorted(rls))
            obs_flight.FLIGHT.flush("rederive_crosscheck")

    def _gather_votes(self, i: int, op: bytes, auth: Optional[dict],
                      prev_head: bytes, attempt: int,
                      repair: Optional[dict], tp: Optional[str] = None):
        """-> (sigs_by_attempt, refusals, diverged): verified signatures
        grouped by the attempt each validator actually signed at (an
        idempotent re-sign may report a higher attempt than requested;
        payloads differ per attempt, so a certificate needs a uniform
        group).  `diverged` holds the clients whose ok-reply signature
        did NOT verify over our payload — the fingerprint of a replica
        voting on a stale fork (its head differs), which needs an active
        certificate resync, not a repair round."""
        new_head = next_head(prev_head, op)
        votes: Dict[int, Dict[int, bytes]] = {}
        refusals: List[dict] = []
        diverged: List[ValidatorClient] = []
        rls: Dict[int, dict] = {}
        lock = threading.Lock()

        amb = (obs_trace.TRACE.current_traceparent()
               if obs_trace.TRACE.enabled else None)

        def ask(client):
            with obs_trace.TRACE.span_from(
                    amb or tp, "bft.vote_rtt",
                    links=[tp] if tp else None, i=i):
                r = self._vote_one(client, i, op, auth, attempt, repair,
                                   tp=tp)
            if r is None:
                return
            if not r.get("ok"):
                with lock:
                    refusals.append(r)
                return
            try:
                vidx = int(r["validator"])
                vt = int(r.get("t", attempt))
                sig = bytes.fromhex(r["sig"])
            except (KeyError, TypeError, ValueError):
                return
            pub = self.keys.get(vidx)
            if pub is None:
                return
            # verify BEFORE counting: a Byzantine validator's garbage
            # signature (or a vote minted on a diverged replica, whose
            # head therefore differs) must not contribute to the quorum
            payload = cert_payload(i, prev_head, op, new_head, vt)
            with lock:
                if verify_signature(pub, payload, sig):
                    votes.setdefault(vt, {})[vidx] = sig
                    if isinstance(r.get("rl"), dict):
                        rls[vidx] = r["rl"]
                else:
                    diverged.append(client)

        threads = [threading.Thread(target=ask, args=(c,), daemon=True)
                   for c in self._clients]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.timeout_s + 5.0)
        if len(rls) >= 2:
            self._crosscheck(i, rls)
        return votes, refusals, diverged

    def _resync_diverged(self, client: ValidatorClient, i: int) -> bool:
        """Heal a replica that kept extending a stale fork: locate the
        first position where its head leaves our chain, then present the
        commit certificate for OUR op there — the validator verifies the
        quorum binding over its own shared prefix, rolls its suffix back
        and rejoins (ValidatorNode resync path).  The regular backlog
        replay then carries it forward."""
        if self.backlog_fn is None:
            return False
        try:
            inf = client.request("info")
            size = min(int(inf.get("log_size", 0)), i)
        except (ConnectionError, WireError, OSError, TypeError,
                ValueError):
            client.close()
            return False
        # our heads over the certified backlog (chain-rule fold).  On a
        # compacted writer the fold starts at the certified snapshot's
        # base instead of genesis — an above-base fork must keep the
        # op-level resync (a snapshot install would be refused by a
        # replica whose chain already reaches past the snapshot).
        base, base_head = 0, _EMPTY_HEAD
        try:
            ops = [self.backlog_fn(j) for j in range(size)]
        except PrefixCompacted as e:
            if e.offer is None or size <= int(e.offer["i"]) + 1:
                # the replica itself lags at/below the certified
                # snapshot: installing it is the only heal
                return self._offer_snapshot(client, e)
            from bflc_demo_tpu.ledger.snapshot import snapshot_base_head
            base = int(e.offer["i"]) + 1
            base_head = snapshot_base_head(e.offer)
            try:
                ops = [self.backlog_fn(j) for j in range(base, size)]
            except PrefixCompacted:
                return False            # GC advanced mid-walk: retry
                #                         lands on the newer snapshot
        heads = []
        h = base_head
        for entry in ops:
            heads.append(next_head(h, entry[0]))
            h = heads[-1]
        d = size                        # first divergent index
        for j in range(size, base, -1):
            try:
                r = client.request("info", at=j)
            except (ConnectionError, WireError, OSError):
                client.close()
                return False
            if r.get("head_at") and \
                    bytes.fromhex(r["head_at"]) == heads[j - base - 1]:
                break
            d = j - 1
        if d >= size:
            return False                # no divergence below i after all
        op, auth = ops[d - base][0], ops[d - base][1]
        cert = ops[d - base][2] if len(ops[d - base]) > 2 else None
        if cert is None:
            return False
        try:
            r = client.request("bft_validate", i=d, op=op.hex(),
                               auth=auth, cert=cert)
            return bool(r.get("ok"))
        except (ConnectionError, WireError, OSError):
            client.close()
            return False

    def _offer_snapshot(self, client: ValidatorClient,
                        exc: PrefixCompacted) -> bool:
        """Hand a lagging replica the writer's certified snapshot
        (`bft_snapshot`); True when it installed.  The validator
        verifies everything itself — quorum certificate + state digest
        — so a corrupt offer costs a refusal, never a poisoned
        replica."""
        offer = exc.offer
        if offer is None:
            return False
        op = offer["op"]
        prev = offer["prev_head"]
        try:
            r = client.request(
                "bft_snapshot", i=int(offer["i"]),
                op=op if isinstance(op, str) else op.hex(),
                prev_head=prev if isinstance(prev, str) else prev.hex(),
                state=bytes(offer["state"]), cert=offer.get("cert"))
        except (ConnectionError, WireError, OSError):
            client.close()
            return False
        return bool(r.get("ok"))

    def _abandon_round(self, i: int, attempt: int):
        """Ask every validator for a signed abandon statement at (i,
        attempt); one internal re-ask at a higher attempt when stale
        promises surface.  -> (statements, attempt_used)."""
        for _ in range(2):
            stmts: List[dict] = []
            stale = attempt
            lock = threading.Lock()

            def ask(client):
                nonlocal stale
                try:
                    r = client.request("bft_abandon", i=i, t=attempt)
                except (ConnectionError, WireError, OSError):
                    client.close()
                    return
                with lock:
                    if r.get("ok"):
                        stmts.append(r)
                    elif r.get("status") == "STALE_ATTEMPT":
                        try:
                            stale = max(stale,
                                        int(r.get("promised", 0)),
                                        int(r.get("voted_t", 0)))
                        except (TypeError, ValueError):
                            pass

            threads = [threading.Thread(target=ask, args=(c,),
                                        daemon=True)
                       for c in self._clients]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=self.timeout_s + 5.0)
            if len(stmts) >= self.quorum or stale <= attempt:
                return stmts, attempt
            attempt = stale + 1
        return stmts, attempt

    def certify(self, i: int, op: bytes, auth: Optional[dict],
                prev_head: bytes,
                tp: Optional[str] = None) -> Optional[CommitCertificate]:
        self.superseded_op = None
        op_hash = hashlib.sha256(op).digest()
        new_head = next_head(prev_head, op)
        attempt, repair = 0, None
        for _ in range(self.max_repair_rounds + 1):
            votes, refusals, diverged = self._gather_votes(
                i, op, auth, prev_head, attempt, repair, tp=tp)
            if diverged:
                # heal stale-fork replicas BEFORE taking the quorum exit:
                # a diverged validator silently erodes the f margin, and
                # its certificate-led rollback is cheap — then re-gather
                healed = [self._resync_diverged(c, i) for c in diverged]
                if any(healed):
                    continue
            for vt, sigs in sorted(votes.items()):
                if len(sigs) >= self.quorum:
                    return CommitCertificate(
                        index=i, prev_head=prev_head or _EMPTY_HEAD,
                        op_hash=op_hash, new_head=new_head,
                        attempt=vt, sigs=dict(sigs))
            blockers = [r for r in refusals
                        if r.get("status") in ("CONFLICT", "PROMISED",
                                               "STALE_ATTEMPT")]
            if not blockers or self.quorum <= 0:
                # transport / availability failure, not divergence: a
                # repair round cannot help — the caller retries later
                return None
            hint = 0
            for r in blockers:
                try:
                    hint = max(hint, int(r.get("promised", 0) or 0),
                               int(r.get("voted_t", 0) or 0))
                except (TypeError, ValueError):
                    pass
            for vt in votes:
                hint = max(hint, vt)
            stmts, next_t = self._abandon_round(i, max(attempt, hint) + 1)
            proof = {"stmts": stmts}
            ok, mandated, mop = verify_repair_proof(
                proof, i, next_t, self.quorum, self.keys)
            if not ok:
                return None             # no statement quorum reachable
            if mandated is not None and mandated != op_hash:
                # a foreign op is the only safely bindable one here: OUR
                # chain suffix lost the race — step aside, don't stall
                self.superseded_op = mop
                return None
            attempt, repair = next_t, proof
        return None


def provision_validators(n: int, master_seed: bytes):
    """Deterministic validator identities for a deployment: wallets (one
    per validator, seeded like provision_wallets) + the public-key map
    every certificate verifier needs.  Returns (wallets, keys)."""
    from bflc_demo_tpu.comm.identity import Wallet
    wallets = [Wallet.from_seed(master_seed + b"|bft-validator|"
                                + struct.pack("<q", v)) for v in range(n)]
    return wallets, {v: w.public_bytes for v, w in enumerate(wallets)}
