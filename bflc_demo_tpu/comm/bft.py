"""Byzantine no-fork commits: quorum-validated, co-signed ledger binding.

The reference's substrate is a 4-node PBFT chain: every `Aggregate` /
`UploadLocalUpdate` executes on ALL nodes and a 2f+1 quorum must agree
before the result binds, so one arbitrarily faulty node can neither fork
history nor fabricate state (README.md:162-183; every
`sendRawTransactionGetReceipt` in python-sdk/main.py:160,219 is a consensus
boundary).  Rounds 2-5 reproduced replication, failover, fencing and
quorum-ACK durability — all fail-stop properties.  This module reproduces
the *Byzantine* property for the writer itself:

- a fleet of **validators** (`ValidatorNode`) each holds its own replica
  of the chain.  Before an op binds, the writer must collect a **commit
  certificate**: `bft_quorum(n)` validators independently re-execute the
  op against their replicas — the full guard set (epoch / role / cap /
  duplicate, `ledger.validate_op`) PLUS the client's Ed25519 op tag for
  client-originated ops — and co-sign `(index, chain_prefix_digest,
  op_digest, resulting_head)` with their comm.identity wallets;
- a validator signs **at most one op per chain position** and refuses
  client ops whose tag does not verify against its own mirrored key
  directory, so a writer that fabricates a score row, drops a client's
  op, or equivocates (different ops to different validators) can never
  gather a quorum: any two quorums intersect in an honest validator;
- the writer may only ACK — and clients (`FailoverClient(bft_keys=...)`)
  and standbys (`Standby(bft_keys=...)`) only accept — state that carries
  a valid certificate.  At the reference's 4-validator geometry this
  tolerates f=1 crashed OR lying validators (protocol.constants.bft_*).

Deliberate non-goals, documented rather than implied (PARITY.md): the
commit op's MODEL HASH is re-executed as a guard check but not re-derived
(validators hold no payload blobs, so a writer lying about the FedAvg
output hash is caught by committee score attestation + any-holder
re-verification, not here); reads are not certified; and there is no view
change — validators whose replicas a hostile writer managed to diverge
(each applied a different op at one index; possible only while it holds
valid client tags for BOTH ops) stall certification rather than elect a
new writer, which is a liveness, never a safety, loss.

Deployment note: validator ports belong on the coordinator-side network
segment (like standby subscriptions).  The drill in tests/test_bft.py is
the module's specification: a hostile writer forging a score row, dropping
an acknowledged upload, and forking history fails certification while one
crashed-or-lying validator is tolerated.
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from bflc_demo_tpu.comm.identity import (PublicDirectory, _op_bytes,
                                         address_of, verify_signature)
from bflc_demo_tpu.comm.wire import WireError, recv_msg, send_msg
from bflc_demo_tpu.ledger import LedgerStatus, make_ledger
from bflc_demo_tpu.ledger.base import (encode_register_op,
                                       encode_scores_op, encode_upload_op)
from bflc_demo_tpu.protocol.constants import ProtocolConfig, bft_quorum
from bflc_demo_tpu.protocol.types import CommitCertificate

Endpoint = Tuple[str, int]

_CERT_MAGIC = b"BFLCCERT1"
_EMPTY_HEAD = b"\0" * 32        # head digest of the empty chain (log_head())

# ledger op codec (must match pyledger/ledger.cpp opcode table)
_OP_REGISTER, _OP_UPLOAD, _OP_SCORES = 1, 2, 3


def cert_payload_digest(index: int, prev_head: bytes, op_digest: bytes,
                        new_head: bytes) -> bytes:
    """THE byte layout a validator signs — the one encoder every signing
    and verification site shares, so the layout cannot desynchronize."""
    return (_CERT_MAGIC + struct.pack("<q", index)
            + (prev_head or _EMPTY_HEAD) + op_digest + new_head)


def cert_payload(index: int, prev_head: bytes, op: bytes,
                 new_head: bytes) -> bytes:
    """The byte string a validator signs: position + chain prefix + op
    digest + resulting head.  Binding the PREFIX digest (not just the op)
    is what makes certificates fork-proof — a signature minted on one
    history is meaningless on any other."""
    return cert_payload_digest(index, prev_head,
                               hashlib.sha256(op).digest(), new_head)


def next_head(prev_head: bytes, op: bytes) -> bytes:
    """The chain rule (ledger.cpp append_log / pyledger._append_log):
    head' = SHA-256(head || op), with the empty chain contributing no
    prefix bytes."""
    d = hashlib.sha256()
    if prev_head and prev_head != _EMPTY_HEAD:
        d.update(prev_head)
    d.update(op)
    return d.digest()


def verify_certificate(cert: CommitCertificate, *, index: int,
                       prev_head: bytes, op: bytes, quorum: int,
                       validator_keys: Dict[int, bytes]) -> bool:
    """Full verification for a party that HOLDS the chain (standby /
    promoted writer): the certificate must bind exactly (index, our
    prefix head, this op, the implied next head) and carry >= quorum
    valid signatures by DISTINCT provisioned validators."""
    new_head = next_head(prev_head, op)
    if (cert.index != index
            or (cert.prev_head or _EMPTY_HEAD) != (prev_head or _EMPTY_HEAD)
            or cert.op_hash != hashlib.sha256(op).digest()
            or cert.new_head != new_head):
        return False
    return count_valid_sigs(cert, validator_keys) >= quorum


def count_valid_sigs(cert: CommitCertificate,
                     validator_keys: Dict[int, bytes]) -> int:
    """Signatures by distinct PROVISIONED validators that verify over the
    certificate's own payload.  Shared by full verification and the
    client-side structural check."""
    payload = cert_payload_digest(cert.index, cert.prev_head,
                                  cert.op_hash, cert.new_head)
    n = 0
    for vidx, sig in cert.sigs.items():
        pub = validator_keys.get(vidx)
        if pub is not None and verify_signature(pub, payload, sig):
            n += 1
    return n


def verify_certificate_sigs(cert_wire, quorum: int,
                            validator_keys: Dict[int, bytes],
                            op_hash: Optional[bytes] = None) -> bool:
    """Client-side acceptance check (no chain held): the certificate's
    quorum signatures are authentic over its OWN claimed binding, and —
    when the caller supplies `op_hash` — the certificate binds THAT op.

    Always pass op_hash when checking the ack for your own mutation
    (`expected_op_hash` reconstructs it from the request fields): without
    it, a Byzantine writer that once certified ANY op honestly could
    replay that old certificate on a forged ack for a dropped or
    fabricated op.  A hostile writer cannot forge the signatures (only
    validators hold the keys, and they sign only ops their replicas
    accepted), so sigs + op binding together prove a quorum bound this
    exact op.  Never raises on malformed input."""
    try:
        cert = (cert_wire if isinstance(cert_wire, CommitCertificate)
                else CommitCertificate.from_wire(cert_wire))
    except (ValueError, TypeError):
        return False
    if op_hash is not None and cert.op_hash != op_hash:
        return False
    return count_valid_sigs(cert, validator_keys) >= quorum


# ------------------------------------------------ canonical op encoding
# The encoders are shared with PyLedger's append sites (ledger.base — one
# definition) so a party holding only the REQUEST fields can reconstruct
# the op bytes the writer must have appended — the request->certificate
# binding both the server (attaching the right cert to a DUPLICATE-class
# reply) and the client (rejecting replayed certificates) depend on.

def expected_op_hash(method: str, fields: dict) -> Optional[bytes]:
    """sha256 of the op the writer must append for this request — None
    when the method is not a client mutation or the fields are
    malformed (callers then skip the binding check)."""
    try:
        if method == "register":
            op = encode_register_op(fields["addr"])
        elif method == "upload":
            op = encode_upload_op(fields["addr"],
                                  bytes.fromhex(fields["hash"]),
                                  int(fields["n"]), float(fields["cost"]),
                                  int(fields["epoch"]))
        elif method == "scores":
            op = encode_scores_op(fields["addr"], int(fields["epoch"]),
                                  [float(s) for s in fields["scores"]])
        else:
            return None
        return hashlib.sha256(op).digest()
    except (KeyError, TypeError, ValueError):
        return None


# --------------------------------------------------------------- op auth
def check_op_auth(op: bytes, auth: Optional[dict],
                  directory: PublicDirectory) -> str:
    """'' when `op` is admissible w.r.t. origin authentication; a reason
    string otherwise.

    Client-originated ops (register/upload/scores) must carry the
    client's Ed25519 tag in `auth`, verified against the validator's OWN
    directory mirror — this is precisely what stops a Byzantine writer
    from fabricating a score row: it cannot produce a committee member's
    signature.  The float fields need care: tags sign the client's f64
    payload while ops store f32, so `auth` carries the original f64
    values and this check pins op bytes == exact f32 quantisation of the
    signed values.  Coordinator-authority ops (commit/close/force/
    reseat/promote) carry no tag — their admissibility is the replica
    re-execution (`validate_op`), the same authority split the
    AuthenticatedLedger applies.
    """
    if not op or op[0] not in (_OP_REGISTER, _OP_UPLOAD, _OP_SCORES):
        return ""
    if not isinstance(auth, dict):
        return "client op without auth evidence"
    body = op[1:]

    def _str_at(off):
        (n,) = struct.unpack_from("<q", body, off)
        if n < 0 or off + 8 + n > len(body):
            raise ValueError("string past end of op")
        return body[off + 8:off + 8 + n].decode(), off + 8 + n

    try:
        tag = bytes.fromhex(auth["tag"])
        if op[0] == _OP_REGISTER:
            addr, _ = _str_at(0)
            pub = bytes.fromhex(auth.get("pubkey", ""))
            if not directory.knows(addr):
                if address_of(pub) != addr:
                    return "register: address/pubkey mismatch"
                directory.enroll(pub)
            if not directory.verify(addr, _op_bytes("register", addr, 0,
                                                    b""), tag):
                return "register: bad tag"
            return ""
        if op[0] == _OP_UPLOAD:
            sender, off = _str_at(0)
            payload_hash = body[off:off + 32]
            ns, = struct.unpack_from("<q", body, off + 32)
            cost_f32, = struct.unpack_from("<f", body, off + 40)
            epoch, = struct.unpack_from("<q", body, off + 44)
            n, cost = int(auth["n"]), float(auth["cost"])
            if n != ns:
                return "upload: n_samples mismatch"
            if struct.pack("<f", np.float32(cost)) != \
                    struct.pack("<f", cost_f32):
                return "upload: cost not the f32 image of the signed value"
            payload = payload_hash + struct.pack("<qd", n, cost)
            if not directory.verify(sender, _op_bytes("upload", sender,
                                                      epoch, payload), tag):
                return "upload: bad tag"
            return ""
        # _OP_SCORES
        sender, off = _str_at(0)
        epoch, = struct.unpack_from("<q", body, off)
        cnt, = struct.unpack_from("<q", body, off + 8)
        if cnt < 0 or off + 16 + 4 * cnt > len(body):
            return "scores: malformed op"
        row_f32 = struct.unpack_from(f"<{cnt}f", body, off + 16)
        scores = [float(s) for s in auth["scores"]]
        if len(scores) != cnt:
            return "scores: row length mismatch"
        for got, claimed in zip(row_f32, scores):
            if struct.pack("<f", np.float32(claimed)) != \
                    struct.pack("<f", got):
                return "scores: row not the f32 image of the signed values"
        payload = struct.pack(f"<{len(scores)}d", *scores)
        if not directory.verify(sender, _op_bytes("scores", sender, epoch,
                                                  payload), tag):
            return "scores: bad tag"
        return ""
    except (KeyError, TypeError, ValueError, struct.error,
            UnicodeDecodeError) as e:
        return f"undecodable op/auth: {type(e).__name__}: {e}"


# --------------------------------------------------------------- validator
class ValidatorNode:
    """One member of the commit quorum: replica + wallet + vote server.

    Serves two methods over comm.wire frames:
    - ``bft_validate {i, op, auth?}``: validate op for chain position i.
      Exactly-once voting per position; ops arrive strictly in order
      (``OUT_OF_ORDER`` + our log size tells a lagging writer what to
      resend); re-requests for an already-applied identical op re-sign
      idempotently (a writer retrying after a lost reply must not wedge).
    - ``info``: replica position (log_size/log_head/epoch), the resync
      surface.

    The node APPLIES an op the moment it votes for it: its vote is a
    promise that this op IS position i of its chain, which is exactly
    what makes a second, different op at i unsignable ("CONFLICT").
    """

    def __init__(self, cfg: ProtocolConfig, wallet, index: int, *,
                 host: str = "127.0.0.1", port: int = 0,
                 ledger_backend: str = "python",
                 require_auth: bool = True,
                 directory: Optional[PublicDirectory] = None,
                 validator_keys: Optional[Dict[int, bytes]] = None,
                 quorum: Optional[int] = None,
                 verbose: bool = False):
        cfg.validate()
        self.cfg = cfg
        self.wallet = wallet
        self.index = index
        self.require_auth = require_auth
        # peer validator public keys: with these provisioned, a backlog op
        # carrying an existing quorum CERTIFICATE is admitted without
        # client auth evidence — the quorum already re-verified the tag,
        # and auth evidence is writer-process-local, so a validator that
        # restarts after a failover could otherwise never resync past
        # historical client ops (the f-tolerance must cover validator
        # crash + rejoin, not just crash)
        self.validator_keys: Dict[int, bytes] = dict(validator_keys or {})
        if self.validator_keys and quorum is None:
            quorum = bft_quorum(len(self.validator_keys))
        self.quorum = quorum or 0
        self.verbose = verbose
        # python backend by default: validate_op is O(1) snapshot/restore
        # there, O(chain) through the native mirror fallback
        self.ledger = make_ledger(cfg, backend=ledger_backend)
        self.directory = directory if directory is not None \
            else PublicDirectory()
        self._lock = threading.Lock()
        self._voted: Dict[int, bytes] = {}      # index -> op digest signed
        self._heads: List[bytes] = []           # head after each op
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(32)
        self.host, self.port = self._sock.getsockname()

    # ------------------------------------------------------------- server
    def start(self) -> None:
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def serve_forever(self) -> None:
        self.start()
        self._stop.wait()

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                msg = recv_msg(conn)
                if msg is None:
                    return
                method = msg.get("method", "")
                if method == "info":
                    with self._lock:
                        reply = {"ok": True, "validator": self.index,
                                 "log_size": self.ledger.log_size(),
                                 "log_head": self.ledger.log_head().hex(),
                                 "epoch": self.ledger.epoch}
                elif method == "bft_validate":
                    reply = self._validate(msg)
                else:
                    reply = {"ok": False,
                             "error": f"unknown method {method!r}"}
                send_msg(conn, reply)
        except (WireError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # --------------------------------------------------------------- vote
    def _refuse(self, status: str, detail: str = "") -> dict:
        if self.verbose:
            print(f"[validator {self.index}] refuse: {status} {detail}",
                  flush=True)
        return {"ok": False, "status": status, "detail": detail,
                "log_size": self.ledger.log_size()}

    def _sign_position(self, i: int, op: bytes) -> dict:
        prev = self._heads[i - 1] if i > 0 else _EMPTY_HEAD
        head = self._heads[i]
        sig = self.wallet.sign(cert_payload(i, prev, op, head))
        return {"ok": True, "i": i, "validator": self.index,
                "head": head.hex(), "sig": sig.hex()}

    def _certified_backlog(self, msg: dict, i: int, op: bytes) -> bool:
        """True when `msg` carries a quorum certificate binding exactly
        (i, OUR head, op) — an op the validator fleet already admitted
        once, acceptable without per-client auth evidence (which lives
        only in the original writer's process).  For register ops the
        self-authenticating pubkey still enrolls, so later FRESH ops from
        that client verify here."""
        if not self.validator_keys:
            return False
        cert_wire = msg.get("cert")
        if not isinstance(cert_wire, dict):
            return False
        try:
            cert = CommitCertificate.from_wire(cert_wire)
        except ValueError:
            return False
        prev = self._heads[i - 1] if i > 0 else _EMPTY_HEAD
        if not verify_certificate(cert, index=i, prev_head=prev, op=op,
                                  quorum=self.quorum,
                                  validator_keys=self.validator_keys):
            return False
        auth = msg.get("auth")
        if op and op[0] == _OP_REGISTER and isinstance(auth, dict):
            try:
                pub = bytes.fromhex(auth.get("pubkey", ""))
                body = op[1:]
                (n,) = struct.unpack_from("<q", body, 0)
                addr = body[8:8 + n].decode()
                if pub and address_of(pub) == addr \
                        and not self.directory.knows(addr):
                    self.directory.enroll(pub)
            except (ValueError, UnicodeDecodeError, struct.error):
                pass
        return True

    def _validate(self, msg: dict) -> dict:
        try:
            i = int(msg["i"])
            op = bytes.fromhex(msg["op"])
        except (KeyError, TypeError, ValueError):
            return self._refuse("BAD_REQUEST")
        op_hash = hashlib.sha256(op).digest()
        with self._lock:
            size = self.ledger.log_size()
            if i < size:
                # already bound here: idempotent re-sign IF it is the same
                # op; anything else is an attempted fork of our history
                if self._voted.get(i) == op_hash:
                    return self._sign_position(i, op)
                return self._refuse("CONFLICT",
                                    f"position {i} already holds a "
                                    f"different op")
            if i > size:
                # strict ordering: we cannot judge op i without the prefix
                return self._refuse("OUT_OF_ORDER",
                                    f"replica at {size}, asked for {i}")
            if self.require_auth:
                err = check_op_auth(op, msg.get("auth"), self.directory)
                if err and not self._certified_backlog(msg, i, op):
                    return self._refuse("AUTH", err)
            st = self.ledger.validate_op(op)
            if st != LedgerStatus.OK:
                # the replica's own re-execution of the decision procedure
                # (epoch/role/cap/duplicate guards) rejected the op
                return self._refuse(st.name)
            st = self.ledger.apply_op(op)
            if st != LedgerStatus.OK:   # unreachable: validate just passed
                return self._refuse(st.name, "apply after validate")
            self._voted[i] = op_hash
            self._heads.append(self.ledger.log_head())
            return self._sign_position(i, op)


class ValidatorClient:
    """Writer-side connection to one validator; reconnects lazily."""

    def __init__(self, endpoint: Endpoint, timeout_s: float = 10.0,
                 tls=None):
        self.endpoint = endpoint
        self.timeout_s = timeout_s
        self._tls = tls
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self.endpoint,
                                         timeout=self.timeout_s)
            if self._tls is not None:
                s = self._tls.wrap_socket(s,
                                          server_hostname=self.endpoint[0])
            self._sock = s
        return self._sock

    def request(self, method: str, **fields) -> dict:
        send_msg(self._connect(), {"method": method, **fields})
        reply = recv_msg(self._sock)
        if reply is None:
            raise ConnectionError("validator closed the connection")
        return reply

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class CertificateAssembler:
    """Collects a quorum of validator votes for consecutive ops.

    Owned by the writer (comm.ledger_service.LedgerServer) and by a
    promoting standby (for its fence op).  `certify(i, op, auth,
    prev_head)` contacts every validator in parallel, resyncing lagging
    replicas from `backlog_fn(j) -> (op, auth, cert_wire)` (a rejoining
    validator admits certified backlog ops on the certificate when the
    writer-local auth evidence is gone — see ValidatorNode), verifies
    each vote signature against the provisioned keys (a lying
    validator's garbage does not count), and returns the certificate
    once >= quorum distinct valid signatures agree — or None.
    """

    def __init__(self, endpoints: List[Endpoint],
                 validator_keys: Dict[int, bytes], quorum: int, *,
                 timeout_s: float = 10.0, tls=None, backlog_fn=None):
        self.endpoints = list(endpoints)
        self.keys = dict(validator_keys)
        self.quorum = quorum
        self.timeout_s = timeout_s
        self.backlog_fn = backlog_fn
        self._clients = [ValidatorClient(ep, timeout_s=timeout_s, tls=tls)
                         for ep in endpoints]

    def close(self) -> None:
        for c in self._clients:
            c.close()

    def _vote_one(self, client: ValidatorClient, i: int, op: bytes,
                  auth: Optional[dict]) -> Optional[dict]:
        """One validator's vote for (i, op), resyncing its replica from
        the backlog when it reports OUT_OF_ORDER.  None = no usable vote
        (refusal, conflict, or transport failure)."""
        for attempt in (0, 1):          # one reconnect per certify call
            try:
                r = client.request("bft_validate", i=i, op=op.hex(),
                                   auth=auth)
                while (not r.get("ok")
                       and r.get("status") == "OUT_OF_ORDER"
                       and self.backlog_fn is not None):
                    behind = int(r.get("log_size", -1))
                    if not 0 <= behind < i:
                        break
                    for j in range(behind, i):
                        entry = self.backlog_fn(j)
                        bop, bauth = entry[0], entry[1]
                        bcert = entry[2] if len(entry) > 2 else None
                        rj = client.request("bft_validate", i=j,
                                            op=bop.hex(), auth=bauth,
                                            cert=bcert)
                        if not rj.get("ok"):
                            return None
                    r = client.request("bft_validate", i=i, op=op.hex(),
                                       auth=auth)
                return r if r.get("ok") else None
            except (ConnectionError, WireError, OSError):
                client.close()
                if attempt:
                    return None
        return None

    def certify(self, i: int, op: bytes, auth: Optional[dict],
                prev_head: bytes) -> Optional[CommitCertificate]:
        new_head = next_head(prev_head, op)
        payload = cert_payload(i, prev_head, op, new_head)
        votes: Dict[int, bytes] = {}
        lock = threading.Lock()

        def ask(client):
            r = self._vote_one(client, i, op, auth)
            if r is None:
                return
            try:
                vidx = int(r["validator"])
                sig = bytes.fromhex(r["sig"])
            except (KeyError, TypeError, ValueError):
                return
            pub = self.keys.get(vidx)
            # verify BEFORE counting: a Byzantine validator's garbage
            # signature (or a vote minted on a diverged replica, whose
            # head therefore differs) must not contribute to the quorum
            if pub is not None and verify_signature(pub, payload, sig):
                with lock:
                    votes[vidx] = sig

        threads = [threading.Thread(target=ask, args=(c,), daemon=True)
                   for c in self._clients]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.timeout_s + 5.0)
        if len(votes) < self.quorum:
            return None
        return CommitCertificate(index=i, prev_head=prev_head or _EMPTY_HEAD,
                                 op_hash=hashlib.sha256(op).digest(),
                                 new_head=new_head, sigs=dict(votes))


def provision_validators(n: int, master_seed: bytes):
    """Deterministic validator identities for a deployment: wallets (one
    per validator, seeded like provision_wallets) + the public-key map
    every certificate verifier needs.  Returns (wallets, keys)."""
    from bflc_demo_tpu.comm.identity import Wallet
    wallets = [Wallet.from_seed(master_seed + b"|bft-validator|"
                                + struct.pack("<q", v)) for v in range(n)]
    return wallets, {v: w.public_bytes for v, w in enumerate(wallets)}
