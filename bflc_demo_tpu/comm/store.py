"""Content-addressed tensor store — the off-ledger payload plane.

Where the reference writes whole models as JSON strings into the replicated
chain table (local_updates map, CommitteePrecompiled.cpp:246-253), this store
keeps tensor pytrees in device/host memory keyed by their content hash; only
the 32-byte keys go into the ledger.  `get` verifies integrity by rehashing on
request (cheap at these sizes; gated for large payloads).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from bflc_demo_tpu.utils.serialization import hash_pytree

Pytree = Any


class UpdateStore:
    def __init__(self, verify_on_get: bool = True):
        self._blobs: Dict[bytes, Pytree] = {}
        self._verify = verify_on_get

    def put(self, tree: Pytree) -> bytes:
        h = hash_pytree(tree)
        self._blobs[h] = tree
        return h

    def get(self, h: bytes) -> Pytree:
        tree = self._blobs[h]
        if self._verify and hash_pytree(tree) != h:
            raise ValueError(f"payload integrity failure for {h.hex()[:16]}…")
        return tree

    def contains(self, h: bytes) -> bool:
        return h in self._blobs

    def drop(self, h: bytes) -> None:
        self._blobs.pop(h, None)

    def clear(self) -> None:
        self._blobs.clear()

    def __len__(self) -> int:
        return len(self._blobs)
