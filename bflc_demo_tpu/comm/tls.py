"""Control-plane TLS: confidentiality for every client<->coordinator byte.

The reference's client<->chain transport is the FISCO Channel protocol —
TLS with certs provisioned by copying the node's sdk/ directory
(README.md:240-260).  comm.wire's Ed25519 tags give integrity/authenticity
but (by documented scope) not confidentiality: score rows, model hashes and
blob traffic were readable on the wire.  This module closes that gap the
same way the reference does:

- `provision_tls(dir)` — the cert-copy step: a self-signed CA plus a
  server key/cert signed by it, written as PEMs (ca.pem, server.pem,
  server.key).  Idempotent: existing files are reused.
- `server_context(dir)` / `client_context(dir)` — ssl.SSLContexts for the
  two ends; the client verifies the server cert against the CA (server
  authentication + encryption; CLIENT authentication stays with Ed25519 op
  tags, which also cover the in-process runtimes where there is no socket).

LedgerServer accepts `tls=server_context(...)`; CoordinatorClient and
FailoverClient accept `tls=client_context(...)`.  A plaintext client
against a TLS server fails the handshake and is rejected.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
from typing import Tuple

CA_PEM = "ca.pem"
SERVER_PEM = "server.pem"
SERVER_KEY = "server.key"


def provision_tls(cert_dir: str, common_name: str = "127.0.0.1",
                  days: int = 365,
                  include_loopback: bool = True) -> Tuple[str, str, str]:
    """Write (or reuse) ca.pem / server.pem / server.key under cert_dir.

    Returns the three paths.  The server cert carries SANs for the common
    name and (unless include_loopback=False — e.g. provisioning for a real
    remote host) 127.0.0.1/localhost so loopback deployments verify
    cleanly.  Clients enforce the SAN match (client_context keeps
    check_hostname on), so a cert provisioned for one host is useless for
    impersonating another even inside the same CA.

    Without the `cryptography` wheel, generation falls back to the
    pure-Python Ed25519 x509 path (comm.x509mini — same files, same SAN
    policy; OpenSSL >= 1.1.1 negotiates TLS 1.3 with Ed25519 certs), so
    TLS provisioning works everywhere the repo's identity layer does.
    """
    os.makedirs(cert_dir, exist_ok=True)
    ca_path = os.path.join(cert_dir, CA_PEM)
    crt_path = os.path.join(cert_dir, SERVER_PEM)
    key_path = os.path.join(cert_dir, SERVER_KEY)
    if all(os.path.exists(p) for p in (ca_path, crt_path, key_path)):
        return ca_path, crt_path, key_path
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID
    except ImportError:
        from bflc_demo_tpu.comm.x509mini import provision_tls_pure
        return provision_tls_pure(cert_dir, common_name=common_name,
                                  days=days,
                                  include_loopback=include_loopback)

    now = datetime.datetime.now(datetime.timezone.utc)
    ca_key = ec.generate_private_key(ec.SECP256R1())
    ca_name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME,
                                            "bflc-demo-tpu-ca")])
    ca_cert = (x509.CertificateBuilder()
               .subject_name(ca_name).issuer_name(ca_name)
               .public_key(ca_key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - datetime.timedelta(minutes=5))
               .not_valid_after(now + datetime.timedelta(days=days))
               .add_extension(x509.BasicConstraints(ca=True,
                                                    path_length=0),
                              critical=True)
               .sign(ca_key, hashes.SHA256()))

    srv_key = ec.generate_private_key(ec.SECP256R1())
    sans = [x509.DNSName(common_name) if not _is_ip(common_name)
            else x509.IPAddress(ipaddress.ip_address(common_name))]
    if include_loopback:
        sans.insert(0, x509.DNSName("localhost"))
        sans.append(x509.IPAddress(ipaddress.ip_address("127.0.0.1")))
    srv_cert = (x509.CertificateBuilder()
                .subject_name(x509.Name([x509.NameAttribute(
                    NameOID.COMMON_NAME, common_name)]))
                .issuer_name(ca_name)
                .public_key(srv_key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - datetime.timedelta(minutes=5))
                .not_valid_after(now + datetime.timedelta(days=days))
                .add_extension(x509.SubjectAlternativeName(sans),
                               critical=False)
                .sign(ca_key, hashes.SHA256()))

    with open(ca_path, "wb") as f:
        f.write(ca_cert.public_bytes(serialization.Encoding.PEM))
    with open(crt_path, "wb") as f:
        f.write(srv_cert.public_bytes(serialization.Encoding.PEM))
    # 0600: the unencrypted server key must not be world-readable — a local
    # reader could impersonate the coordinator
    fd = os.open(key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(srv_key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption()))
    return ca_path, crt_path, key_path


def _is_ip(name: str) -> bool:
    try:
        ipaddress.ip_address(name)
        return True
    except ValueError:
        return False


def server_context(cert_dir: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_cert_chain(os.path.join(cert_dir, SERVER_PEM),
                        os.path.join(cert_dir, SERVER_KEY))
    return ctx


def client_context(cert_dir: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_verify_locations(os.path.join(cert_dir, CA_PEM))
    # Full server identity: the presented cert must chain to the CA AND
    # carry a SAN matching the address the client dialed (ssl validates IP
    # SANs under check_hostname too — provision_tls always includes the
    # 127.0.0.1 IP SAN plus the deployment's common name).  CA membership
    # alone would let any CA-signed cert impersonate any server.
    ctx.check_hostname = True
    ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx
