"""Seeded synthetic datasets shaped like the scale-out configs' benchmarks.

This image has zero egress, so MNIST/CIFAR/FEMNIST/SST-2 cannot be
downloaded; these generators produce learnable class-conditional data with
the right shapes/cardinalities so every config's full protocol path (models,
partitioners, committee scoring, aggregation) runs and converges for real.
A run against the true datasets only requires pointing the loaders at files
on disk (see `load_image_dataset`).
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np


def synthetic_image_classification(n: int, shape: Tuple[int, ...],
                                   num_classes: int, seed: int = 0,
                                   noise: float = 0.35,
                                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Class template + Gaussian noise images in [0, 1]; learnable by a
    linear probe but not trivially (noise swamps individual pixels)."""
    rng = np.random.default_rng(seed)
    templates = rng.random((num_classes,) + tuple(shape), np.float32)
    y = rng.integers(0, num_classes, n).astype(np.int32)
    x = templates[y] + rng.standard_normal((n,) + tuple(shape)).astype(
        np.float32) * noise
    return np.clip(x, 0.0, 1.0).astype(np.float32), y


def _real_or_synthetic(name: str, n: int, shape, num_classes: int,
                       seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Prefer a real dataset dropped at $BFLC_DATA_DIR/<name>.npz (arrays
    'x'/'y', `load_image_dataset` contract); otherwise the seeded synthetic
    stand-in.  Shape/cardinality are validated so a mislabeled file fails
    loudly instead of silently training on the wrong geometry."""
    data_dir = os.environ.get("BFLC_DATA_DIR", "")
    if data_dir:
        path = os.path.join(data_dir, f"{name}.npz")
        if os.path.exists(path):
            x, y = load_image_dataset(path)
            if tuple(x.shape[1:]) != tuple(shape):
                raise ValueError(f"{path}: images are {x.shape[1:]}, "
                                 f"config expects {shape}")
            if int(y.min()) < 0 or int(y.max()) >= num_classes:
                raise ValueError(f"{path}: labels span "
                                 f"[{int(y.min())}, {int(y.max())}], "
                                 f"need [0, {num_classes})")
            if float(x.min()) < 0.0 or float(x.max()) > 1.0:
                raise ValueError(f"{path}: pixel range "
                                 f"[{float(x.min()):g}, "
                                 f"{float(x.max()):g}] violates the [0, 1] "
                                 f"contract (0-255 file? divide by 255)")
            if n and len(x) < n:
                raise ValueError(f"{path}: {len(x)} samples < requested "
                                 f"{n}; lower n_data or provide more data")
            if n and len(x) > n:
                rng = np.random.default_rng(seed)
                idx = rng.permutation(len(x))[:n]
                return x[idx], y[idx]
            return x, y
    return synthetic_image_classification(n, shape, num_classes, seed)


def synthetic_mnist(n: int = 6000, seed: int = 0):
    return _real_or_synthetic("mnist", n, (28, 28, 1), 10, seed)


def synthetic_cifar10(n: int = 6000, seed: int = 0):
    return _real_or_synthetic("cifar10", n, (32, 32, 3), 10, seed)


def synthetic_cifar100(n: int = 6000, seed: int = 0):
    return _real_or_synthetic("cifar100", n, (32, 32, 3), 100, seed)


def synthetic_femnist(n: int = 8000, seed: int = 0):
    return _real_or_synthetic("femnist", n, (28, 28, 1), 62, seed)


def synthetic_text_classification(n: int, seq_len: int = 64,
                                  vocab_size: int = 1000,
                                  num_classes: int = 2, seed: int = 0,
                                  ) -> Tuple[np.ndarray, np.ndarray]:
    """SST-2-shaped token sequences: class-conditional unigram mixtures over
    a shared background distribution (id 0 = PAD)."""
    rng = np.random.default_rng(seed)
    background = rng.dirichlet([0.1] * (vocab_size - 1))
    class_dists = []
    for _ in range(num_classes):
        signal = rng.dirichlet([0.05] * (vocab_size - 1))
        class_dists.append(0.7 * background + 0.3 * signal)
    y = rng.integers(0, num_classes, n).astype(np.int32)
    x = np.zeros((n, seq_len), np.int32)
    for c in range(num_classes):
        idx = np.flatnonzero(y == c)
        draws = rng.choice(vocab_size - 1, size=(len(idx), seq_len),
                           p=class_dists[c]) + 1
        x[idx] = draws.astype(np.int32)
    # variable lengths: pad a random tail with 0
    lengths = rng.integers(seq_len // 2, seq_len + 1, n)
    for i in range(n):
        x[i, lengths[i]:] = 0
    return x, y


def load_image_dataset(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Load a real dataset from an .npz with arrays 'x' (N,H,W,C in [0,1])
    and 'y' (N,) int labels — the hook for running the benchmark configs on
    true MNIST/CIFAR files when they are available on disk."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    with np.load(path) as z:
        return (np.asarray(z["x"], np.float32),
                np.asarray(z["y"], np.int32))
