"""Datasets and client partitioning.

The reference's data layer (SURVEY.md §1 L4) is the UCI Occupancy Detection
CSV, split 75/25 and sharded contiguously over 20 clients
(python-sdk/main.py:33-53).  This package reproduces that pipeline in numpy
(host side; shards are device_put once and stay in HBM) and adds the
partitioners the scale-out configs need (Dirichlet non-IID, per-round client
sampling).
"""

from bflc_demo_tpu.data.occupancy import load_occupancy, synthesize_occupancy  # noqa: F401
from bflc_demo_tpu.data.partition import iid_shards, dirichlet_shards, one_hot  # noqa: F401
