"""UCI Occupancy Detection dataset — config-1 parity data pipeline.

Reference (python-sdk/main.py:33-53): read data/datatraining.txt (8,143 rows;
features Temperature, Humidity, Light, CO2, HumidityRatio; binary Occupancy
label, imbalanced 6,414/1,729), 75/25 train/test split with a fixed seed,
one-hot labels, train side split into CLIENT_NUM contiguous shards.

The CSV itself is UCI data, not framework code; we read it from disk when
present (BFLC_TPU_OCCUPANCY env var or a default path) and otherwise fall back
to a seeded synthetic generator with the same shape, scale and class-imbalance
structure so the whole test suite is hermetic.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np

N_FEATURES = 5
N_CLASS = 2

def _default_paths() -> tuple:
    # env var read per-call so late os.environ changes are honoured
    return (
        os.environ.get("BFLC_TPU_OCCUPANCY", ""),
        os.path.join(os.path.dirname(__file__), "datatraining.txt"),
        "/root/reference/python-sdk/data/datatraining.txt",
    )


def _parse_csv(path: str) -> Tuple[np.ndarray, np.ndarray]:
    feats, labels = [], []
    with open(path, "r") as f:
        header = f.readline()  # "date","Temperature",... — discarded
        del header
        for line in f:
            parts = line.rstrip("\n").split(",")
            if len(parts) < 8:
                continue
            # parts: "rowid","date",Temp,Humidity,Light,CO2,HumidityRatio,Occupancy
            feats.append([float(v) for v in parts[2:7]])
            labels.append(int(parts[7]))
    return np.asarray(feats, np.float32), np.asarray(labels, np.int32)


def synthesize_occupancy(n: int = 8143, seed: int = 0,
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Seeded stand-in with the real dataset's scale and imbalance.

    Class-conditional Gaussians around the real data's per-class feature means
    (occupied rooms: more light, more CO2, slightly warmer) at realistic
    magnitudes, ~21% positive rate like the real 1,729/8,143.
    """
    rng = np.random.default_rng(seed)
    y = (rng.random(n) < 0.2123).astype(np.int32)
    mu0 = np.array([20.6, 27.0, 40.0, 600.0, 0.0042], np.float32)
    mu1 = np.array([22.4, 27.5, 460.0, 1000.0, 0.0047], np.float32)
    sd = np.array([1.0, 4.5, 120.0, 180.0, 0.0007], np.float32)
    x = np.where(y[:, None] == 1, mu1, mu0) + rng.standard_normal(
        (n, N_FEATURES)).astype(np.float32) * sd
    return x.astype(np.float32), y


def occupancy_source() -> str:
    """'csv' when a real datatraining.txt is reachable through the default
    path chain, else 'synthetic'.  Accuracy bars calibrate per source: the
    reference's 0.9214 plateau is a property of the REAL distribution; the
    seeded stand-in is more linearly separable but worse-conditioned (raw
    light/CO2 scales), so its fixed-lr trajectory oscillates and peaks
    differently — tests assert the matching band, never silently cross."""
    return "csv" if any(p and os.path.exists(p)
                        for p in _default_paths()) else "synthetic"


def load_occupancy(test_fraction: float = 0.25, seed: int = 42,
                   path: str | None = None,
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Returns (x_train, y_train, x_test, y_test); labels as int32 class ids.

    Split mirrors the reference's train_test_split(test_size=0.25,
    random_state=42) (main.py:41-42): one seeded permutation, last quarter out.
    """
    if path is not None:
        # an explicit path must not silently degrade to synthetic data
        if not os.path.exists(path):
            raise FileNotFoundError(f"occupancy dataset not found: {path}")
        x, y = _parse_csv(path)
    else:
        x = y = None
        for p in _default_paths():
            if p and os.path.exists(p):
                x, y = _parse_csv(p)
                break
        if x is None:
            x, y = synthesize_occupancy()

    rng = np.random.default_rng(seed)
    perm = rng.permutation(len(x))
    x, y = x[perm], y[perm]
    n_test = int(len(x) * test_fraction)
    return (x[n_test:], y[n_test:], x[:n_test], y[:n_test])
