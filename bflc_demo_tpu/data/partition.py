"""Client-shard partitioners.

- `iid_shards`: contiguous near-equal split, the reference's
  np.array_split(train, CLIENT_NUM) (main.py:47-48).
- `dirichlet_shards`: label-skewed non-IID split (Dirichlet over label
  proportions per client) for the CIFAR-style configs (BASELINE.json config 2).
- `one_hot`: label encoding (main.py:43-44).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def one_hot(y: np.ndarray, num_classes: int) -> np.ndarray:
    out = np.zeros((len(y), num_classes), np.float32)
    out[np.arange(len(y)), y] = 1.0
    return out


def iid_shards(x: np.ndarray, y: np.ndarray, num_clients: int,
               ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Contiguous near-equal shards (np.array_split semantics, main.py:47-48)."""
    xs = np.array_split(x, num_clients)
    ys = np.array_split(y, num_clients)
    return list(zip(xs, ys))


def dirichlet_shards(x: np.ndarray, y: np.ndarray, num_clients: int,
                     alpha: float = 0.5, seed: int = 0, min_size: int = 2,
                     ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Label-skew non-IID: per class, split indices by Dirichlet(alpha) props.

    Standard recipe for federated CIFAR benchmarks; lower alpha = more skew.
    Re-draws until every client holds at least `min_size` examples.
    """
    rng = np.random.default_rng(seed)
    classes = np.unique(y)
    for _ in range(100):
        idx_per_client: List[List[int]] = [[] for _ in range(num_clients)]
        for c in classes:
            idx_c = np.flatnonzero(y == c)
            rng.shuffle(idx_c)
            props = rng.dirichlet([alpha] * num_clients)
            cuts = (np.cumsum(props) * len(idx_c)).astype(int)[:-1]
            for client, part in enumerate(np.split(idx_c, cuts)):
                idx_per_client[client].extend(part.tolist())
        if min(len(ix) for ix in idx_per_client) >= min_size:
            return [(x[np.asarray(ix, dtype=np.intp)],
                     y[np.asarray(ix, dtype=np.intp)]) for ix in idx_per_client]
    raise ValueError(
        f"could not draw a Dirichlet(alpha={alpha}) split giving every one of "
        f"{num_clients} clients >= {min_size} examples from {len(x)} rows")
