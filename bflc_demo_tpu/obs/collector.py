"""FleetCollector: scrape every live role into one run artifact.

The process-federation driver (client/process_runtime) owns the fleet's
endpoint map, so it is the natural scrape point — each round it pulls a
`telemetry` RPC snapshot from every wire-serving role (writer,
validators, mesh executor) and reads the file snapshots that socket-less
roles (clients, un-promoted standbys) publish via their telemetry
thread.  Everything lands on ONE timeline file, `metrics.jsonl`:

    {"type": "scrape", "t": ..., "tag": ..., "roles": {role: snapshot},
     "coverage": {"answered": n, "expected": m, "missing": [...]}}
    {"type": "fault", "t": ..., ...}      # chaos events, interleaved
    {"type": "note",  "t": ..., ...}      # run milestones (round commits)

so a chaos post-mortem reads fault -> metric causality off a single
ordered stream (tools/fleet_top.py renders it).  A scrape NEVER raises:
an unreachable role is a coverage miss, not a driver crash — under
faults the collector's job is precisely to keep observing the part of
the fleet that still answers.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from bflc_demo_tpu.obs import metrics as obs_metrics


def publish_snapshot(path: str) -> bool:
    """Write the process registry's snapshot to `path` atomically — the
    file-publication half for roles that serve no socket.  True when a
    file was written."""
    snap = obs_metrics.REGISTRY.snapshot()
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as fh:
            json.dump(snap, fh)
        os.replace(tmp, path)
        return True
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def read_snapshot_file(path: str) -> Optional[dict]:
    """A file-published snapshot, or None when absent/garbled (a role
    killed mid-publish leaves the previous complete file — rename-into-
    place — so garble means 'never published', not 'torn')."""
    try:
        with open(path) as fh:
            snap = json.load(fh)
        return snap if isinstance(snap, dict) else None
    except (OSError, ValueError):
        return None


def load_timeline(jsonl_path: str) -> List[dict]:
    """Parse a metrics.jsonl run artifact, skipping any garbled line
    (a crashed driver may tear the final append)."""
    out: List[dict] = []
    try:
        with open(jsonl_path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


class FleetCollector:
    """Periodic whole-fleet scraper writing the metrics.jsonl timeline.

    rpc_roles: {role: (host, port)} — roles serving the `telemetry` wire
    RPC (writer, validators, executor).
    file_roles: {role: path} — roles publishing snapshot files instead
    (clients, standbys); a missing file counts as a coverage miss.
    tls/tls_roles: the ssl context is applied ONLY to roles named in
    `tls_roles` — in a TLS deployment the coordinator serves TLS but the
    BFT validators speak plaintext on the coordinator-side segment, so
    one blanket context would fail every validator scrape.
    """

    def __init__(self, rpc_roles: Dict[str, Tuple[str, int]],
                 file_roles: Optional[Dict[str, str]] = None, *,
                 jsonl_path: str = "", timeout_s: float = 1.0,
                 tls=None, tls_roles=()):
        self.rpc_roles = dict(rpc_roles)
        self.file_roles = dict(file_roles or {})
        self.jsonl_path = jsonl_path
        self.timeout_s = timeout_s
        self.tls = tls
        self.tls_roles = set(tls_roles)
        self.scrapes = 0
        self.answered_total = 0
        self.expected_total = 0
        self.last_scrape: Optional[dict] = None
        # record observers (obs.timeline.RoundForensics subscribes):
        # every scrape/fault/note record is handed to each observer as
        # it is appended — the live feed of the round-forensics joiner
        self.observers: List = []
        if jsonl_path:
            os.makedirs(os.path.dirname(os.path.abspath(jsonl_path)),
                        exist_ok=True)

    def add_observer(self, fn) -> None:
        """Subscribe `fn(record)` to the collector's record stream (the
        same records metrics.jsonl receives).  Observer errors are
        swallowed — a forensics bug must never break the scrape loop."""
        self.observers.append(fn)

    # ------------------------------------------------------------- write
    def _append(self, rec: dict) -> None:
        for fn in self.observers:
            try:
                fn(rec)
            except Exception:   # noqa: BLE001 — observability only
                pass
        if not self.jsonl_path:
            return
        try:
            with open(self.jsonl_path, "a") as fh:
                fh.write(json.dumps(rec) + "\n")
        except OSError:
            pass

    def note(self, name: str, **attrs) -> None:
        """Milestone line on the shared timeline (round commits etc.)."""
        self._append({"type": "note", "t": time.time(), "name": name,
                      **attrs})

    def observe_fault(self, event: dict, source: str = "chaos") -> None:
        """Inject a chaos FaultEvent (or any fault dict) into the
        timeline — the fault->metric causality anchor.  A chaos event's
        own 't' is schedule-relative (seconds from campaign t0); it must
        not clobber the record's wall-clock 't' or the merged timeline
        sorts every fault to the dawn of time."""
        ev = dict(event)
        if "t" in ev:
            ev["t_sched"] = ev.pop("t")
        self._append({"type": "fault", "t": time.time(),
                      "source": source, **ev})

    # ------------------------------------------------------------ scrape
    def _scrape_rpc(self, role: str, ep: Tuple[str, int]
                    ) -> Tuple[Optional[dict], Optional[int]]:
        """(snapshot, reported ledger epoch).  The epoch rides the
        `telemetry` reply itself (comm.ledger_service) — the writer's
        authoritative round position at scrape time, stamped into the
        scrape record so the forensics joiner never has to infer it
        from wall clocks (obs.timeline.round_of_scrape)."""
        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        try:
            c = CoordinatorClient(ep[0], ep[1], timeout_s=self.timeout_s,
                                  tls=(self.tls if role in self.tls_roles
                                       else None))
        except (ConnectionError, OSError):
            return None, None
        try:
            r = c.request("telemetry")
            snap = r.get("snapshot")
            rep_ep = r.get("epoch")
            return (snap if r.get("ok") and isinstance(snap, dict)
                    else None,
                    rep_ep if isinstance(rep_ep, int) else None)
        except (ConnectionError, OSError, ValueError):
            return None, None
        finally:
            c.close()

    def scrape(self, tag: Any = None) -> dict:
        """One fleet-wide scrape; appends the record to metrics.jsonl
        and returns it.  Partial coverage is normal under faults.  The
        record carries `epoch` — the writer-reported ledger epoch —
        whenever the writer answered (fault-darkened writers leave it
        absent; the joiner falls back to the tag)."""
        roles: Dict[str, Optional[dict]] = {}
        epoch: Optional[int] = None
        for role, ep in self.rpc_roles.items():
            snap, rep_ep = self._scrape_rpc(role, ep)
            roles[role] = snap
            if role == "writer" and rep_ep is not None:
                epoch = rep_ep
        for role, path in self.file_roles.items():
            roles[role] = read_snapshot_file(path)
        answered = sorted(r for r, s in roles.items() if s is not None)
        missing = sorted(r for r, s in roles.items() if s is None)
        rec = {"type": "scrape", "t": time.time(), "tag": tag,
               "roles": {r: s for r, s in roles.items()
                         if s is not None},
               "coverage": {"answered": len(answered),
                            "expected": len(roles),
                            "missing": missing}}
        if epoch is not None:
            rec["epoch"] = epoch
        self.scrapes += 1
        self.answered_total += len(answered)
        self.expected_total += len(roles)
        self.last_scrape = rec
        self._append(rec)
        return rec

    # ---------------------------------------------------------- reports
    def coverage_report(self) -> dict:
        return {"scrapes": self.scrapes,
                "roles_expected": len(self.rpc_roles)
                + len(self.file_roles),
                "answered_total": self.answered_total,
                "expected_total": self.expected_total,
                "coverage": (self.answered_total / self.expected_total
                             if self.expected_total else 0.0),
                "last_missing": (self.last_scrape or {}).get(
                    "coverage", {}).get("missing", [])}

    def write_prometheus(self, path: str) -> bool:
        """Dump the latest scrape in Prometheus text format (role label
        distinguishes the fleet's processes)."""
        if self.last_scrape is None:
            return False
        snaps = []
        for role, snap in sorted(self.last_scrape["roles"].items()):
            # the collector's role key wins: it is what the operator
            # addresses the process by (a shared-process fleet self-
            # declares one registry role — or none at all)
            snaps.append({**snap, "role": role})
        text = obs_metrics.to_prometheus(snaps)
        try:
            with open(path, "w") as fh:
                fh.write(text)
            return True
        except OSError:
            return False
