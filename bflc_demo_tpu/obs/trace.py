"""Causal op tracing: fleet-wide spans + wire trace-context (Dapper).

The metrics plane (obs.metrics) says *how much* and the flight recorders
say *what last happened*; nothing could answer *why was round N slow* —
an upload's journey (client train -> writer admission -> BFT vote batch
-> certificate -> standby mirror -> aggregate -> commit -> read fan-out)
crosses five processes and no identifier followed it.  This module is
the Dapper design (Sigelman et al., 2010) grafted onto the certified op
stream:

- **head-based sampling**: the trace decision is made ONCE, where an op
  originates (the client's round action), and propagates with the
  context — `--trace-sample P` (default 0 = off); an unsampled op costs
  one float compare and nothing else.
- **context propagation**: an active span's `traceparent`
  (``00-<trace_id>-<span_id>-01``, W3C shape) rides as a `_tp` field in
  every wire frame `comm.wire.send_msg` emits while the span is open.
  The field is plain JSON header data, so it survives BIN1, legacy
  hex-JSON and compressed frames unchanged, and an untraced peer simply
  ignores the extra key — mixed-fleet safe by construction.
- **local span emission**: each process appends finished spans to a
  bounded ring flushed tmp-then-rename to ``<role>.spans.jsonl`` in the
  telemetry dir (the flight-recorder discipline: the artifact either
  parses or is the previous complete flush).  Spans carry MONOTONIC
  timestamps plus a per-process (wall, mono) anchor in the file header,
  so durations are immune to wall-clock steps and the offline reader
  re-anchors each process onto the shared wall timeline.
- **offline reassembly**: `gather_spans` / `assemble_traces` /
  `round_reports` rebuild causality after the fact — including spans
  that belong to MANY traces at once (a batched BFT vote round-trip
  certifies ops from several clients; it records `links` to every op's
  trace) — and compute the per-round **critical path**: a timeline walk
  that attributes every instant of the round to the deepest span active
  then, so the segment sum equals the round wall time by construction
  and "which edge did the round wait on" is read off, not guessed.

Trust: spans are ADVISORY observability data.  Nothing here touches
admission, certification or the certificate byte format; a forged or
absent `_tp` can at worst mislabel a trace (PARITY.md).

``BFLC_TRACE_LEGACY=1`` pins tracing out entirely (install becomes a
no-op), the before/after benchmark switch
(`eval.benchmarks.trace_overhead_config1`).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple


def trace_legacy() -> bool:
    """True when tracing is pinned out (benchmark before-leg)."""
    return bool(os.environ.get("BFLC_TRACE_LEGACY"))


# ---------------------------------------------------------- traceparent
_TP_VERSION = "00"
_TP_FLAGS = "01"


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"{_TP_VERSION}-{trace_id}-{span_id}-{_TP_FLAGS}"


def parse_traceparent(tp) -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) or None on anything malformed — a hostile or
    garbled header must never raise out of a dispatch path."""
    if not isinstance(tp, str):
        return None
    parts = tp.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16)
        int(parts[2], 16)
    except ValueError:
        return None
    return parts[1], parts[2]


# ------------------------------------------------------------- recorder
class _NullSpan:
    """Singleton no-op context manager for the disabled/unsampled path:
    no span allocation, no clock read, no context mutation."""

    __slots__ = ()

    def __enter__(self):
        return _SINK

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()
#: attrs sink handed out by the null span so call sites can always write
#: `sp["key"] = v`; never read, bounded by the handful of attr keys the
#: instrumentation sites use
_SINK: Dict[str, Any] = {}


class _SpanCM:
    """One live span: activates its context thread-locally on enter,
    records the finished span on exit (exception or not)."""

    __slots__ = ("_rec", "trace", "parent", "name", "links", "attrs",
                 "_sid", "_t0", "_prev")

    def __init__(self, rec: "SpanRecorder", trace: str,
                 parent: Optional[str], name: str,
                 links: Optional[List[str]], attrs: Dict[str, Any]):
        self._rec = rec
        self.trace = trace
        self.parent = parent
        self.name = name
        self.links = links
        self.attrs = attrs

    def __enter__(self):
        rec = self._rec
        self._sid = os.urandom(8).hex()
        self._prev = getattr(rec._local, "ctx", None)
        rec._local.ctx = (self.trace, self._sid)
        self._t0 = time.monotonic()
        return self.attrs

    def __exit__(self, *exc):
        t1 = time.monotonic()
        rec = self._rec
        rec._local.ctx = self._prev
        span = dict(self.attrs)
        span.update({"trace": self.trace, "span": self._sid,
                     "parent": self.parent, "role": rec.role,
                     "name": self.name, "t0": self._t0, "t1": t1})
        if self.links:
            span["links"] = self.links
        with rec._lock:
            rec._ring.append(span)
        return False


class SpanRecorder:
    """Process-wide span buffer + head sampler + context holder.

    Disabled by default: every entry point is one attribute check and a
    singleton return.  Armed by `obs.install_process_telemetry(...,
    trace_sample=P)` (the federation spawner threads the sample rate
    through each child's telemetry spec) or `install` directly.
    Access as `trace.TRACE` (module attribute) — same aliasing rule as
    metrics.REGISTRY.
    """

    def __init__(self, capacity: int = 8192):
        self.enabled = False
        self.sample = 0.0
        self.role = ""
        self.path = ""
        self.anchor_wall = 0.0
        self.anchor_mono = 0.0
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.RLock()
        self._local = threading.local()
        self._rng = random.Random()
        self._flusher: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------ context
    def _ctx(self) -> Optional[Tuple[str, str]]:
        return getattr(self._local, "ctx", None)

    def current_traceparent(self) -> Optional[str]:
        """The active span's wire context, or None.  `comm.wire.send_msg`
        calls this behind an `enabled` check — the one hot-path hook."""
        ctx = self._ctx()
        return format_traceparent(*ctx) if ctx is not None else None

    # -------------------------------------------------------------- spans
    def start_trace(self, name: str, **attrs):
        """Root span: the HEAD sampling decision happens here and only
        here — an unsampled root returns the null span, no context
        activates, and nothing downstream (wire `_tp`, server spans,
        stream tp, vote links) ever sees the op."""
        if not self.enabled or self._rng.random() >= self.sample:
            return _NULL
        return _SpanCM(self, os.urandom(16).hex(), None, name, None,
                       attrs)

    def span(self, name: str, **attrs):
        """Child of the thread's ambient span; null without one (so
        instrumentation sites need no sampled/unsampled awareness)."""
        if not self.enabled:
            return _NULL
        ctx = self._ctx()
        if ctx is None:
            return _NULL
        return _SpanCM(self, ctx[0], ctx[1], name, None, attrs)

    def span_from(self, traceparent, name: str,
                  links: Optional[Sequence[str]] = None, **attrs):
        """Child of an EXPLICIT remote parent (`traceparent` from a wire
        frame or a stashed context), optionally linked into further
        traces (`links`: traceparents of every op a batched operation
        covers).  With no parseable parent but usable links, the span
        roots itself in the first linked trace — a monitor-loop certify
        sweep still lands in the traces it served."""
        if not self.enabled:
            return _NULL
        link_ids = None
        if links:
            link_ids = [pc[0] for pc in
                        (parse_traceparent(t) for t in links)
                        if pc is not None]
            link_ids = link_ids or None
        pc = parse_traceparent(traceparent)
        if pc is None:
            if not link_ids:
                return _NULL
            return _SpanCM(self, link_ids[0], None, name, link_ids,
                           attrs)
        return _SpanCM(self, pc[0], pc[1], name, link_ids, attrs)

    # -------------------------------------------------------------- flush
    def flush(self, reason: str = "periodic") -> bool:
        """Persist the ring atomically (tmp + rename — the flight
        recorder discipline: a kill mid-flush leaves the previous
        complete file, never a torn one)."""
        if not self.path:
            return False
        with self._lock:
            spans = list(self._ring)
        header = {"type": "spans_header", "role": self.role,
                  "pid": os.getpid(), "sample": self.sample,
                  "anchor_wall": self.anchor_wall,
                  "anchor_mono": self.anchor_mono,
                  "reason": reason, "flushed_at": time.time(),
                  "n_spans": len(spans)}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                fh.write(json.dumps(header) + "\n")
                for s in spans:
                    fh.write(json.dumps(s) + "\n")
            os.replace(tmp, self.path)
            return True
        except (OSError, TypeError, ValueError):
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False

    def _flush_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            self.flush("periodic")

    # ------------------------------------------------------------ install
    def install(self, role: str, out_dir: str, *, sample: float,
                interval_s: float = 1.0) -> None:
        """Arm the recorder: per-role spans file, periodic flusher, and a
        terminal flush chained onto the flight recorder's SIGTERM /
        excepthook / atexit paths (obs.flight.TERMINAL_FLUSHES) so a
        terminated role loses at most one flush interval of tail.
        BFLC_TRACE_LEGACY=1 or sample <= 0 leaves tracing pinned out."""
        if trace_legacy() or sample <= 0.0:
            return
        os.makedirs(out_dir, exist_ok=True)
        self.role = role
        self.sample = min(float(sample), 1.0)
        self.path = os.path.join(out_dir, f"{role}.spans.jsonl")
        self.anchor_wall = time.time()
        self.anchor_mono = time.monotonic()
        self.enabled = True
        if self._flusher is None:
            self._flusher = threading.Thread(
                target=self._flush_loop, args=(interval_s,), daemon=True)
            self._flusher.start()
            import atexit

            from bflc_demo_tpu.obs import flight
            flight.TERMINAL_FLUSHES.append(
                lambda: self.flush("terminal"))
            atexit.register(lambda: self.flush("atexit"))
        # file exists from role bring-up (even an instant kill leaves a
        # parseable, possibly empty artifact)
        self.flush("install")

    def close(self) -> None:
        self._stop.set()
        if self.enabled:
            self.flush("close")
        self.enabled = False


#: process-wide recorder every instrumentation site consults.  Access as
#: `trace.TRACE` (module attribute), never `from ... import TRACE`.
TRACE = SpanRecorder()


def server_span(msg: dict, name: str, links_key: str = "", **attrs):
    """Serve-side adoption helper: a span parented on the request's
    `_tp` wire context (and linked via `msg[links_key]` when given) —
    the null span when tracing is off or the frame is untraced, so
    dispatch loops call it unconditionally."""
    if not TRACE.enabled:
        return _NULL
    links = msg.get(links_key) if links_key else None
    return TRACE.span_from(msg.get("_tp"), name,
                           links=links if isinstance(links, list)
                           else None, **attrs)


# ===================================================== offline analysis
_CORE_KEYS = ("trace", "span", "parent", "role", "name", "t0", "t1",
              "links")


def load_spans(path: str) -> List[dict]:
    """Parse one ``<role>.spans.jsonl``: spans with t0/t1 re-anchored to
    WALL time via the header's (wall, mono) anchor pair, role attached.
    Garbled lines are skipped (same tolerance as the flight loader)."""
    out: List[dict] = []
    header: Optional[dict] = None
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict):
                    continue
                if rec.get("type") == "spans_header":
                    header = rec
                    continue
                if "trace" not in rec or "t0" not in rec:
                    continue
                out.append(rec)
    except OSError:
        return []
    off = 0.0
    if header is not None:
        off = (float(header.get("anchor_wall", 0.0))
               - float(header.get("anchor_mono", 0.0)))
    for s in out:
        s["t0"] = float(s["t0"]) + off
        s["t1"] = float(s["t1"]) + off
        s.setdefault("role", (header or {}).get("role", ""))
    return out


def gather_spans(telemetry_dir: str) -> List[dict]:
    """Every span from every ``*.spans.jsonl`` in the telemetry dir —
    the whole-fleet view the FleetCollector's artifact directory holds."""
    spans: List[dict] = []
    try:
        names = sorted(os.listdir(telemetry_dir))
    except OSError:
        return spans
    for n in names:
        if n.endswith(".spans.jsonl"):
            spans.extend(load_spans(os.path.join(telemetry_dir, n)))
    return spans


def assemble_traces(spans: Iterable[dict]) -> Dict[str, List[dict]]:
    """{trace_id: [span, ...]} with multi-trace spans (batched votes /
    certifies carrying `links`) attached to EVERY trace they served."""
    traces: Dict[str, List[dict]] = {}
    for s in spans:
        traces.setdefault(s["trace"], []).append(s)
        for lt in s.get("links") or ():
            if lt != s["trace"]:
                traces.setdefault(lt, []).append(s)
    return traces


def role_class(role: str) -> str:
    """'client-7' -> 'client' (aggregation key); 'writer' -> 'writer'."""
    head, sep, tail = role.rpartition("-")
    return head if sep and tail.isdigit() else role


def span_label(s: dict, full_role: bool = False) -> str:
    role = s.get("role", "") if full_role else role_class(
        s.get("role", ""))
    name = s.get("name", "?")
    if "method" in s:
        name = f"{name}[{s['method']}]"
    return f"{role}:{name}" if role else name


def trace_role_classes(trace_spans: Iterable[dict]) -> List[str]:
    return sorted({role_class(s.get("role", "")) for s in trace_spans})


def critical_path(spans: List[dict], lo: float,
                  hi: float) -> List[Tuple[str, float]]:
    """Attribute every instant of [lo, hi] to the DEEPEST span active
    then (children start after parents, so latest-start ~= deepest; ties
    go to the shorter span).  Instants no span covers are ``(wait)``.
    The segment durations therefore sum to exactly hi - lo — the
    critical path is a partition of the round wall time, not a guess."""
    if hi <= lo:
        return []
    cuts = {lo, hi}
    for s in spans:
        for t in (s["t0"], s["t1"]):
            if lo < t < hi:
                cuts.add(t)
    bounds = sorted(cuts)
    segs: List[Tuple[str, float]] = []
    for a, b in zip(bounds, bounds[1:]):
        active = [s for s in spans if s["t0"] <= a < s["t1"]]
        if active:
            deepest = max(active,
                          key=lambda s: (s["t0"], s["t0"] - s["t1"]))
            label = span_label(deepest, full_role=True)
        else:
            label = "(wait)"
        if segs and segs[-1][0] == label:
            segs[-1] = (label, segs[-1][1] + (b - a))
        else:
            segs.append((label, b - a))
    return segs


def _dedupe(spans: Iterable[dict]) -> List[dict]:
    """Unique spans by span id (a linked span reached through several
    traces must be walked once, not once per trace)."""
    seen: Dict[str, dict] = {}
    for s in spans:
        seen.setdefault(s.get("span", id(s)), s)
    return list(seen.values())


def _upload_arrivals(trace_lists: List[Tuple[str, List[dict]]]
                     ) -> Dict[str, float]:
    """{client role: wall time its upload reached writer admission} for
    each upload-op trace of one round — writer serve-span start when
    present, else the client upload span's end."""
    arrivals: Dict[str, float] = {}
    for _tid, tspans in trace_lists:
        root = next((s for s in tspans
                     if s.get("name") == "client.upload_op"), None)
        if root is None:
            continue
        t = None
        for s in tspans:
            if s.get("name") == "serve" and s.get("method") == "upload" \
                    and role_class(s.get("role", "")) != "client":
                t = s["t0"] if t is None else min(t, s["t0"])
        if t is None:
            ups = [s["t1"] for s in tspans if s.get("name") == "upload"]
            t = min(ups) if ups else None
        if t is not None:
            prev = arrivals.get(root.get("role", "?"))
            arrivals[root.get("role", "?")] = (
                t if prev is None else min(prev, t))
    return arrivals


def round_reports(spans: Iterable[dict],
                  faults: Optional[List[dict]] = None) -> List[dict]:
    """Per-round reassembly: group traces by their root's `epoch` attr,
    compute the round interval, the critical path, the straggler ranking
    (upload arrival lag behind the round's first upload) and — when
    chaos fault events are supplied — which segment each fault landed
    in.  Returns reports sorted by epoch."""
    traces = assemble_traces(spans)
    by_epoch: Dict[int, List[Tuple[str, List[dict]]]] = {}
    for tid, tspans in traces.items():
        ep = None
        for s in sorted(tspans, key=lambda s: s["t0"]):
            if "epoch" in s:
                ep = s["epoch"]
                break
        if ep is None:
            continue
        try:
            by_epoch.setdefault(int(ep), []).append((tid, tspans))
        except (TypeError, ValueError):
            continue
    reports: List[dict] = []
    for ep in sorted(by_epoch):
        trace_lists = by_epoch[ep]
        allspans = _dedupe(s for _t, ts in trace_lists for s in ts)
        lo = min(s["t0"] for s in allspans)
        hi = max(s["t1"] for s in allspans)
        segs = critical_path(allspans, lo, hi)
        by_label: Dict[str, float] = {}
        for label, dur in segs:
            by_label[label] = by_label.get(label, 0.0) + dur
        wait = by_label.get("(wait)", 0.0)
        arrivals = _upload_arrivals(trace_lists)
        first = min(arrivals.values()) if arrivals else 0.0
        stragglers = sorted(((r, t - first)
                             for r, t in arrivals.items()),
                            key=lambda rt: -rt[1])
        fault_hits: List[dict] = []
        for f in faults or ():
            t = f.get("t")
            if not isinstance(t, (int, float)) or not lo <= t <= hi:
                continue
            active = [s for s in allspans if s["t0"] <= t < s["t1"]]
            label = (span_label(max(active, key=lambda s: s["t0"]),
                                full_role=True)
                     if active else "(wait)")
            fault_hits.append({"kind": f.get("kind"),
                               "target": f.get("target"),
                               "landed_in": label})
        reports.append({
            "epoch": ep, "t0": lo, "t1": hi,
            "wall_s": hi - lo,
            "segments": segs,
            "by_label": by_label,
            "covered_frac": (1.0 - wait / (hi - lo)) if hi > lo else 0.0,
            "traces": len(trace_lists),
            "stragglers": stragglers,
            "faults": fault_hits,
        })
    return reports


def _pctl(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    k = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[k]


def segment_stats(reports: List[dict]) -> Dict[str, dict]:
    """Across-round distribution per segment label (role-CLASS labels so
    20 clients aggregate into one row): total per round -> p50/p95."""
    per: Dict[str, List[float]] = {}
    for rep in reports:
        acc: Dict[str, float] = {}
        for label, dur in rep["segments"]:
            cls = label
            if ":" in label:
                role, name = label.split(":", 1)
                cls = f"{role_class(role)}:{name}"
            acc[cls] = acc.get(cls, 0.0) + dur
        for cls, tot in acc.items():
            per.setdefault(cls, []).append(tot)
    out: Dict[str, dict] = {}
    for cls, vals in per.items():
        vals.sort()
        out[cls] = {"rounds": len(vals),
                    "p50_s": _pctl(vals, 0.50),
                    "p95_s": _pctl(vals, 0.95),
                    "mean_s": sum(vals) / len(vals)}
    return out


def format_round_report(rep: dict, top: int = 6) -> str:
    """One round's report as the text block trace_report / fleet_top
    print."""
    wall = rep["wall_s"]
    lines = [f"round {rep['epoch']}: wall {wall:.3f}s  "
             f"traces {rep['traces']}  "
             f"attributed {rep['covered_frac']:.0%}"]
    ranked = sorted(rep["by_label"].items(), key=lambda kv: -kv[1])
    path = "  ".join(f"{label} {dur:.3f}s ({dur / wall:.0%})"
                     for label, dur in ranked[:top] if wall)
    lines.append(f"  critical path: {path}")
    if rep["stragglers"]:
        worst = ", ".join(f"{r} +{lag:.3f}s"
                          for r, lag in rep["stragglers"][:5])
        lines.append(f"  upload stragglers: {worst}")
    for f in rep["faults"]:
        lines.append(f"  fault {f['kind']} {f['target']} -> landed in "
                     f"{f['landed_in']}")
    return "\n".join(lines)
