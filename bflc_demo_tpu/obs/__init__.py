"""Fleet telemetry plane (PR 4): metrics registry + flight recorder +
collector.

Three pieces, importable independently (none imports jax or comm at
module load, so every role — including the lean validator children —
can afford them):

- `obs.metrics`  — process-wide Counter/Gauge/Histogram registry with
  bounded label cardinality; near-zero cost disabled; absorbs the
  utils.tracing.PROC cost categories into every snapshot.
- `obs.flight`   — bounded event ring flushed to a per-role file on a
  short cadence and on SIGTERM / unhandled exception / invariant
  violation, so chaos post-mortems have data from the DEAD process.
- `obs.collector`— FleetCollector: per-round whole-fleet scrapes
  (telemetry RPC for socket-serving roles, file snapshots for the
  rest) onto one metrics.jsonl timeline interleaved with chaos fault
  events; Prometheus text dumps.

Later PRs grew the plane to four layers on the same scrape spine:
`obs.trace` (causal spans), `obs.health` (model-quality verdicts), and
`obs.timeline` + `obs.slo` (the round-forensics joiner and burn-rate
SLO engine riding the FleetCollector's record stream).

`install_process_telemetry` is the one-call arming point every child
process entry uses (client/process_runtime), mirroring how chaos
injectors install.
"""

from __future__ import annotations

import threading
import time

from bflc_demo_tpu.obs import flight, metrics
from bflc_demo_tpu.obs.collector import FleetCollector  # noqa: F401

_PUBLISHER: "threading.Thread | None" = None


def install_process_telemetry(role: str, out_dir: str, *,
                              interval_s: float = 1.0,
                              enable_tracing: bool = True,
                              signals: bool = True,
                              trace_sample: float = 0.0) -> None:
    """Arm this process's telemetry: enable the metrics registry under
    `role`, flip the cost tracer on (the charge sites are shared), arm
    the flight recorder at <out_dir>/<role>.flight.jsonl, and start the
    snapshot publisher writing <out_dir>/<role>.metrics.json — the
    scrape surface for roles that serve no socket.  Idempotent.

    trace_sample > 0 additionally arms the causal span recorder
    (obs.trace) at <out_dir>/<role>.spans.jsonl with that head-sampling
    rate (BFLC_TRACE_LEGACY=1 pins it out regardless)."""
    global _PUBLISHER
    metrics.REGISTRY.enabled = True
    metrics.REGISTRY.role = role
    if enable_tracing:
        from bflc_demo_tpu.utils import tracing
        tracing.PROC.enabled = True
    flight.FLIGHT.install(role, out_dir, interval_s=interval_s,
                          signals=signals)
    # model-quality health plane (obs.health): point this process's
    # monitors at the telemetry dir for their <role>.health.jsonl
    # records (the plane itself arms off the metrics registry +
    # BFLC_HEALTH_LEGACY — installing the sink changes nothing when
    # it is pinned off)
    from bflc_demo_tpu.obs import health as _health
    _health.install(out_dir)
    # device plane (obs.device): point compile/memory records at
    # <role>.device.jsonl and register the terminal flusher with the
    # flight recorder's kill path (inert under BFLC_DEVICE_OBS=0)
    from bflc_demo_tpu.obs import device as _device
    _device.install(out_dir)
    if trace_sample > 0.0:
        from bflc_demo_tpu.obs import trace as obs_trace
        obs_trace.TRACE.install(role, out_dir, sample=trace_sample,
                                interval_s=interval_s)
    if _PUBLISHER is None:
        import os

        from bflc_demo_tpu.obs.collector import publish_snapshot
        path = os.path.join(out_dir, f"{role}.metrics.json")

        def _loop() -> None:
            while True:
                try:
                    # memory watermark gauges ride every snapshot the
                    # scrape loop reads (device stats / RSS fallback)
                    _device.sample_memory()
                except Exception:       # noqa: BLE001 — observability
                    pass
                publish_snapshot(path)
                time.sleep(interval_s)

        publish_snapshot(path)          # exists from role bring-up
        _PUBLISHER = threading.Thread(target=_loop, daemon=True)
        _PUBLISHER.start()
