"""Model-quality health plane: per-delta statistics, convergence
telemetry, streaming anomaly verdicts.

The fleet can explain where round time goes (obs.trace) and what each
role is doing (obs.metrics) but was blind to WHAT the federation is
learning: a sign-flipped or scaled Byzantine delta that survives
committee scoring was invisible until accuracy cratered.  This module
is the third observability pillar (Bonawitz 2019 treats population
analytics as a first-class subsystem of production FL — PAPERS.md):

- **per-delta statistics** — L2 norm, max-abs, NaN/Inf count, zero
  fraction, cosine against the previous round's aggregated delta
  direction, computed in ONE batched pass over the flattened rows the
  writer already stages at admission (meshagg.stats);
- **per-round convergence telemetry** — global update norm, model
  drift from the arming-time model, committee-score median/IQR/
  disagreement, the async drain's staleness distribution, and a
  per-client contribution ledger (admitted/selected counts, cumulative
  merge-weight share);
- **a streaming anomaly detector** — rolling median/MAD robust
  z-scores of each delta's L2 norm against the fleet's recent window,
  plus a sign-flip rule (negative cosine while the fleet's median
  cosine is positive) and an instant nonfinite rule, escalating to a
  WARN/CRIT round verdict emitted as metrics, flight events and one
  ``<role>.health.jsonl`` record per round (tools/health_report.py is
  the post-mortem renderer).

**The health plane changes no trust and no bytes.**  Verdicts never
gate admission, selection or aggregation; every statistic is computed
from decodes the writer already performed, AFTER the certified
arithmetic ran.  ``BFLC_HEALTH_LEGACY=1`` pins the plane off entirely;
committed model hashes are byte-identical either way (drilled in
tests/test_health.py), and a bug anywhere in this module is caught by
the caller and dropped — observability must never kill a commit.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bflc_demo_tpu.obs import flight as obs_flight
from bflc_demo_tpu.obs import metrics as obs_metrics

LEVELS = ("ok", "warn", "crit")

# --- health-plane telemetry (obs.metrics; no-ops unless the registry
# is enabled).  Round-scoped values are gauges set at verdict time (the
# scrape that follows is always current); distributions accumulate.
_G_VERDICT = obs_metrics.REGISTRY.gauge(
    "health_verdict",
    "last round's health verdict (0 ok / 1 warn / 2 crit)")
_C_VERDICTS = obs_metrics.REGISTRY.counter(
    "health_verdicts_total", "round health verdicts by level",
    ("level",))
_C_FLAGS = obs_metrics.REGISTRY.counter(
    "health_sender_flags_total",
    "per-delta anomaly flags by rule (sender detail rides the "
    "health.jsonl records — sender labels would blow the cardinality "
    "cap at fleet scale)", ("reason",))
_G_FLAGGED = obs_metrics.REGISTRY.gauge(
    "health_flagged_senders",
    "senders at warn-or-worse in the last round")
_G_UPDATE_NORM = obs_metrics.REGISTRY.gauge(
    "global_update_norm",
    "L2 norm of the last committed global model update")
_G_DRIFT = obs_metrics.REGISTRY.gauge(
    "model_drift",
    "L2 distance of the model from the health plane's arming-time "
    "reference")
_G_SCORE_MED = obs_metrics.REGISTRY.gauge(
    "committee_score_median", "median committee score, last round")
_G_SCORE_IQR = obs_metrics.REGISTRY.gauge(
    "committee_score_iqr",
    "IQR of per-candidate median committee scores, last round")
_G_SCORE_DIS = obs_metrics.REGISTRY.gauge(
    "committee_score_disagreement",
    "mean per-candidate spread (IQR) ACROSS committee members, last "
    "round — high = the committee cannot agree what a good delta is")
_M_DELTA_L2 = obs_metrics.REGISTRY.histogram(
    "delta_l2_norm", "per-delta L2 norm at aggregation",
    buckets=(1e-4, 1e-3, 1e-2, 0.1, 0.3, 1.0, 3.0, 10.0, 100.0,
             float("inf")))
_M_DELTA_COS = obs_metrics.REGISTRY.histogram(
    "delta_cos_prev",
    "per-delta cosine vs the previous round's aggregate direction",
    buckets=(-0.5, -0.25, 0.0, 0.25, 0.5, 0.75, 0.9, 1.0, float("inf")))
_M_COST = obs_metrics.REGISTRY.histogram(
    "health_seconds", "health-plane wall cost per round verdict")

#: per-process output sink (obs.install_process_telemetry arms it with
#: the telemetry dir): monitors append their round records to
#: <dir>/<role>.health.jsonl.  Unarmed -> metrics/flight only.
_SINK = {"dir": ""}


def install(out_dir: str) -> None:
    """Point every monitor in this process at `out_dir` for its
    ``<role>.health.jsonl`` records."""
    _SINK["dir"] = out_dir


def health_legacy() -> bool:
    """BFLC_HEALTH_LEGACY=1 pins the whole health plane off (the
    overhead benchmark's baseline switch)."""
    return bool(os.environ.get("BFLC_HEALTH_LEGACY"))


def health_armed() -> bool:
    """The ONE arming decision the instrumented aggregation paths ask:
    telemetry on and no legacy pin.  Dark fleets pay two attribute
    checks and skip even the row flattening."""
    return obs_metrics.REGISTRY.enabled and not health_legacy()


def _quantile(sorted_vals: np.ndarray, q: float) -> float:
    if len(sorted_vals) == 0:
        return 0.0
    return float(np.quantile(sorted_vals, q))


class HealthMonitor:
    """Streaming per-writer health state: rolling robust baselines,
    per-sender escalation streaks, the contribution ledger, and the
    round-record emitter.

    Thresholds: a delta is *crit-worthy* when its L2 robust z-score
    (|x - median| / max(1.4826 * MAD, rel_floor * median)) reaches
    ``crit_z``, or when its cosine against the previous aggregate
    direction is <= ``cos_flip`` while the round's median cosine is
    positive (the sign-flip signature; the default -0.75 clears the
    honest range — real small-batch SGD deltas measured down to -0.61
    against the previous aggregate while a true sign-flip sits at -1);
    *warn-worthy* at ``warn_z``.
    CRIT requires ``crit_streak`` CONSECUTIVE crit-worthy rounds for
    the same sender (a single outlier on a noisy fleet must not page),
    except NaN/Inf entries which are CRIT instantly — no honest f32
    delta contains them.  A streak survives short absences (async
    drains admit a sender only every few rounds) but EXPIRES after
    ``streak_gap`` monitor rounds without a trip — two isolated
    outliers hundreds of rounds apart must not page either.  z-scores
    only fire once the rolling window holds ``min_baseline``
    observations, so a cold start cannot produce false verdicts.

    ``density`` is the protocol's upload-delta density (1.0 = dense):
    sparse mode legitimately drives every honest delta's ``zero_frac``
    to ~``1 - density``, so the free-rider rule below warns past
    ``max(1 - density/2, 0.98)`` — strictly above what an honest
    top-k encoder can produce (k = ceil(density * size) nonzeros means
    zero_frac <= 1 - density < the ceiling), while an all-zero /
    dead-sender delta still trips.  The rule is ACTIVE ONLY in sparse
    mode (density < 1): dense fleets keep their pre-sparse behavior —
    no zero_frac judgement — because other encodings also produce
    exact zeros legitimately (i8 quantization zeroes every entry below
    half a scale step; ReLU models have structurally dead gradients)
    and a density-blind ceiling would cry wolf on honest fleets.  For
    the same reason, CALLERS feed density=1.0 (rule off) when
    quantization composes with sparsification (delta_dtype != 'f32'):
    an honest outlier-dominated sparse x i8 delta can dequantize its
    whole survivor set to exact zeros (every |v| < scale/2), which the
    f32-only ``zero_frac <= 1 - density`` bound does not cover — the
    writer wiring (comm.ledger_service / hier.aggregator) does this.
    Warn-worthy only (never crit on its own).
    """

    def __init__(self, role: str = "writer", *, window: int = 128,
                 min_baseline: int = 16, warn_z: float = 4.0,
                 crit_z: float = 8.0, rel_floor: float = 0.05,
                 cos_flip: float = -0.75, crit_streak: int = 2,
                 streak_gap: int = 8, density: float = 1.0,
                 per_leaf: Optional[bool] = None,
                 leaf_top: int = 3,
                 jsonl_path: Optional[str] = None,
                 keep_records: int = 512):
        self.role = role
        # per-leaf WHERE refinement (meshagg.stats.per_leaf_stats):
        # opt-in (BFLC_HEALTH_PER_LEAF=1 or per_leaf=True) because the
        # extra O(N x P) pass only pays off when someone is triaging —
        # and computed ONLY on rounds that flagged a sender, so even
        # armed it costs nothing on a healthy fleet
        self.per_leaf = (bool(os.environ.get("BFLC_HEALTH_PER_LEAF"))
                         if per_leaf is None else bool(per_leaf))
        self.leaf_top = int(leaf_top)
        self.density = float(density)
        self._zf_ceiling = max(1.0 - self.density / 2.0, 0.98)
        self.window = int(window)
        self.min_baseline = int(min_baseline)
        self.warn_z = float(warn_z)
        self.crit_z = float(crit_z)
        self.rel_floor = float(rel_floor)
        self.cos_flip = float(cos_flip)
        self.crit_streak = int(crit_streak)
        self.streak_gap = int(streak_gap)
        self._jsonl_path = jsonl_path
        self._l2_window: deque = deque(maxlen=self.window)
        # sender -> (consecutive crit-worthy trips, monitor round of
        # the last trip) — the round anchor expires stale streaks
        self._streak: Dict[str, Tuple[int, int]] = {}
        self._ref_row: Optional[np.ndarray] = None
        self._base_row: Optional[np.ndarray] = None
        self.contribution: Dict[str, Dict[str, float]] = {}
        self.records: deque = deque(maxlen=keep_records)
        self.rounds = 0

    # ----------------------------------------------------------- helpers
    def _baseline(self) -> Optional[Tuple[float, float]]:
        """(median, robust scale) of the rolling L2 window — computed
        ONCE per round (the window only changes between rounds), or
        None below min_baseline (cold start never judges)."""
        if len(self._l2_window) < self.min_baseline:
            return None
        arr = np.asarray(self._l2_window, np.float64)
        med = float(np.median(arr))
        mad = float(np.median(np.abs(arr - med)))
        return med, max(1.4826 * mad, self.rel_floor * abs(med), 1e-12)

    def _path(self) -> str:
        if self._jsonl_path is not None:
            return self._jsonl_path
        d = _SINK["dir"]
        return os.path.join(d, f"{self.role}.health.jsonl") if d else ""

    @staticmethod
    def _score_stats(medians, candidate_scores):
        """(median, iqr, disagreement) of the committee outcome:
        median/IQR over the per-candidate medians, disagreement = mean
        per-candidate IQR ACROSS committee members.  The async path
        passes no medians — they re-derive from the score rows."""
        med = iqr = dis = 0.0
        if (medians is None or not len(medians)) and candidate_scores:
            medians = [float(np.median(np.asarray(list(r), np.float64)))
                       if len(list(r)) else 0.0
                       for r in candidate_scores]
        if medians is not None and len(medians):
            m = np.sort(np.asarray(medians, np.float64))
            med = float(np.median(m))
            iqr = _quantile(m, 0.75) - _quantile(m, 0.25)
        if candidate_scores:
            rows = [np.asarray(list(r), np.float64)
                    for r in candidate_scores]
            lens = {len(r) for r in rows}
            if lens == {len(rows[0])} and len(rows[0]) >= 2:
                # rectangular (every candidate scored by the same
                # committee count, the common case): one vectorized
                # quantile pass instead of a per-candidate loop
                m = np.stack(rows)
                q75, q25 = np.quantile(m, (0.75, 0.25), axis=1)
                dis = float(np.mean(q75 - q25))
            else:
                spreads = [
                    _quantile(np.sort(r), 0.75)
                    - _quantile(np.sort(r), 0.25)
                    for r in rows if len(r) >= 2]
                if spreads:
                    dis = float(np.mean(spreads))
        return med, iqr, dis

    # -------------------------------------------------------------- round
    def on_round(self, *, epoch: int, senders: Sequence[str],
                 rows: Sequence[np.ndarray], weights: Sequence[float],
                 selected: Sequence[int],
                 medians=None,
                 candidate_scores: Optional[List[Sequence[float]]] = None,
                 staleness: Optional[Sequence[int]] = None,
                 old_row: Optional[np.ndarray] = None,
                 new_row: Optional[np.ndarray] = None,
                 leaf_layout=None,
                 mode: str = "sync") -> Dict[str, Any]:
        """Ingest one committed round and return its health record.

        `rows` are the admitted deltas' flattened float32 rows (engine
        staging images) aligned with `senders`/`weights`; `selected`
        indexes the merged subset; `old_row`/`new_row` are the global
        model before/after (omitted at the cell tier, where the
        "update" is the partial itself); `leaf_layout` is the row's
        ``[(key, offset, size, ...)]`` leaf map (engine._leaf_layout) —
        with the per-leaf mode armed, any FLAGGED sender's record then
        carries its ``leaf_top`` worst-offending leaves (the ROADMAP
        "WHERE a model diverges" refinement).  Never raises past
        numeric work the caller already survived — callers still wrap
        it."""
        from bflc_demo_tpu.meshagg.stats import (batch_delta_stats,
                                                 weighted_mean_row)
        t0 = time.perf_counter()
        self.rounds += 1
        mat = (np.stack([np.asarray(r, np.float32) for r in rows])
               if len(rows) else np.zeros((0, 0), np.float32))
        ref = self._ref_row
        if ref is not None and (mat.ndim != 2
                                or ref.shape[0] != mat.shape[1]):
            ref = None                      # schema changed: re-anchor
        stats = batch_delta_stats(mat, ref)
        agg_row = weighted_mean_row(mat, list(weights), list(selected)) \
            if len(rows) else np.zeros(0)

        # convergence telemetry
        if old_row is not None and new_row is not None:
            upd = (np.asarray(new_row, np.float64)
                   - np.asarray(old_row, np.float64))
            update_norm = float(np.sqrt(np.nansum(upd * upd)))
            if self._base_row is None \
                    or self._base_row.shape != np.asarray(new_row).shape:
                self._base_row = np.asarray(old_row, np.float64).copy()
            dv = np.asarray(new_row, np.float64) - self._base_row
            drift = float(np.sqrt(np.nansum(dv * dv)))
            update_nonfinite = int(
                (~np.isfinite(np.asarray(new_row))).sum())
        else:
            update_norm = float(np.sqrt(np.nansum(agg_row * agg_row)))
            drift = 0.0
            update_nonfinite = 0
        score_med, score_iqr, score_dis = self._score_stats(
            medians, candidate_scores)

        # streaming anomaly detection (per sender)
        cos_med = (float(np.median(stats["cos_ref"]))
                   if ref is not None and len(rows) else 0.0)
        baseline = self._baseline()
        sender_recs: List[Dict[str, Any]] = []
        sel = {int(s) for s in selected}
        wtot = float(sum(float(weights[i]) for i in sel)) or 1.0
        worst = 0
        flagged = 0
        for i, sender in enumerate(senders):
            l2 = float(stats["l2"][i])
            cos = float(stats["cos_ref"][i])
            nf = int(stats["nonfinite"][i])
            reasons: List[str] = []
            crit_worthy = False
            level = 0
            if nf > 0:
                # instant CRIT — and crit-worthy, so it EXTENDS an
                # in-progress streak instead of resetting it (review:
                # an attacker interleaving NaN rounds must not get its
                # l2_z streak erased by the clean-appearance branch)
                reasons.append("nonfinite")
                level = 2
                crit_worthy = True
            z = ((l2 - baseline[0]) / baseline[1]
                 if baseline is not None else None)
            if z is not None and abs(z) >= self.crit_z:
                reasons.append("l2_z")
                crit_worthy = True
            elif z is not None and abs(z) >= self.warn_z:
                reasons.append("l2_warn")
            if ref is not None and cos <= self.cos_flip \
                    and cos_med >= 0.1:
                reasons.append("cos_flip")
                crit_worthy = True
            if self.density < 1.0 and \
                    float(stats["zero_frac"][i]) > self._zf_ceiling:
                # free-rider / dead delta: more zeros than an honest
                # top-k encoder at this protocol density can produce
                # (class docstring; sparse mode only) — warn-worthy,
                # never crit alone
                reasons.append("zero_frac")
            if crit_worthy:
                prev, last = self._streak.get(sender, (0, -10 ** 9))
                streak = (prev + 1 if self.rounds - last
                          <= self.streak_gap else 1)
                self._streak[sender] = (streak, self.rounds)
                level = max(level, 2 if streak >= self.crit_streak
                            else 1)
            else:
                self._streak.pop(sender, None)
                if reasons and level < 1:
                    level = 1
            if reasons:
                flagged += 1
                for r in reasons:
                    _C_FLAGS.inc(reason=r)
            worst = max(worst, level)
            _M_DELTA_L2.observe(l2)
            if ref is not None:
                _M_DELTA_COS.observe(cos)
            c = self.contribution.setdefault(
                sender, {"admitted": 0, "selected": 0,
                         "weight_share": 0.0})
            c["admitted"] += 1
            if i in sel:
                c["selected"] += 1
                c["weight_share"] += float(weights[i]) / wtot
            sender_recs.append({
                "sender": sender, "l2": round(l2, 6),
                "max_abs": round(float(stats["max_abs"][i]), 6),
                "zero_frac": round(float(stats["zero_frac"][i]), 4),
                "cos": round(cos, 4) if ref is not None else None,
                "nonfinite": nf,
                "z": round(z, 2) if z is not None else None,
                "level": LEVELS[level], "reasons": reasons,
                "selected": i in sel,
                "w_share": (round(float(weights[i]) / wtot, 4)
                            if i in sel else 0.0)})
        if update_nonfinite:
            worst = 2
        if self.per_leaf and leaf_layout is not None and len(rows) \
                and any(r["reasons"] for r in sender_recs):
            # the WHERE refinement, lazily: one per-leaf pass only on
            # rounds that flagged someone.  Leaves ranked by the
            # sender's leaf L2 over the round's MEDIAN for that leaf —
            # a scaled or flipped layer stands out against its own
            # fleet baseline, not against other layers' magnitudes.
            try:
                from bflc_demo_tpu.meshagg.stats import per_leaf_stats
                leaf = per_leaf_stats(mat, leaf_layout, ref)
                med = {k: float(np.median(v["l2"]))
                       for k, v in leaf.items()}
                for i, srec in enumerate(sender_recs):
                    if not srec["reasons"]:
                        continue
                    ranked = sorted(
                        ((k, float(v["l2"][i]), med[k],
                          float(v["cos"][i]))
                         for k, v in leaf.items()),
                        key=lambda e: -(e[1] / (e[2] + 1e-12)))
                    srec["leaves"] = [
                        {"key": k, "l2": round(l2, 6),
                         "l2_med": round(m, 6),
                         "ratio": round(l2 / (m + 1e-12), 2),
                         "cos": (round(c, 4) if ref is not None
                                 else None)}
                        for k, l2, m, c in ranked[:self.leaf_top]]
            except Exception:   # noqa: BLE001 — observability only:
                pass            # the flat verdict already stands
        # baselines update AFTER judging the round (a huge outlier
        # joins the window, where the median/MAD absorb it)
        for i in range(len(senders)):
            self._l2_window.append(float(stats["l2"][i]))
        self._ref_row = (np.asarray(agg_row, np.float32)
                         if len(rows) else self._ref_row)

        record: Dict[str, Any] = {
            "type": "health_round", "t": time.time(),
            "role": self.role, "mode": mode, "epoch": int(epoch),
            "verdict": LEVELS[worst], "n": len(senders),
            "n_selected": len(sel), "flagged": flagged,
            "update_norm": round(update_norm, 6),
            "model_drift": round(drift, 6),
            "update_nonfinite": update_nonfinite,
            "score_median": round(score_med, 4),
            "score_iqr": round(score_iqr, 4),
            "score_disagreement": round(score_dis, 4),
            "senders": sender_recs,
        }
        if staleness is not None:
            s = [int(x) for x in staleness]
            record["staleness"] = {
                "min": min(s, default=0), "max": max(s, default=0),
                "mean": round(float(np.mean(s)) if s else 0.0, 2)}
        self.records.append(record)

        # emit: metrics + flight + health.jsonl
        _G_VERDICT.set(worst)
        _C_VERDICTS.inc(level=LEVELS[worst])
        _G_FLAGGED.set(flagged)
        _G_UPDATE_NORM.set(update_norm)
        _G_DRIFT.set(drift)
        _G_SCORE_MED.set(score_med)
        _G_SCORE_IQR.set(score_iqr)
        _G_SCORE_DIS.set(score_dis)
        obs_flight.FLIGHT.record(
            "event", "health_round", epoch=int(epoch), mode=mode,
            verdict=LEVELS[worst], flagged=flagged,
            update_norm=round(update_norm, 6),
            flagged_senders=[r["sender"] for r in sender_recs
                             if r["level"] != "ok"])
        if worst >= 2:
            # a CRIT verdict is exactly the moment a post-mortem wants
            # on disk even if the process dies next — flush now
            obs_flight.FLIGHT.flush("health_crit")
        path = self._path()
        if path:
            try:
                with open(path, "a") as fh:
                    fh.write(json.dumps(record) + "\n")
            except OSError:
                pass
        _M_COST.observe(time.perf_counter() - t0)
        return record

    # ------------------------------------------------------------- report
    def report(self) -> Dict[str, Any]:
        """Aggregate view over every retained round record — the same
        shape tools/health_report.py builds offline from the jsonl."""
        return summarize_records(list(self.records),
                                 contribution=self.contribution)


def load_health_records(path: str) -> List[Dict[str, Any]]:
    """Every parseable health_round record under `path` (a dir is
    globbed for *.health.jsonl; torn trailing lines are skipped — the
    stream is append-only and a kill can tear the last line).  The ONE
    loader tools/health_report.py, tools/chaos_soak.py's --fail-on-crit
    gate and the forensics joiner's tests share."""
    files = []
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            if name.endswith(".health.jsonl"):
                files.append(os.path.join(path, name))
    else:
        files = [path]
    records: List[Dict[str, Any]] = []
    for fp in files:
        try:
            with open(fp) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue            # torn tail line
                    if isinstance(rec, dict) \
                            and rec.get("type") == "health_round":
                        rec.setdefault("role",
                                       os.path.basename(fp).split(
                                           ".health.jsonl")[0])
                        records.append(rec)
        except OSError:
            continue
    records.sort(key=lambda r: (r.get("t", 0.0), r.get("epoch", 0)))
    return records


def summarize_records(records: List[Dict[str, Any]], *,
                      contribution: Optional[Dict] = None
                      ) -> Dict[str, Any]:
    """{verdicts, flagged_senders ranking, per-round table rows} from
    health_round records (live monitor or parsed jsonl)."""
    verdicts = {lv: 0 for lv in LEVELS}
    flagged: Dict[str, Dict[str, Any]] = {}
    contrib: Dict[str, Dict[str, float]] = \
        {k: dict(v) for k, v in (contribution or {}).items()}
    rows = []
    for rec in records:
        if rec.get("type") != "health_round":
            continue
        verdicts[rec.get("verdict", "ok")] = \
            verdicts.get(rec.get("verdict", "ok"), 0) + 1
        rows.append({k: rec.get(k) for k in
                     ("epoch", "mode", "verdict", "n", "flagged",
                      "update_norm", "model_drift", "score_median",
                      "score_iqr", "score_disagreement", "staleness")})
        for s in rec.get("senders", []):
            if contribution is None:
                c = contrib.setdefault(
                    s["sender"], {"admitted": 0, "selected": 0,
                                  "weight_share": 0.0})
                c["admitted"] += 1
                if s.get("selected"):
                    c["selected"] += 1
                    c["weight_share"] += float(s.get("w_share", 0.0))
            if s.get("level", "ok") == "ok":
                continue
            f = flagged.setdefault(
                s["sender"], {"warn": 0, "crit": 0, "max_abs_z": 0.0,
                              "reasons": []})
            f[s["level"]] += 1
            if s.get("z") is not None:
                f["max_abs_z"] = max(f["max_abs_z"], abs(s["z"]))
            for r in s.get("reasons", []):
                if r not in f["reasons"]:
                    f["reasons"].append(r)
    ranking = sorted(
        flagged.items(),
        key=lambda kv: (-kv[1]["crit"], -kv[1]["warn"],
                        -kv[1]["max_abs_z"]))
    return {"rounds": len(rows), "verdicts": verdicts,
            "flagged_senders": [{"sender": k, **v} for k, v in ranking],
            "contribution": contrib, "round_rows": rows}
