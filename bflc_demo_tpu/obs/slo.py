"""Declarative SLO engine: objectives as data, streaming multi-window
burn-rate alerts that carry their own evidence.

The alerting half of the fourth observability layer (obs.timeline is
the forensics half).  An operator states objectives as ``SLOSpec``
records — *round latency p95 under X*, *certify latency under Y*,
*async staleness bounded*, *scrape coverage over Z*, *a CRIT-verdict
budget*, *accuracy must not regress* — and the engine judges each
committed round's joined signal summary (obs.timeline.slo_summary)
against every objective, streaming:

- **breach** — one round outside its objective (counted, never paged
  alone: noise budget is the whole point of an SLO);
- **burn rate** — breach fraction over a rolling window divided by the
  objective's budget (burn 1.0 = exactly spending the allowance);
- **alert** — Google-SRE-style multi-window rule: page only when BOTH
  the fast window (default 5 rounds, catches onset quickly) and the
  slow window (default 25 rounds, confirms it is sustained) burn over
  their thresholds.  One alert per excursion: the alert latches until
  the fast window cools below burn 1.0, so a sustained breach pages
  once, not every round.

Every alert is emitted three ways so the page carries its own evidence:
a metric (``slo_alerts_total{slo=...}``), a flight event (flushed
immediately — the alert survives a SIGKILL), and one record in
``alerts.jsonl`` embedding the correlated round context (the joined
round record: wall, health verdict, faults, critical path when traced).
``alerts.jsonl`` is rewritten tmp-then-rename on every alert — like the
flight recorder, a kill mid-write can never tear it (drilled in
tests/test_forensics.py).

**The SLO plane changes no trust and no bytes** (PARITY.md): it runs
driver-side off scrape artifacts, gates nothing in the protocol, and
``BFLC_SLO_LEGACY=1`` pins it off entirely — committed model hashes are
byte-identical either way.  Operator tooling (tools/chaos_soak.py
``--fail-on-slo`` / ``--fail-on-crit``) turns verdicts into exit codes
OUTSIDE the protocol, which is exactly where enforcement belongs until
validators re-derive the signals themselves (ROADMAP).
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional

from bflc_demo_tpu.obs import flight as obs_flight
from bflc_demo_tpu.obs import metrics as obs_metrics

_C_BREACH = obs_metrics.REGISTRY.counter(
    "slo_breaches_total", "rounds outside an SLO's objective", ("slo",))
_C_ALERTS = obs_metrics.REGISTRY.counter(
    "slo_alerts_total", "multi-window burn-rate pages", ("slo",))
_G_BURN_FAST = obs_metrics.REGISTRY.gauge(
    "slo_burn_rate_fast", "fast-window burn rate, last judged round",
    ("slo",))
_G_BURN_SLOW = obs_metrics.REGISTRY.gauge(
    "slo_burn_rate_slow", "slow-window burn rate, last judged round",
    ("slo",))
_G_ALERT_ACTIVE = obs_metrics.REGISTRY.gauge(
    "slo_alert_active", "1 while an alert excursion is latched",
    ("slo",))
_C_NOTIFY = obs_metrics.REGISTRY.counter(
    "slo_notify_total",
    "operator notify commands spawned per alert, by outcome",
    ("result",))


def slo_legacy() -> bool:
    """BFLC_SLO_LEGACY=1 pins the whole SLO/forensics plane off (the
    overhead benchmark's baseline switch)."""
    return bool(os.environ.get("BFLC_SLO_LEGACY"))


def slo_armed() -> bool:
    """The one arming decision the driver wiring asks: telemetry on and
    no legacy pin (same shape as obs.health.health_armed)."""
    return obs_metrics.REGISTRY.enabled and not slo_legacy()


@dataclass(frozen=True)
class SLOSpec:
    """One objective as data.

    ``signal`` names a key in the joined round summary
    (obs.timeline.RoundTimeline.slo_summary); ``op`` states the GOOD
    condition (``"<="``: value <= bound is healthy, ``">="``: value >=
    bound is healthy); ``budget`` is the tolerated breach fraction
    (0.1 = one round in ten may breach before burn reaches 1.0).
    A round whose signal is None is SKIPPED — absence of data is a
    coverage problem (its own SLO), never a breach of this one.

    ``warmup > 0`` arms ADAPTIVE baselining: the first ``warmup``
    observed samples are collected (not judged) and the effective bound
    is learned from that healthy history as a robust envelope —
    ``median + adapt_mult * max(MAD, adapt_floor)`` for ``"<="``
    objectives (mirrored for ``">="``) — then clamped to never be more
    LAX than the static ``bound`` (the static bound stays the outer
    guard-rail; adaptation only tightens toward what this deployment
    actually delivers).  Median/MAD, not mean/stddev: one straggler
    round in the warmup must not inflate the baseline it anchors."""
    name: str
    signal: str
    bound: float
    op: str = "<="                      # "<=" or ">="
    budget: float = 0.1
    fast_window: int = 5
    slow_window: int = 25
    # adaptive baselining (0 = static bound)
    warmup: int = 0
    adapt_mult: float = 4.0
    adapt_floor: float = 0.0
    # page when fast >= burn_fast AND slow >= burn_slow.  Windows
    # younger than their configured length are PADDED with healthy
    # history (the denominator is the configured window), so the
    # absolute breach count needed to page is uniform across a run —
    # round 2 is judged exactly like round 200.  At the default budget
    # 0.1 one isolated breach never pages (1/5 / 0.1 = burn 2 < 3)
    # while two consecutive breaches do (2/5 / 0.1 = 4 >= 3, slow
    # window confirming at 2/25 / 0.1 = 0.8 >= 0.6) — "within 2
    # rounds of onset" by design.
    burn_fast: float = 3.0
    burn_slow: float = 0.6
    description: str = ""

    def healthy(self, value: float, bound: Optional[float] = None) -> bool:
        b = self.bound if bound is None else bound
        return (value <= b if self.op == "<=" else value >= b)

    def learn_bound(self, samples: List[float]) -> float:
        """The adaptive-envelope rule (class docstring): robust center +
        scaled robust spread, clamped by the static bound so a slow
        warmup can only tighten, never loosen, the objective."""
        med = _median(samples)
        mad = _median([abs(v - med) for v in samples])
        spread = self.adapt_mult * max(mad, self.adapt_floor)
        if self.op == "<=":
            return min(self.bound, med + spread)
        return max(self.bound, med - spread)


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


def burn_rate(breaches: int, window: int, budget: float) -> float:
    """The ONE burn-rate rule every window shares: breach fraction over
    the window divided by the budget.  The engine passes the CONFIGURED
    window length even while the observed history is shorter (young
    windows are padded with healthy rounds), so onset sensitivity is
    uniform across a run — a lone breach in round 2 must not page just
    because the denominator was small."""
    if window <= 0 or budget <= 0:
        return 0.0
    return (breaches / window) / budget


@dataclass
class _SLOState:
    spec: SLOSpec
    fast: Deque[int] = field(default_factory=deque)
    slow: Deque[int] = field(default_factory=deque)
    breaches: int = 0
    judged: int = 0
    alerts: int = 0
    active: bool = False
    last_fast_burn: float = 0.0
    last_slow_burn: float = 0.0
    # adaptive baselining (SLOSpec.warmup): healthy-history samples
    # collected during warmup, then the learned effective bound
    baseline: List[float] = field(default_factory=list)
    learned_bound: Optional[float] = None

    def bound(self) -> float:
        return (self.learned_bound if self.learned_bound is not None
                else self.spec.bound)


def adaptive_warmup() -> int:
    """BFLC_SLO_ADAPTIVE=W arms adaptive baselining: wall-clock-shaped
    objectives learn their bound from the run's own first W healthy
    rounds instead of the deployment-agnostic static default (0 = off).
    Malformed values read as off — a typo must not change judging."""
    try:
        return max(int(os.environ.get("BFLC_SLO_ADAPTIVE", "0")), 0)
    except ValueError:
        return 0


def default_slos(*, round_latency_s: float = 30.0,
                 certify_latency_s: float = 5.0,
                 max_staleness: float = 8.0,
                 scrape_coverage: float = 0.9,
                 acc_regression: float = 0.05,
                 warmup: Optional[int] = None) -> List[SLOSpec]:
    """The standing fleet objectives.  Bounds are deployment knobs —
    the process runtime scales round_latency off its own timeout and
    staleness off the protocol genome; these defaults suit config-1
    geometry on a shared host.  ``warmup`` (default: BFLC_SLO_ADAPTIVE)
    arms adaptive baselining on the wall-clock objectives — round and
    certify latency, whose absolute bounds are host-dependent; the
    protocol-genome and fraction objectives stay static (their bounds
    are principled, not environmental)."""
    w = adaptive_warmup() if warmup is None else max(int(warmup), 0)
    return [
        SLOSpec("round_latency", "round_wall_s", round_latency_s,
                warmup=w, adapt_floor=0.25,
                description="commit-to-commit round wall time"),
        SLOSpec("certify_latency", "certify_p95_s", certify_latency_s,
                warmup=w, adapt_floor=0.05,
                description="per-round p95 BFT certification latency "
                            "(cumulative-histogram delta)"),
        SLOSpec("async_staleness", "staleness_p95", max_staleness,
                description="per-round p95 admitted async staleness "
                            "(epochs); only fires on async fleets"),
        SLOSpec("scrape_coverage", "scrape_coverage", scrape_coverage,
                op=">=",
                description="fraction of roles answering the round's "
                            "fleet scrape"),
        SLOSpec("health_budget", "health_verdict", 1.0, budget=0.05,
                description="model-quality verdict budget: CRIT rounds "
                            "are the breach (obs.health)"),
        SLOSpec("accuracy_progress", "acc_drop_from_best",
                acc_regression,
                description="committed accuracy must stay within "
                            "acc_regression of the best seen"),
        # validator re-derivation coverage (ledger.rederive): a skipped
        # re-derivation means a commit was certified WITHOUT its model
        # hash being reproduced — tolerable as a rare cache race, a
        # sustained burn is a coverage hole in the trust plane.  Only
        # fires on fleets whose scrapes carry the validator counter.
        SLOSpec("rederive_skip", "rederive_skipped_delta", 0.0,
                budget=0.05,
                description="validator re-derivations skipped this "
                            "round (fleet-wide counter delta)"),
        # device plane (obs.device): post-warmup steady state is ZERO
        # fresh XLA compiles per round — any delta is a breach, and a
        # sustained burn is a recompile storm (async round-geometry
        # churn is the live risk).  The timeline skips the signal
        # (None) for the first rounds, so legitimate warmup compiles
        # are never judged.  Only fires on fleets whose scrapes carry
        # the device counters.
        SLOSpec("device_recompiles", "device_recompiles_delta", 0.0,
                budget=0.05,
                description="fleet-wide fresh XLA compile events this "
                            "round, post-warmup (device plane)"),
        # memory-ceiling objective: peak watermark as a fraction of the
        # device's reported bytes_limit (TPU) or the operator ceiling
        # BFLC_DEVICE_MEM_CEILING_BYTES; fleets with no known ceiling
        # report None and SKIP.
        SLOSpec("device_mem_ceiling", "device_mem_frac", 0.9,
                budget=0.05,
                description="worst role peak memory / capacity "
                            "(device plane watermark)"),
    ]


class SLOEngine:
    """Streaming evaluator: feed each round's signal summary, collect
    alerts.  ``jsonl_path`` arms the durable alerts.jsonl artifact
    (rewritten atomically per alert)."""

    def __init__(self, slos: Optional[List[SLOSpec]] = None, *,
                 jsonl_path: str = "", keep_alerts: int = 256,
                 notify_cmd: Optional[str] = None):
        self.slos = list(slos if slos is not None else default_slos())
        self.jsonl_path = jsonl_path
        self._state = {s.name: _SLOState(s) for s in self.slos}
        self.alerts: List[dict] = []
        self.keep_alerts = int(keep_alerts)
        self.rounds = 0
        # alert routing beyond file/exit-code (--notify-cmd /
        # BFLC_SLO_NOTIFY_CMD): one operator command spawned PER ALERT
        # with the alerts.jsonl record on stdin — the hook a pager /
        # webhook bridge hangs off.  Failure-isolated: a broken command
        # is counted (`slo_notify_total{result=...}`), never raised —
        # alerting must not be able to kill the judge.
        self.notify_cmd = (notify_cmd if notify_cmd is not None
                          else os.environ.get("BFLC_SLO_NOTIFY_CMD", ""))
        self.notified = 0
        self.notify_failures = 0

    # ------------------------------------------------------------- judge
    def observe_round(self, summary: Dict[str, Any],
                      context: Optional[Dict[str, Any]] = None
                      ) -> List[dict]:
        """Judge one round's joined summary against every objective;
        returns the alerts this round raised (usually none).  `context`
        is the full joined round record embedded into each alert so the
        page carries its own evidence."""
        self.rounds += 1
        epoch = summary.get("epoch")
        raised: List[dict] = []
        for st in self._state.values():
            spec = st.spec
            value = summary.get(spec.signal)
            if value is None:
                continue                    # no data != breach
            if spec.warmup > 0 and st.learned_bound is None:
                # adaptive warmup: collect, don't judge — these rounds
                # ARE the healthy history the bound is learned from
                st.baseline.append(float(value))
                if len(st.baseline) >= spec.warmup:
                    st.learned_bound = spec.learn_bound(st.baseline)
                    obs_flight.FLIGHT.record(
                        "event", "slo_baseline_learned", slo=spec.name,
                        epoch=epoch, samples=len(st.baseline),
                        bound=round(st.learned_bound, 6),
                        static_bound=spec.bound)
                continue
            breached = not spec.healthy(float(value), st.bound())
            st.judged += 1
            st.fast.append(1 if breached else 0)
            st.slow.append(1 if breached else 0)
            while len(st.fast) > spec.fast_window:
                st.fast.popleft()
            while len(st.slow) > spec.slow_window:
                st.slow.popleft()
            fast = burn_rate(sum(st.fast),
                             max(len(st.fast), spec.fast_window),
                             spec.budget)
            slow = burn_rate(sum(st.slow),
                             max(len(st.slow), spec.slow_window),
                             spec.budget)
            st.last_fast_burn, st.last_slow_burn = fast, slow
            _G_BURN_FAST.set(fast, slo=spec.name)
            _G_BURN_SLOW.set(slow, slo=spec.name)
            if breached:
                st.breaches += 1
                _C_BREACH.inc(slo=spec.name)
            if st.active and fast < 1.0:
                st.active = False           # excursion over: un-latch
                _G_ALERT_ACTIVE.set(0, slo=spec.name)
            if not st.active and fast >= spec.burn_fast \
                    and slow >= spec.burn_slow:
                st.active = True
                st.alerts += 1
                raised.append(self._raise(spec, epoch, float(value),
                                          fast, slow, summary, context))
        return raised

    def _raise(self, spec: SLOSpec, epoch, value: float, fast: float,
               slow: float, summary: Dict[str, Any],
               context: Optional[Dict[str, Any]]) -> dict:
        st = self._state[spec.name]
        alert = {
            "type": "slo_alert", "t": time.time(), "slo": spec.name,
            "epoch": epoch, "signal": spec.signal,
            "value": round(value, 6), "bound": st.bound(),
            "op": spec.op, "budget": spec.budget,
            "burn_fast": round(fast, 3), "burn_slow": round(slow, 3),
            "windows": {"fast": list(st.fast), "slow_breaches":
                        sum(st.slow), "slow_len": len(st.slow)},
            "summary": dict(summary),
        }
        if context is not None:
            alert["context"] = context
        self.alerts.append(alert)
        if len(self.alerts) > self.keep_alerts:
            del self.alerts[0]
        _C_ALERTS.inc(slo=spec.name)
        _G_ALERT_ACTIVE.set(1, slo=spec.name)
        # the page is exactly the moment a post-mortem wants the ring on
        # disk even if the driver dies next — record AND flush
        obs_flight.FLIGHT.record(
            "event", "slo_alert", slo=spec.name, epoch=epoch,
            value=round(value, 6), bound=st.bound(),
            burn_fast=round(fast, 3), burn_slow=round(slow, 3))
        obs_flight.FLIGHT.flush("slo_alert")
        self._write_alerts()
        self._notify(alert)
        return alert

    def _notify(self, alert: dict) -> None:
        """Spawn the operator's notify command with the alert record on
        stdin (one JSON line — the exact alerts.jsonl shape).  The
        child runs detached through a shell so operators can write
        `--notify-cmd 'curl -s -d @- https://pager/...'` one-liners;
        feeding stdin happens on a reaper thread so a slow or wedged
        pager can never block the judging path."""
        if not self.notify_cmd:
            return
        import subprocess
        import threading
        try:
            proc = subprocess.Popen(
                self.notify_cmd, shell=True,
                stdin=subprocess.PIPE,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        except (OSError, ValueError):
            self.notify_failures += 1
            _C_NOTIFY.inc(result="spawn_error")
            return
        payload = (json.dumps(alert) + "\n").encode()

        def _feed():
            ok = False
            try:
                proc.communicate(payload, timeout=30.0)
                ok = proc.returncode == 0
            except Exception:       # noqa: BLE001 — failure-isolated
                try:
                    proc.kill()
                    # reap the killed child, or an alert storm against
                    # a hung pager accumulates one zombie per page
                    proc.communicate()
                except (OSError, ValueError):
                    pass
            if ok:
                _C_NOTIFY.inc(result="ok")
            else:
                self.notify_failures += 1
                _C_NOTIFY.inc(result="failed")

        self.notified += 1
        threading.Thread(target=_feed, daemon=True).start()

    def _write_alerts(self) -> None:
        """Persist every retained alert atomically (tmp-then-rename,
        the flight recorder's durability rule: a SIGKILL mid-write
        leaves the previous complete file, never a torn one)."""
        if not self.jsonl_path:
            return
        tmp = f"{self.jsonl_path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                for a in self.alerts:
                    fh.write(json.dumps(a) + "\n")
            os.replace(tmp, self.jsonl_path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass

    # ------------------------------------------------------------ report
    def report(self) -> Dict[str, Any]:
        return {
            "rounds_judged": self.rounds,
            "alerts": len(self.alerts),
            "notified": self.notified,
            "notify_failures": self.notify_failures,
            "slos": {
                name: {"judged": st.judged, "breaches": st.breaches,
                       "alerts": st.alerts, "active": st.active,
                       "burn_fast": round(st.last_fast_burn, 3),
                       "burn_slow": round(st.last_slow_burn, 3),
                       **({"learned_bound":
                           round(st.learned_bound, 6)
                           if st.learned_bound is not None else None,
                           "warmup_collected": len(st.baseline)}
                          if st.spec.warmup > 0 else {})}
                for name, st in self._state.items()},
        }


def load_alerts(path: str) -> List[dict]:
    """Parse an alerts.jsonl (or glob one from a telemetry dir) —
    tolerant like every other artifact loader."""
    if os.path.isdir(path):
        path = os.path.join(path, "alerts.jsonl")
    out: List[dict] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) \
                        and rec.get("type") == "slo_alert":
                    out.append(rec)
    except OSError:
        pass
    return out
