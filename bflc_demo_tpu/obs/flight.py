"""Flight recorder: a bounded ring of recent events, durable past death.

The chaos engine's central observability problem: a SIGKILLed writer or
validator takes its evidence with it — `info.perf` only reports from
processes that survive, which is exactly the wrong sample under faults.
The flight recorder is the Dapper-style out-of-band answer (Sigelman et
al., 2010): every role keeps a small in-memory ring of recent
spans/events and a background flusher persists it to a per-role file on
a short cadence, so even a SIGKILL (uncatchable by design) loses at most
one flush interval of tail.  Catchable exits flush synchronously:

- SIGTERM (the fleet teardown path and `Process.terminate`);
- an unhandled exception (sys.excepthook);
- an invariant violation (chaos.invariants flags call `note` + `flush`);
- interpreter exit (atexit).

Files are written tmp-then-rename so a kill mid-flush can never leave a
torn file — the post-mortem artifact either parses or is the previous
complete flush.  Format: one JSON object per line; line 0 is a header
{type: "flight_header", role, pid, reason, flushed_at}, the rest are the
ring's events oldest-first.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional


#: extra flush callables chained onto the terminal paths (SIGTERM /
#: unhandled exception / atexit) the flight recorder already owns — the
#: span recorder (obs.trace) registers here so a terminated role's spans
#: land on disk with the same guarantees as its flight dump.  Read at
#: fire time, so late registration is fine.
TERMINAL_FLUSHES: List = []


def _run_terminal_flushes() -> None:
    for fn in list(TERMINAL_FLUSHES):
        try:
            fn()
        except Exception:       # noqa: BLE001 — a failing secondary
            pass                # flush must never block the primary one


class FlightRecorder:
    """Bounded event ring + periodic/terminal flusher (module doc)."""

    def __init__(self, capacity: int = 1024):
        self.enabled = False
        self.role = ""
        self.path = ""
        self._ring: deque = deque(maxlen=capacity)
        # RLock: the SIGTERM handler runs on the main thread and calls
        # flush(); if the signal lands while that same thread is inside
        # record()'s critical section, a plain Lock would deadlock the
        # teardown path (Process.terminate would never complete)
        self._lock = threading.RLock()
        self._flusher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._installed_sigterm = False

    # ------------------------------------------------------------ record
    def record(self, kind: str, name: str, **attrs) -> None:
        """Append one event (no-op unless installed).  `kind` is a small
        closed vocabulary (span/event/fault/invariant_violation/...)."""
        if not self.enabled:
            return
        ev: Dict[str, Any] = {"t": time.time(), "kind": kind,
                              "name": name}
        if attrs:
            ev.update(attrs)
        with self._lock:
            self._ring.append(ev)

    # ------------------------------------------------------------- flush
    def flush(self, reason: str = "periodic") -> bool:
        """Persist the ring to `self.path` atomically (tmp + rename).
        True when a file was written."""
        if not self.path:
            return False
        with self._lock:
            events = list(self._ring)
        header = {"type": "flight_header", "role": self.role,
                  "pid": os.getpid(), "reason": reason,
                  "flushed_at": time.time(), "n_events": len(events)}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                fh.write(json.dumps(header) + "\n")
                for ev in events:
                    fh.write(json.dumps(ev) + "\n")
            os.replace(tmp, self.path)
            return True
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False

    def _flush_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            self.flush("periodic")

    # ----------------------------------------------------------- install
    def install(self, role: str, out_dir: str, *,
                interval_s: float = 1.0,
                signals: bool = True) -> None:
        """Arm the recorder for this process: per-role dump path, the
        periodic flusher thread, and — when `signals` (and running in the
        main thread) — SIGTERM + excepthook + atexit terminal flushes.

        SIGTERM chains to the default disposition after flushing so
        `Process.terminate` still kills the process with the usual
        -SIGTERM exitcode (a swallowed TERM would wedge fleet teardown).
        """
        os.makedirs(out_dir, exist_ok=True)
        self.role = role
        self.path = os.path.join(out_dir, f"{role}.flight.jsonl")
        self.enabled = True
        self.record("event", "flight_recorder_installed", role=role)
        if self._flusher is None:
            self._flusher = threading.Thread(
                target=self._flush_loop, args=(interval_s,), daemon=True)
            self._flusher.start()
        if signals:
            import atexit
            atexit.register(lambda: (self.flush("atexit"),
                                     _run_terminal_flushes()))
            prev_hook = sys.excepthook

            def _hook(tp, val, tb):
                self.record("event", "unhandled_exception",
                            error=f"{tp.__name__}: {val}")
                self.flush("exception")
                _run_terminal_flushes()
                prev_hook(tp, val, tb)

            sys.excepthook = _hook
            if not self._installed_sigterm and \
                    threading.current_thread() is threading.main_thread():
                def _on_term(signum, frame):
                    self.record("event", "sigterm")
                    self.flush("sigterm")
                    _run_terminal_flushes()
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

                try:
                    signal.signal(signal.SIGTERM, _on_term)
                    self._installed_sigterm = True
                except (ValueError, OSError):
                    pass
        # first flush immediately: the file must exist from the moment
        # the role is up, so even an instant SIGKILL leaves an artifact
        self.flush("install")

    def close(self) -> None:
        self._stop.set()
        if self.enabled:
            self.flush("close")
        self.enabled = False


def load_flight(path: str) -> Dict[str, Any]:
    """Parse a flight-recorder dump: {"header": dict, "events": [dict]}.
    Raises ValueError on a malformed file (the artifact contract is that
    dumps ALWAYS parse — rename-into-place guarantees it)."""
    events: List[dict] = []
    header: Optional[dict] = None
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if i == 0 and rec.get("type") == "flight_header":
                header = rec
            else:
                events.append(rec)
    if header is None:
        raise ValueError(f"{path}: missing flight_header line")
    return {"header": header, "events": events}


#: process-wide recorder, armed by obs.install_process_telemetry.
#: Access as `flight.FLIGHT` (module attribute).
FLIGHT = FlightRecorder()
