"""Unified round timeline: one correlated record per federated round.

The fleet emits five observability artifact streams — periodic metrics
scrapes (``metrics.jsonl``, PR 4), causal spans (``*.spans.jsonl``,
PR 8), model-quality health verdicts (``*.health.jsonl``, PR 11),
flight-recorder events (``*.flight.jsonl``) and chaos fault records —
but until this module nothing joined them: answering "why did round 41
breach latency while health went WARN" meant hand-correlating five file
formats.  This is the forensics half of the fourth observability layer
(obs.slo is the alerting half): a **canonical event model** and a
**streaming joiner** that keys every event onto its round and produces
ONE queryable per-round record:

    {epoch, t0, t1, wall_s, commit {acc, ...}, health {role: verdict
     record}, faults in window, scrape stats (coverage, per-round
     certify/staleness tails from cumulative-histogram deltas),
     critical-path segments + straggler ranking (when spans exist),
     alerts}

**Round keying.**  The canonical round key is the pre-commit ledger
epoch ``r`` — what health records, round_commit notes and trace roots
already carry.  Periodic scrapes are post-commit observations: the
writer's `telemetry` RPC stamps its CURRENT epoch ``E`` into each
scrape record (PR 13 — previously scrapes were wall-clock-only and the
joiner had to infer), so a scrape stamped ``E`` describes the fleet
just after round ``E - 1`` committed.  Mixed-version artifacts degrade
gracefully: an unstamped scrape falls back to parsing its ``round-N``
tag, an untagged one joins by wall-clock window, and unknown record
types are skipped — shuffled, truncated or torn streams never raise
(property-tested in tests/test_forensics.py).

Two feeding modes, same joiner:

- **live** — ``RoundForensics`` subscribes to the FleetCollector's
  record stream (collector.add_observer) and evaluates the SLO engine
  as each round's post-commit scrape lands;
- **offline** — ``load_round_timeline(telemetry_dir)`` rebuilds the
  identical state from the artifact directory (tools/obs_query.py,
  tools/incident_bundle.py).

Observability only: nothing here feeds back into admission, selection
or the certified bytes — ``BFLC_SLO_LEGACY=1`` pins the whole plane off
and committed model hashes are byte-identical either way (drilled in
tests/test_forensics.py).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

#: artifact schema revision stamped into joined records (bump when the
#: round-record shape changes; the joiner itself stays tolerant of
#: records from any earlier revision)
SCHEMA_VERSION = 1

#: rounds whose device signals report None (SLO SKIP): every program
#: family legitimately compiles on its first appearance, and the
#: zero-tolerance device_recompiles objective must only ever judge the
#: post-warmup steady state
DEVICE_SLO_WARMUP_ROUNDS = 2


def _round_of_tag(tag) -> Optional[int]:
    """'round-41' -> 41 (the pre-epoch-stamp scrape convention)."""
    if isinstance(tag, str) and tag.startswith("round-"):
        try:
            return int(tag[len("round-"):])
        except ValueError:
            return None
    return None


def round_of_scrape(rec: dict) -> Optional[int]:
    """The round a scrape record DESCRIBES (None when undeterminable).

    A stamped scrape carries the writer's post-commit ledger epoch
    ``E`` — it observes the fleet after round ``E - 1`` committed, so
    it describes round ``E - 1``.  Unstamped records (pre-PR-13
    artifacts) fall back to the driver's ``round-N`` tag, which names
    the round directly."""
    ep = rec.get("epoch")
    if isinstance(ep, int):
        return ep - 1 if ep >= 1 else None
    return _round_of_tag(rec.get("tag"))


def _merge_hist(snapshot: dict, name: str) -> Dict[str, Any]:
    """Merged cumulative-histogram sample for `name` across its label
    sets, from one role snapshot ({} when absent)."""
    from bflc_demo_tpu.obs.metrics import merge_hist_samples
    samples = ((snapshot.get("metrics") or {}).get(name) or {}).get(
        "samples") or []
    return merge_hist_samples(samples) if samples else {}


def hist_delta(cur: Dict[str, Any],
               prev: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-interval histogram: cur - prev on count/sum/cumulative
    buckets.  Exported histograms are cumulative since process start, so
    two consecutive scrapes bracket one round — the delta is the ROUND's
    distribution, which is what an SLO on per-round tail latency must
    judge (a cumulative p95 would average the breach away).  A counter
    reset (role restart: cur < prev) falls back to cur."""
    if not cur:
        return {}
    if not prev:
        return dict(cur)
    if cur.get("count", 0) < prev.get("count", 0):
        return dict(cur)                    # restarted role: fresh epoch
    out = {"count": cur.get("count", 0) - prev.get("count", 0),
           "sum": cur.get("sum", 0.0) - prev.get("sum", 0.0),
           "buckets": {}}
    pb = prev.get("buckets") or {}
    for le, cum in (cur.get("buckets") or {}).items():
        out["buckets"][le] = cum - pb.get(le, 0)
    return out


def _gauge(snapshot: dict, name: str, default=None):
    s = ((snapshot.get("metrics") or {}).get(name) or {}).get(
        "samples") or []
    return s[0].get("value", default) if s else default


def _counter_sum(snapshot: dict, name: str) -> Optional[float]:
    """Sum of a counter's samples across label sets for one role
    snapshot; None when the role doesn't export the metric."""
    s = ((snapshot.get("metrics") or {}).get(name) or {}).get(
        "samples")
    if not s:
        return None
    return float(sum(x.get("value", 0.0) for x in s))


def _fleet_counter(roles: dict, name: str,
                   prefix: str = "validator") -> Optional[float]:
    """Fleet-wide counter total over every answering role whose name
    starts with `prefix`; None when no such role exports it."""
    vals = [_counter_sum(snap, name) for role, snap in roles.items()
            if role.startswith(prefix) and snap]
    vals = [v for v in vals if v is not None]
    return sum(vals) if vals else None


def _fleet_counter_by_label(roles: dict, name: str,
                            label: str) -> Dict[str, float]:
    """Fleet-wide per-label counter totals across EVERY answering role
    ({} when no role exports the metric) — the per-family compile
    attribution the recompile-storm detector differences."""
    out: Dict[str, float] = {}
    for _role, snap in roles.items():
        if not snap:
            continue
        samples = ((snap.get("metrics") or {}).get(name) or {}).get(
            "samples") or []
        for s in samples:
            key = str((s.get("labels") or {}).get(label, ""))
            out[key] = out.get(key, 0.0) + float(s.get("value", 0.0))
    return out


def _fleet_mem_frac(roles: dict) -> Optional[float]:
    """Worst role's peak-memory watermark as a fraction of its known
    capacity; None when no answering role knows a ceiling (backend
    bytes_limit on TPU, BFLC_DEVICE_MEM_CEILING_BYTES elsewhere)."""
    worst = None
    for _role, snap in roles.items():
        if not snap:
            continue
        metrics = snap.get("metrics") or {}

        def _max_sample(name):
            s = (metrics.get(name) or {}).get("samples") or []
            vals = [float(x.get("value", 0.0)) for x in s]
            return max(vals) if vals else 0.0

        peak = _max_sample("device_mem_peak_bytes")
        limit = _max_sample("device_mem_limit_bytes")
        if peak > 0.0 and limit > 0.0:
            frac = peak / limit
            worst = frac if worst is None else max(worst, frac)
    return worst


class RoundTimeline:
    """The streaming joiner (module docstring).  Feed it canonical
    records via ``observe*``; query joined rounds via
    ``round_record`` / ``slo_summary``.  Bounded: only the newest
    ``keep_rounds`` rounds retain full detail."""

    def __init__(self, keep_rounds: int = 1024):
        self.keep_rounds = int(keep_rounds)
        # round r -> commit evidence {t, acc?, loss?}
        self.commits: Dict[int, dict] = {}
        # round r -> [scrape digests] (post-commit observations of r)
        self.scrapes: Dict[int, List[dict]] = {}
        # (role, round) -> health_round record
        self.health: Dict[tuple, dict] = {}
        # wall-clock-only events awaiting window assignment
        self.faults: List[dict] = []
        self.notes: List[dict] = []
        self.alerts: List[dict] = []
        self.spans: List[dict] = []
        # device-plane records (obs.device jsonl): compile events are
        # wall-clock (window-assigned at query time), storm verdicts
        # are epoch-keyed
        self.device: List[dict] = []
        self._prev_scrape_roles: Optional[dict] = None
        self._prev_rederive_skip: Optional[float] = None
        self._prev_device_fams: Optional[Dict[str, float]] = None
        self._span_reports: Optional[Dict[int, dict]] = None

    # ------------------------------------------------------------ ingest
    def observe(self, rec: dict) -> None:
        """One record off the FleetCollector stream (scrape / note /
        fault) or any other canonical dict — unknown types are skipped,
        never raised on (mixed-version tolerance)."""
        if not isinstance(rec, dict):
            return
        t = rec.get("type")
        if t == "scrape":
            self._observe_scrape(rec)
        elif t == "note":
            self._observe_note(rec)
        elif t == "fault":
            self.faults.append(rec)
        elif t == "health_round":
            self.observe_health(rec)
        elif t == "slo_alert":
            self.observe_alert(rec)
        elif isinstance(t, str) and t.startswith("device_"):
            self.observe_device(rec)
        # anything else: a future stream this revision doesn't know

    def _observe_note(self, rec: dict) -> None:
        self.notes.append(rec)
        if rec.get("name") == "round_commit" \
                and isinstance(rec.get("epoch"), int):
            c = self.commits.setdefault(rec["epoch"], {})
            c["t"] = rec.get("t", c.get("t"))
            if "acc" in rec:
                c["acc"] = rec["acc"]
            self._gc()

    def _observe_scrape(self, rec: dict) -> None:
        r = round_of_scrape(rec)
        roles = rec.get("roles") or {}
        # None = writer darkened this scrape (chaos kill / partition):
        # it must NOT clobber the previous answered snapshot, or the
        # next answered scrape's "per-round" histogram deltas would
        # silently fall back to whole-run cumulatives exactly under
        # the faults this plane exists to diagnose
        writer_answered = roles.get("writer")
        writer = writer_answered or {}
        digest = {
            "t": rec.get("t", 0.0),
            "epoch": rec.get("epoch"),
            "epoch_stamped": isinstance(rec.get("epoch"), int),
            "coverage": dict(rec.get("coverage") or {}),
            "health_verdict": _gauge(writer, "health_verdict"),
            "health_flagged": _gauge(writer, "health_flagged_senders"),
            "round_gauge": _gauge(writer, "round"),
            "backlog": _gauge(writer, "uncertified_backlog"),
            "async_depth": _gauge(writer, "async_buffer_depth"),
            # per-round tails: delta of the writer's cumulative
            # histograms against the PREVIOUS scrape (module docstring)
            "certify_hist": hist_delta(
                _merge_hist(writer, "certify_latency_seconds"),
                _merge_hist(self._prev_scrape_roles,
                            "certify_latency_seconds")
                if self._prev_scrape_roles is not None else None),
            "staleness_hist": hist_delta(
                _merge_hist(writer, "async_admitted_staleness"),
                _merge_hist(self._prev_scrape_roles,
                            "async_admitted_staleness")
                if self._prev_scrape_roles is not None else None),
            "upload_lag_hist": hist_delta(
                _merge_hist(writer, "upload_lag_seconds"),
                _merge_hist(self._prev_scrape_roles,
                            "upload_lag_seconds")
                if self._prev_scrape_roles is not None else None),
        }
        # validator-plane coverage: fleet-summed rederive_skipped_total,
        # differenced scrape-to-scrape so the SLO judges THIS round's
        # skips, not the whole run's.  A shrinking total (validator
        # restart reset its counter) reads as zero, never negative.
        skip_total = _fleet_counter(roles, "rederive_skipped_total")
        if skip_total is not None:
            prev = self._prev_rederive_skip
            digest["rederive_skipped_delta"] = (
                max(skip_total - prev, 0.0) if prev is not None
                else skip_total)
            self._prev_rederive_skip = skip_total
        else:
            digest["rederive_skipped_delta"] = None
        # device plane: fleet-summed fresh-compile counters, differenced
        # scrape-to-scrape per family (the storm detector's feed) and
        # totalled (the device_recompiles SLO signal).  The FIRST
        # observation reports None — the compiles before it are warmup,
        # and a restarted role's shrinking counter clamps to zero like
        # the rederive delta above.
        dev_fams = _fleet_counter_by_label(
            roles, "device_compile_total", "family")
        if dev_fams:
            prev_fams = self._prev_device_fams
            if prev_fams is None:
                digest["device_fresh_by_family"] = None
                digest["device_recompiles_delta"] = None
            else:
                by_fam = {f: max(v - prev_fams.get(f, 0.0), 0.0)
                          for f, v in dev_fams.items()}
                digest["device_fresh_by_family"] = by_fam
                digest["device_recompiles_delta"] = sum(by_fam.values())
            self._prev_device_fams = dev_fams
        else:
            digest["device_fresh_by_family"] = None
            digest["device_recompiles_delta"] = None
        digest["device_mem_frac"] = _fleet_mem_frac(roles)
        if writer_answered is not None:
            self._prev_scrape_roles = writer_answered
        if r is not None and r >= 0:
            self.scrapes.setdefault(r, []).append(digest)
            self._gc()
        else:
            # window-assigned later (fleet_up / pre-stamp artifacts)
            self.notes.append({"type": "scrape_unkeyed", **digest})

    def observe_health(self, rec: dict) -> None:
        if rec.get("type") != "health_round":
            return
        ep = rec.get("epoch")
        if isinstance(ep, int):
            self.health[(rec.get("role", "writer"), ep)] = rec
            self._gc()

    def observe_alert(self, rec: dict) -> None:
        if rec.get("type") == "slo_alert":
            self.alerts.append(rec)

    def observe_device(self, rec: dict) -> None:
        """One device-plane record (obs.device ``*.device.jsonl``):
        compile events / memory watermarks / storm verdicts / xprof
        markers.  Storm records are epoch-keyed; the rest join by wall
        window at query time."""
        if isinstance(rec, dict) and str(
                rec.get("type", "")).startswith("device_"):
            self.device.append(rec)

    def observe_spans(self, spans: List[dict]) -> None:
        """Offline feed: spans as obs.trace.load_spans returns them
        (wall-anchored t0/t1).  Invalidates the cached reports."""
        self.spans.extend(s for s in spans
                          if isinstance(s, dict) and "t0" in s)
        self._span_reports = None

    def observe_flight(self, events: List[dict], role: str = "") -> None:
        """Offline feed: a role's flight-recorder events.  The writer's
        ``round_committed`` / ``async_round_committed`` events anchor
        commits when the driver's metrics.jsonl is missing or torn (a
        SIGKILLed driver takes its notes with it — the flight dump is
        exactly the out-of-band copy)."""
        for ev in events:
            if not isinstance(ev, dict):
                continue
            self.notes.append({**ev, "flight_role": role})
            if ev.get("name") in ("round_committed",
                                  "async_round_committed") \
                    and isinstance(ev.get("epoch"), int):
                c = self.commits.setdefault(ev["epoch"], {})
                c.setdefault("t", ev.get("t"))
                if "loss" in ev:
                    c.setdefault("loss", ev["loss"])

    def _gc(self) -> None:
        """Bound every retained stream to the newest keep_rounds
        rounds: epoch-keyed stores trim by epoch floor, wall-clock
        streams (notes/faults) by the floor round's commit time, and
        alerts by count.  Spans are fed offline only (one load per
        query session) and are not trimmed here."""
        if len(self.alerts) > self.keep_rounds:
            del self.alerts[:len(self.alerts) - self.keep_rounds]
        if len(self.commits) <= self.keep_rounds:
            return
        floor = sorted(self.commits)[-self.keep_rounds]
        floor_t = (self.commits.get(floor) or {}).get("t")
        for d in (self.commits, self.scrapes):
            for k in [k for k in d if k < floor]:
                del d[k]
        for k in [k for k in self.health if k[1] < floor]:
            del self.health[k]
        if floor_t is not None:
            self.faults = [f for f in self.faults
                           if not isinstance(f.get("t"), (int, float))
                           or f["t"] >= floor_t]
            self.notes = [n for n in self.notes
                          if not isinstance(n.get("t"), (int, float))
                          or n["t"] >= floor_t]
            self.device = [d for d in self.device
                           if not isinstance(d.get("t"), (int, float))
                           or d["t"] >= floor_t]

    # ------------------------------------------------------------- query
    def rounds(self) -> List[int]:
        """Every round any stream mentioned, ascending."""
        rs = set(self.commits) | set(self.scrapes)
        rs.update(ep for _role, ep in self.health)
        return sorted(rs)

    def round_bounds(self, r: int):
        """(t0, t1) wall window of round r: previous commit -> this
        commit.  Falls back to health-record / scrape timestamps when a
        commit note is missing (killed driver), and to (None, None)
        when nothing anchors the round in wall time."""
        t1 = (self.commits.get(r) or {}).get("t")
        if t1 is None:
            hs = [h.get("t") for (role, ep), h in self.health.items()
                  if ep == r and h.get("t")]
            t1 = max(hs) if hs else None
        if t1 is None:
            ss = [s["t"] for s in self.scrapes.get(r, ())]
            t1 = min(ss) if ss else None
        prev = [c.get("t") for ep, c in self.commits.items()
                if ep < r and c.get("t") is not None]
        t0 = max(prev) if prev else None
        if t0 is None and t1 is not None:
            hs = [h.get("t") for (role, ep), h in self.health.items()
                  if ep == r - 1 and h.get("t")]
            t0 = max(hs) if hs else None
        return t0, t1

    def _reports_by_epoch(self) -> Dict[int, dict]:
        """Trace round reports keyed by epoch (cached; obs.trace does
        the heavy lifting — segments partition round wall time)."""
        if self._span_reports is None:
            if self.spans:
                from bflc_demo_tpu.obs import trace as obs_trace
                reps = obs_trace.round_reports(self.spans,
                                               faults=self.faults)
                self._span_reports = {rep["epoch"]: rep for rep in reps}
            else:
                self._span_reports = {}
        return self._span_reports

    def faults_in_round(self, r: int) -> List[dict]:
        t0, t1 = self.round_bounds(r)
        if t1 is None:
            return []
        lo = t0 if t0 is not None else t1 - 3600.0
        return [f for f in self.faults
                if isinstance(f.get("t"), (int, float))
                and lo < f["t"] <= t1]

    def device_in_round(self, r: int) -> List[dict]:
        """Round r's device records: epoch-keyed storm verdicts plus
        the wall-window slice of compile / memory / xprof events
        (same window rule as faults_in_round)."""
        out = [d for d in self.device
               if d.get("type") == "device_storm"
               and d.get("epoch") == r]
        t0, t1 = self.round_bounds(r)
        if t1 is not None:
            lo = t0 if t0 is not None else t1 - 3600.0
            out += [d for d in self.device
                    if d.get("type") != "device_storm"
                    and isinstance(d.get("t"), (int, float))
                    and lo < d["t"] <= t1]
        return out

    def round_record(self, r: int) -> Dict[str, Any]:
        """The joined per-round forensic record — every pillar's view of
        round r on one dict (module docstring shape)."""
        t0, t1 = self.round_bounds(r)
        commit = dict(self.commits.get(r) or {})
        scrapes = self.scrapes.get(r, [])
        health = {role: rec for (role, ep), rec in self.health.items()
                  if ep == r}
        verdicts = [h.get("verdict", "ok") for h in health.values()]
        worst = ("crit" if "crit" in verdicts
                 else "warn" if "warn" in verdicts
                 else "ok" if verdicts else None)
        cov = [s["coverage"] for s in scrapes if s.get("coverage")]
        rec: Dict[str, Any] = {
            "schema": SCHEMA_VERSION,
            "epoch": r, "t0": t0, "t1": t1,
            "wall_s": (t1 - t0 if t0 is not None and t1 is not None
                       else None),
            "commit": commit,
            "health_verdict": worst,
            "health": health,
            "faults": self.faults_in_round(r),
            "scrapes": len(scrapes),
            "scrape_coverage": (min(
                (c.get("answered", 0) / c["expected"])
                for c in cov if c.get("expected")) if cov else None),
            "epoch_stamped": any(s.get("epoch_stamped")
                                 for s in scrapes) or None,
            "alerts": [a for a in self.alerts if a.get("epoch") == r],
        }
        # committee seating: the writer's committee_reseat flight events
        # (async re-election, ProtocolConfig.async_reseat_every) name the
        # seats as of each reseat epoch — the round's seated committee
        # is the newest reseat at or before it
        reseats = [n for n in self.notes
                   if n.get("name") == "committee_reseat"
                   and isinstance(n.get("epoch"), int)
                   and isinstance(n.get("seats"), list)]
        if reseats:
            seated = max((n for n in reseats if n["epoch"] <= r),
                         key=lambda n: n["epoch"], default=None)
            if seated is not None:
                rec["committee"] = list(seated["seats"])
            rec["reseat"] = any(n["epoch"] == r for n in reseats) or None
        # closed-loop compression: the writer's genome_update flight
        # events name each certified knob transition (ledger.OP_GENOME)
        # — the record carries the transition THIS round's commit
        # proposed, with the old->new values and the deciding telemetry
        # so forensics can answer "why did density change here?"
        genomes = [n for n in self.notes
                   if n.get("name") == "genome_update"
                   and isinstance(n.get("commit_epoch"), int)
                   and n["commit_epoch"] == r]
        if genomes:
            rec["genome_updates"] = [
                {k: n.get(k) for k in (
                    "epoch", "commit_epoch", "old_density",
                    "new_density", "old_staleness", "new_staleness",
                    "update_norm", "drift", "disagreement")}
                for n in genomes]
        # device plane: the round's compile events / storm verdict /
        # memory watermark plus the last scrape's fleet deltas (what
        # obs_query --round prints and incident bundles slice)
        dev = self.device_in_round(r)
        last = scrapes[-1] if scrapes else {}
        if dev or last.get("device_recompiles_delta") is not None \
                or last.get("device_mem_frac") is not None:
            compiles = [d for d in dev
                        if d.get("type") == "device_compile"]
            by_fam: Dict[str, int] = {}
            for d in compiles:
                f = str(d.get("family", "unattributed"))
                by_fam[f] = by_fam.get(f, 0) + 1
            storms = [d for d in dev if d.get("type") == "device_storm"]
            mems = [d for d in dev if d.get("type") == "device_mem"]
            rec["device"] = {
                "recompiles_delta": last.get("device_recompiles_delta"),
                "mem_frac": last.get("device_mem_frac"),
                "compiles": len(compiles),
                "compiles_by_family": by_fam,
                "compile_events": compiles,
                "storm": storms[-1] if storms else None,
                "mem_peak_bytes": max(
                    (float(d.get("peak_bytes", 0.0)) for d in mems),
                    default=None),
                "xprof": [d for d in dev
                          if d.get("type") == "device_xprof"],
            }
        rep = self._reports_by_epoch().get(r)
        if rep is not None:
            rec["trace"] = {
                "wall_s": rep["wall_s"],
                "segments": rep["segments"],
                "covered_frac": rep["covered_frac"],
                "stragglers": rep["stragglers"],
                "fault_segments": rep["faults"],
            }
        return rec

    def slo_summary(self, r: int) -> Dict[str, Any]:
        """The flat signal dict the SLO engine judges for round r — one
        key per objective signal, None = no data this round (an SLO
        skips, it never breaches on absence).  Uses the round's LAST
        post-commit scrape (the freshest observation of r)."""
        from bflc_demo_tpu.obs.metrics import hist_quantile
        t0, t1 = self.round_bounds(r)
        commit = self.commits.get(r) or {}
        scrapes = self.scrapes.get(r, [])
        last = scrapes[-1] if scrapes else {}
        health = [rec for (role, ep), rec in self.health.items()
                  if ep == r]
        verdict = None
        if health:
            verdict = max({"ok": 0, "warn": 1, "crit": 2}.get(
                h.get("verdict", "ok"), 0) for h in health)
        elif last.get("health_verdict") is not None:
            verdict = int(last["health_verdict"])
        acc = commit.get("acc")
        # regression is judged against the best accuracy STRICTLY
        # BEFORE round r, never the global best: a catch-up pass over
        # an async burst or dark-writer gap judges earlier rounds
        # after later (better) commits are already known, and a
        # look-ahead baseline would page a healthily improving run
        best_prior = max(
            (float(c["acc"]) for ep, c in self.commits.items()
             if ep < r and c.get("acc") is not None), default=None)
        cert = last.get("certify_hist") or {}
        stal = last.get("staleness_hist") or {}
        cov = last.get("coverage") or {}
        return {
            "epoch": r,
            # round 0's "wall" spans fleet spawn + registration — not a
            # latency signal (None = the SLO skips it)
            "round_wall_s": (t1 - t0 if r > 0 and t0 is not None
                             and t1 is not None else None),
            "certify_p95_s": (hist_quantile(cert, 0.95)
                              if cert.get("count") else None),
            "staleness_p95": (hist_quantile(stal, 0.95)
                              if stal.get("count") else None),
            "scrape_coverage": ((cov.get("answered", 0)
                                 / cov["expected"])
                                if cov.get("expected") else None),
            "health_verdict": verdict,
            "accuracy": acc,
            "acc_drop_from_best": (
                round(best_prior - float(acc), 6)
                if acc is not None and best_prior is not None
                else None),
            "rederive_skipped_delta": last.get("rederive_skipped_delta"),
            # device signals skip (None) inside the warmup window —
            # first-appearance compiles are legitimate, and the
            # device_recompiles objective is zero-tolerance after it
            "device_recompiles_delta": (
                last.get("device_recompiles_delta")
                if r >= DEVICE_SLO_WARMUP_ROUNDS else None),
            "device_mem_frac": last.get("device_mem_frac"),
        }


class RoundForensics:
    """The live wiring glue: one RoundTimeline + one SLO engine fed off
    the FleetCollector record stream (collector.add_observer(f.observe)).

    Each round is SLO-judged exactly once, when its post-commit scrape
    lands (by then the round's wall, health gauges, coverage and
    histogram deltas are all observable).  Every failure in here is
    swallowed — forensics must never take down the driver loop."""

    def __init__(self, engine=None, keep_rounds: int = 1024,
                 storm_detector=None):
        self.timeline = RoundTimeline(keep_rounds=keep_rounds)
        self.engine = engine
        # recompile-storm plane (obs.device): fed each judged round's
        # per-family fresh-compile deltas; its records join the
        # timeline like any device stream
        self.storm = storm_detector
        self._judged: set = set()

    def _feed_storm(self, rr: int) -> None:
        if self.storm is None:
            return
        by_fam: Dict[str, float] = {}
        fed = False
        for s in self.timeline.scrapes.get(rr, ()):
            fams = s.get("device_fresh_by_family")
            if fams is None:
                continue                # pre-warmup / dark scrape
            fed = True
            for f, v in fams.items():
                by_fam[f] = by_fam.get(f, 0.0) + float(v)
        if fed:
            self.timeline.observe_device(
                self.storm.observe_round(rr, by_fam))

    def observe(self, rec: dict) -> None:
        try:
            self.timeline.observe(rec)
            if self.engine is None or rec.get("type") != "scrape":
                return
            r = round_of_scrape(rec)
            if r is None or r < 0:
                return
            # judge every committed-but-unjudged round up to r, in
            # order — a fault-darkened writer or an async burst can
            # commit rounds between scrapes, and skipping them would
            # silently shrink the burn windows
            for rr in sorted(ep for ep in self.timeline.commits
                             if ep <= r and ep not in self._judged):
                self._judged.add(rr)
                self._feed_storm(rr)
                for alert in self.engine.observe_round(
                        self.timeline.slo_summary(rr),
                        context=self.timeline.round_record(rr)):
                    self.timeline.observe_alert(alert)
        except Exception:       # noqa: BLE001 — observability only:
            pass                # a forensics bug must not kill the run

    def report(self) -> Dict[str, Any]:
        rep: Dict[str, Any] = {"rounds_joined": len(
            self.timeline.rounds())}
        if self.engine is not None:
            rep.update(self.engine.report())
        if self.storm is not None and self.storm.records:
            last = self.storm.records[-1]
            rep["storm"] = {"rounds": self.storm.rounds,
                            "verdict": last.get("verdict")}
        return rep


def arm_forensics(collector, telemetry_dir: str, *,
                  timeout_s: float = 600.0,
                  max_staleness=None) -> Optional[RoundForensics]:
    """The ONE driver-side arming point (flat process runtime AND the
    hier runtime): build the SLO engine over the standing objectives —
    round-latency bound scaled off the run's own timeout (a round that
    eats a whole fault-recovery window is the breach worth paging on),
    staleness off the protocol genome — subscribe a RoundForensics to
    the collector's record stream, and return it so the caller can
    embed its report in telemetry_report.  None when BFLC_SLO_LEGACY=1
    pins the plane off.  The arming signal is the collector itself,
    NOT this process's metrics registry: drivers never install process
    telemetry (only spawned children do), so a registry check would
    leave the plane dark on every real fleet."""
    from bflc_demo_tpu.obs import device as obs_device
    from bflc_demo_tpu.obs import slo as obs_slo
    if obs_slo.slo_legacy():
        return None
    kw = {"round_latency_s": max(30.0, timeout_s / 20.0)}
    if max_staleness is not None:
        kw["max_staleness"] = float(max(max_staleness, 1))
    engine = obs_slo.SLOEngine(
        obs_slo.default_slos(**kw),
        jsonl_path=os.path.join(telemetry_dir, "alerts.jsonl"))
    # device plane: drivers never install process telemetry, so the
    # driver-side storm records need their sink pointed here
    # explicitly; the detector itself is inert under the device pin
    storm = None
    if not obs_device.device_legacy():
        obs_device.install(telemetry_dir)
        storm = obs_device.RecompileStormDetector(role="driver")
    forensics = RoundForensics(engine, storm_detector=storm)
    collector.add_observer(forensics.observe)
    return forensics


# ------------------------------------------------------------- offline
def load_round_timeline(telemetry_dir: str,
                        keep_rounds: int = 4096) -> RoundTimeline:
    """Rebuild the joined timeline from a telemetry artifact directory:
    metrics.jsonl (scrapes/faults/notes), every *.health.jsonl,
    *.spans.jsonl, *.flight.jsonl, *.device.jsonl, and alerts.jsonl
    when present.  Every
    stream is optional and torn/garbled lines are skipped — a post-
    mortem must parse whatever a dead fleet left behind."""
    from bflc_demo_tpu.obs.collector import load_timeline as _load_jsonl
    tl = RoundTimeline(keep_rounds=keep_rounds)
    mpath = os.path.join(telemetry_dir, "metrics.jsonl")
    for rec in _load_jsonl(mpath):
        tl.observe(rec)
    try:
        names = sorted(os.listdir(telemetry_dir))
    except OSError:
        names = []
    for name in names:
        path = os.path.join(telemetry_dir, name)
        if name.endswith(".health.jsonl"):
            role = name[:-len(".health.jsonl")]
            for rec in _load_jsonl(path):
                rec.setdefault("role", role)
                tl.observe_health(rec)
        elif name.endswith(".spans.jsonl"):
            from bflc_demo_tpu.obs import trace as obs_trace
            tl.observe_spans(obs_trace.load_spans(path))
        elif name.endswith(".flight.jsonl"):
            role = name[:-len(".flight.jsonl")]
            tl.observe_flight(_load_flight_events(path), role)
        elif name.endswith(".device.jsonl"):
            from bflc_demo_tpu.obs import device as obs_device
            for rec in obs_device.load_device_records(path):
                tl.observe_device(rec)
    for rec in _load_jsonl(os.path.join(telemetry_dir, "alerts.jsonl")):
        tl.observe_alert(rec)
    return tl


def _load_flight_events(path: str) -> List[dict]:
    """Flight events, empty on any malformedness (the joiner is the
    tolerant consumer; obs.flight.load_flight stays strict for the
    durability tests)."""
    try:
        from bflc_demo_tpu.obs.flight import load_flight
        return load_flight(path).get("events", [])
    except (OSError, ValueError):
        return []
