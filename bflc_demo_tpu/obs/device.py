"""Device-plane observability: XLA compile/memory telemetry, per-program
cost attribution, recompile-storm detection, and profiler capture
windows.

Four observability layers (metrics, traces, health, forensics/SLO)
watch the *protocol*; this module watches the *device plane* the repo
is named for:

- **compile & cost attribution** — every jit boundary the system owns
  (the meshagg engine's geometry-keyed program cache, the rederive
  plane, the client train step) reports per program-family compile
  events, compile wall seconds, ``compiled.cost_analysis()``
  FLOPs/bytes, execute-time histograms and cache hit/miss counters
  into the one MetricsRegistry — so fleet scrapes, fleet_top and the
  per-round timeline inherit device attribution with no new transport;
- **recompile-storm detection** — `RecompileStormDetector` runs the
  health plane's rolling median/MAD machinery over per-round
  fresh-compile counts per family: after a family's warmup window the
  steady state is ZERO compiles, so any fresh compile is a large
  robust z (WARN), and a sustained streak is CRIT (async mode's
  varying round geometry is the live risk this detector exists for);
- **memory watermarks** — ``device.memory_stats()`` on TPU with an
  RSS / getrusage / tracemalloc CPU fallback chain, published as
  gauges each publisher tick and judged by a memory-ceiling SLO
  objective (obs.slo);
- **profiler capture windows** — `XprofWindow` arms
  ``jax.profiler.trace`` around rounds R..R+K (``--xprof-window R:K``
  / ``BFLC_XPROF``) or on-demand from a CRIT verdict, with the
  artifact dir registered into incident bundles.

**The device plane changes no trust and no bytes.**  The AOT swap in
`instrument` lowers and compiles the SAME jit program XLA would build
on first call (that is where the true compile wall time and
cost_analysis come from), and any failure anywhere in this module
permanently falls back to the untouched jit path — counted, never
raised.  ``BFLC_DEVICE_OBS=0`` pins the plane off entirely; committed
model hashes are byte-identical either way (tests/test_device_obs.py
drills it).
"""

from __future__ import annotations

import json
import os
import sys
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from bflc_demo_tpu.obs import flight as obs_flight
from bflc_demo_tpu.obs import metrics as obs_metrics

LEVELS = ("ok", "warn", "crit")

# --- device-plane telemetry (obs.metrics; no-ops unless the registry
# is enabled).  Families are coarse program identities ("reduce",
# "blocked", "score", "train_step", "eval_step", "rederive") — bounded
# by construction, so label cardinality cannot blow up.
_C_COMPILE = obs_metrics.REGISTRY.counter(
    "device_compile_total",
    "XLA compile events by program family (fresh lowerings, not cache "
    "hits)", ("family",))
_M_COMPILE_S = obs_metrics.REGISTRY.histogram(
    "device_compile_seconds",
    "compile wall seconds per fresh lowering", ("family",))
_C_CACHE = obs_metrics.REGISTRY.counter(
    "device_program_cache_total",
    "program-cache lookups by family and outcome",
    ("family", "event"))
_M_EXEC = obs_metrics.REGISTRY.histogram(
    "device_execute_seconds",
    "per-call execute wall seconds (dispatch + host sync as the caller "
    "sees it — never an added block_until_ready)", ("family",))
_G_FLOPS = obs_metrics.REGISTRY.gauge(
    "device_program_flops",
    "cost_analysis FLOPs of the family's last compiled program",
    ("family",))
_G_PROG_BYTES = obs_metrics.REGISTRY.gauge(
    "device_program_bytes",
    "cost_analysis bytes-accessed of the family's last compiled "
    "program", ("family",))
_C_COST_NA = obs_metrics.REGISTRY.counter(
    "device_cost_analysis_unavailable_total",
    "cost_analysis() calls that raised or returned an unusable shape "
    "(the counted replacement for eval/mfu.py's old silent swallow)",
    ("family",))
_C_AOT_FALLBACK = obs_metrics.REGISTRY.counter(
    "device_aot_fallback_total",
    "instrumented programs that permanently fell back to the plain jit "
    "path after an AOT lower/compile/call failure", ("family",))
_G_MEM_USE = obs_metrics.REGISTRY.gauge(
    "device_mem_bytes_in_use",
    "current device (or process) memory bytes", ("source",))
_G_MEM_PEAK = obs_metrics.REGISTRY.gauge(
    "device_mem_peak_bytes",
    "peak device (or process) memory watermark bytes", ("source",))
_G_MEM_LIMIT = obs_metrics.REGISTRY.gauge(
    "device_mem_limit_bytes",
    "device memory capacity when the backend reports one (0 = unknown)",
    ("source",))
_G_STORM = obs_metrics.REGISTRY.gauge(
    "device_storm_verdict",
    "last recompile-storm verdict (0 ok / 1 warn / 2 crit)")
_C_STORM = obs_metrics.REGISTRY.counter(
    "device_storm_trips_total",
    "recompile-storm trips by family and level", ("family", "level"))
_C_XPROF = obs_metrics.REGISTRY.counter(
    "device_xprof_captures_total",
    "jax.profiler capture windows started, by trigger",
    ("trigger",))

#: per-process output sink (obs.install_process_telemetry arms it with
#: the telemetry dir): device records append to
#: <dir>/<role>.device.jsonl.  Unarmed -> metrics/flight only.
_SINK = {"dir": "", "terminal": False}

#: in-process mirrors of the per-family counters so `report()` (the
#: bench.py `device` artifact section) never has to parse a registry
#: snapshot — plain dicts, updated only when the plane is armed.
_STATE: Dict[str, Dict[str, Any]] = {
    "compiles": {}, "compile_seconds": {}, "flops": {}, "bytes": {},
    "cache_hit": {}, "cache_miss": {}, "execute_calls": {},
    "cost_unavailable": {}, "aot_fallback": {},
}

#: module-level capture window, armed by `arm_xprof` (the driver) so a
#: storm CRIT anywhere in-process can trigger an on-demand capture.
XPROF: Optional["XprofWindow"] = None


def install(out_dir: str) -> None:
    """Point this process's device records at `out_dir` and register
    the terminal flusher with the flight recorder's kill path, so a
    SIGKILLed role's last compile/memory samples survive like its
    spans do."""
    _SINK["dir"] = out_dir
    if not _SINK["terminal"]:
        _SINK["terminal"] = True
        obs_flight.TERMINAL_FLUSHES.append(_terminal_flush)


def device_legacy() -> bool:
    """BFLC_DEVICE_OBS=0 (or false/off/no) pins the whole device plane
    off — the overhead benchmark's baseline switch and the certified-
    bytes drill's legacy leg.  Unset or truthy leaves it armed with
    the rest of telemetry."""
    v = os.environ.get("BFLC_DEVICE_OBS")
    return v is not None and v.strip().lower() in (
        "0", "", "false", "off", "no")


def device_armed() -> bool:
    """The ONE arming decision every instrumented jit boundary asks:
    telemetry on and no legacy pin.  Dark fleets pay two attribute
    checks and keep the untouched jit path."""
    return obs_metrics.REGISTRY.enabled and not device_legacy()


def append_record(rec: Dict[str, Any]) -> None:
    """Eager-append one device record to this process's
    ``<role>.device.jsonl`` (health-plane idiom: append-only, a torn
    tail line is the loader's problem, an OSError is nobody's)."""
    d = _SINK["dir"]
    if not d:
        return
    role = rec.get("role") or obs_metrics.REGISTRY.role or "proc"
    rec.setdefault("role", role)
    try:
        with open(os.path.join(d, f"{role}.device.jsonl"), "a") as fh:
            fh.write(json.dumps(rec) + "\n")
    except OSError:
        pass


def _bump(table: str, family: str, amount: float = 1.0) -> None:
    _STATE[table][family] = _STATE[table].get(family, 0.0) + amount


# --------------------------------------------------- cost attribution
def cost_analysis_stats(compiled: Any, family: str = "unattributed"
                        ) -> Dict[str, float]:
    """{"flops", "bytes"} from ``compiled.cost_analysis()`` — the ONE
    shared helper (eval/mfu.py routes through it).  Per-device lists
    take the first entry; anything unusable counts
    `device_cost_analysis_unavailable_total` and returns zeros —
    counted, never a bare swallow."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            raise TypeError(f"cost_analysis returned {type(ca)}")
        return {"flops": float(ca.get("flops", 0.0) or 0.0),
                "bytes": float(ca.get("bytes accessed", 0.0) or 0.0)}
    except Exception:           # noqa: BLE001 — counted degrade
        _bump("cost_unavailable", family)
        _C_COST_NA.inc(family=family)
        return {"flops": 0.0, "bytes": 0.0}


def record_compile(family: str, seconds: float, *,
                   flops: float = 0.0, bytes_accessed: float = 0.0,
                   estimated: bool = False) -> None:
    """One fresh-lowering compile event: metrics + mirror + sink +
    flight.  `estimated=True` marks first-call wall time standing in
    for compile time (the static-argnames jits, where the trace/compile
    split is not observable without paying a second compile)."""
    if not device_armed():
        return
    _bump("compiles", family)
    _bump("compile_seconds", family, seconds)
    if flops:
        _STATE["flops"][family] = float(flops)
    if bytes_accessed:
        _STATE["bytes"][family] = float(bytes_accessed)
    _C_COMPILE.inc(family=family)
    _M_COMPILE_S.observe(seconds, family=family)
    if flops:
        _G_FLOPS.set(flops, family=family)
    if bytes_accessed:
        _G_PROG_BYTES.set(bytes_accessed, family=family)
    obs_flight.FLIGHT.record(
        "event", "device_compile", family=family,
        seconds=round(seconds, 6), flops=flops,
        estimated=bool(estimated))
    append_record({
        "type": "device_compile", "t": time.time(), "family": family,
        "seconds": round(float(seconds), 6), "flops": float(flops),
        "bytes": float(bytes_accessed), "estimated": bool(estimated)})


def record_cache(family: str, *, hit: bool) -> None:
    """One program-cache lookup outcome for `family`."""
    if not device_armed():
        return
    event = "hit" if hit else "miss"
    _bump("cache_hit" if hit else "cache_miss", family)
    _C_CACHE.inc(family=family, event=event)


def observe_execute(family: str, seconds: float) -> None:
    """One instrumented program call's wall seconds."""
    if not device_armed():
        return
    _bump("execute_calls", family)
    _M_EXEC.observe(seconds, family=family)


# ------------------------------------------------- instrumented jits
class _InstrumentedProgram:
    """AOT-swap wrapper for a geometry-fixed jit (pure array args, no
    statics — the meshagg engine's cached programs).  Armed, the first
    call runs ``fn.lower(*args).compile()`` — the SAME program the jit
    cache would build, so certified bytes cannot change — which is
    where the true compile wall seconds and cost_analysis come from;
    every later call dispatches the compiled executable.  Disarmed, or
    after ANY failure (permanently, counted), calls pass straight to
    the untouched jit."""

    __slots__ = ("fn", "family", "_compiled", "_dead")

    def __init__(self, fn: Callable, family: str):
        self.fn = fn
        self.family = family
        self._compiled: Optional[Any] = None
        self._dead = False

    def _fallback(self, exc_site: str) -> None:
        self._dead = True
        self._compiled = None
        _bump("aot_fallback", self.family)
        _C_AOT_FALLBACK.inc(family=self.family)
        obs_flight.FLIGHT.record(
            "event", "device_aot_fallback", level="WARN",
            family=self.family, site=exc_site)

    def __call__(self, *args):
        if self._dead or not device_armed():
            return self.fn(*args)
        if self._compiled is None:
            try:
                t0 = time.perf_counter()
                compiled = self.fn.lower(*args).compile()
                dt = time.perf_counter() - t0
            except Exception:   # noqa: BLE001 — counted degrade
                self._fallback("compile")
                return self.fn(*args)
            self._compiled = compiled
            stats = cost_analysis_stats(compiled, self.family)
            record_compile(self.family, dt, flops=stats["flops"],
                           bytes_accessed=stats["bytes"])
        t0 = time.perf_counter()
        try:
            out = self._compiled(*args)
        except Exception:       # noqa: BLE001 — counted degrade
            self._fallback("execute")
            return self.fn(*args)
        observe_execute(self.family, time.perf_counter() - t0)
        return out


def instrument(fn: Callable, family: str) -> Callable:
    """Wrap a geometry-fixed jit for AOT compile/cost attribution.
    The wrapper is permanent but inert while disarmed (one attribute
    check per call)."""
    return _InstrumentedProgram(fn, family)


def _static_token(v: Any) -> Any:
    try:
        hash(v)
        return v
    except TypeError:
        return ("id", id(v))


class _JitObserver:
    """Signature-tracking wrapper for a static-argnames jit (the client
    train/eval steps).  A NEW abstract signature — leaf shapes/dtypes +
    pytree structure + static values — means jit will compile; the
    first call's wall time is recorded as an ESTIMATED compile event
    (re-lowering just to time the compile would double the client's
    compile cost).  Known signatures record execute time only."""

    __slots__ = ("fn", "family", "static_argnames", "_seen")

    def __init__(self, fn: Callable, family: str,
                 static_argnames: Tuple[str, ...] = ()):
        self.fn = fn
        self.family = family
        self.static_argnames = tuple(static_argnames)
        self._seen: set = set()

    @staticmethod
    def _leaf_sig(v: Any) -> Tuple:
        if hasattr(v, "shape") and hasattr(v, "dtype"):
            return ("a", tuple(v.shape), str(v.dtype))
        if isinstance(v, (bool, int, str, bytes)):
            # likely a static (batch_size, local_epochs): a new value
            # IS a recompile, so it joins the signature
            return ("s", v)
        if isinstance(v, float):
            # traced weak-typed scalar (lr): value changes don't
            # recompile, so the value stays OUT of the signature
            return ("f",)
        if callable(v):
            return ("c", id(v))
        return ("o", type(v).__name__)

    def _signature(self, args, kwargs):
        import jax
        statics = tuple(sorted(
            (k, _static_token(v)) for k, v in kwargs.items()
            if k in self.static_argnames))
        dyn = {k: v for k, v in kwargs.items()
               if k not in self.static_argnames}
        leaves, treedef = jax.tree_util.tree_flatten((args, dyn))
        return (str(treedef),
                tuple(self._leaf_sig(v) for v in leaves), statics)

    def __call__(self, *args, **kwargs):
        if not device_armed():
            return self.fn(*args, **kwargs)
        try:
            sig = self._signature(args, kwargs)
        except Exception:       # noqa: BLE001 — observability only
            return self.fn(*args, **kwargs)
        fresh = sig not in self._seen
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        if fresh:
            self._seen.add(sig)
            record_compile(self.family, dt, estimated=True)
        observe_execute(self.family, dt)
        return out


def observe_jit(fn: Callable, family: str,
                static_argnames: Tuple[str, ...] = ()) -> Callable:
    """Wrap a static-argnames jit for signature-tracked compile-event
    and execute-time observation (no AOT — see _JitObserver)."""
    return _JitObserver(fn, family, static_argnames)


# ------------------------------------------------- memory watermarks
_LAST_PEAK = {"bytes": 0.0}


def _device_memory_sample() -> Optional[Dict[str, Any]]:
    """Backend memory_stats from an ALREADY-initialized jax — never
    the import/init that would drag a backend up just to measure it."""
    jax = sys.modules.get("jax")
    if jax is None or not _STATE["compiles"] and not _STATE["execute_calls"]:
        return None
    try:
        for dev in jax.devices():
            ms_fn = getattr(dev, "memory_stats", None)
            ms = ms_fn() if callable(ms_fn) else None
            if not ms:
                continue
            return {
                "source": f"device:{dev.platform}",
                "bytes_in_use": float(ms.get("bytes_in_use", 0) or 0),
                "peak_bytes": float(ms.get("peak_bytes_in_use", 0)
                                    or ms.get("bytes_in_use", 0) or 0),
                "bytes_limit": float(ms.get("bytes_limit", 0) or 0)}
    except Exception:           # noqa: BLE001 — observability only
        return None
    return None


def _host_memory_sample() -> Optional[Dict[str, Any]]:
    """CPU fallback chain: /proc RSS/HWM -> getrusage -> tracemalloc."""
    try:
        cur = peak = 0.0
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    cur = float(line.split()[1]) * 1024.0
                elif line.startswith("VmHWM:"):
                    peak = float(line.split()[1]) * 1024.0
        if cur or peak:
            return {"source": "rss", "bytes_in_use": cur,
                    "peak_bytes": max(peak, cur), "bytes_limit": 0.0}
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        peak = float(resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss) * 1024.0
        if peak:
            return {"source": "getrusage", "bytes_in_use": 0.0,
                    "peak_bytes": peak, "bytes_limit": 0.0}
    except Exception:           # noqa: BLE001
        pass
    try:
        import tracemalloc
        if tracemalloc.is_tracing():
            cur, peak = tracemalloc.get_traced_memory()
            return {"source": "tracemalloc",
                    "bytes_in_use": float(cur),
                    "peak_bytes": float(peak), "bytes_limit": 0.0}
    except Exception:           # noqa: BLE001
        pass
    return None


def memory_sample() -> Dict[str, Any]:
    """One memory watermark: ``device.memory_stats()`` when a backend
    is up, else the host fallback chain.  Pure read — no gauges."""
    sample = _device_memory_sample() or _host_memory_sample()
    if sample is None:
        sample = {"source": "none", "bytes_in_use": 0.0,
                  "peak_bytes": 0.0, "bytes_limit": 0.0}
    ceiling = os.environ.get("BFLC_DEVICE_MEM_CEILING_BYTES")
    if ceiling and not sample.get("bytes_limit"):
        try:
            sample["bytes_limit"] = float(ceiling)
        except ValueError:
            pass
    return sample


def sample_memory(*, reason: str = "tick") -> Dict[str, Any]:
    """Take one watermark, publish the gauges, and append a sink
    record when the peak moved >1% (watermarks change rarely; the
    jsonl should not grow one line per publisher tick)."""
    sample = memory_sample()
    if not device_armed():
        return sample
    src = sample["source"]
    _G_MEM_USE.set(sample["bytes_in_use"], source=src)
    _G_MEM_PEAK.set(sample["peak_bytes"], source=src)
    _G_MEM_LIMIT.set(sample.get("bytes_limit", 0.0), source=src)
    peak = float(sample["peak_bytes"])
    if peak > _LAST_PEAK["bytes"] * 1.01 or reason != "tick":
        _LAST_PEAK["bytes"] = max(peak, _LAST_PEAK["bytes"])
        append_record({"type": "device_mem", "t": time.time(),
                       "reason": reason, **sample})
    return sample


# --------------------------------------------- recompile-storm plane
class RecompileStormDetector:
    """Streaming recompile-storm verdicts: the health plane's rolling
    median/MAD machinery over per-round FRESH-COMPILE counts per
    program family.

    After a family's warmup window the healthy steady state is zero
    compiles per round, so the rolling median collapses to 0 and the
    robust scale to ``abs_floor`` — one fresh compile then scores
    ``z = 1/abs_floor`` (WARN at the default 0.25 -> z=4), two or more
    score crit-worthy, and ``crit_streak`` consecutive tripping rounds
    for the same family escalate to CRIT (one legitimate one-off — an
    async re-election changing the score geometry — must not page).
    Streaks EXPIRE past ``streak_gap`` detector rounds, and no family
    is judged before ``min_baseline`` observations or inside its own
    ``warmup`` rounds (cold start cannot produce false verdicts —
    every family legitimately compiles on its first appearance).
    """

    def __init__(self, *, window: int = 64, min_baseline: int = 4,
                 warmup: int = 2, warn_z: float = 4.0,
                 crit_z: float = 8.0, rel_floor: float = 0.05,
                 abs_floor: float = 0.25, crit_streak: int = 2,
                 streak_gap: int = 8, role: str = "driver",
                 keep_records: int = 512):
        self.window = int(window)
        self.min_baseline = int(min_baseline)
        self.warmup = int(warmup)
        self.warn_z = float(warn_z)
        self.crit_z = float(crit_z)
        self.rel_floor = float(rel_floor)
        self.abs_floor = float(abs_floor)
        self.crit_streak = int(crit_streak)
        self.streak_gap = int(streak_gap)
        self.role = role
        self._hist: Dict[str, deque] = {}
        # family -> (consecutive tripping rounds, detector round of
        # the last trip) — the round anchor expires stale streaks
        self._streak: Dict[str, Tuple[int, int]] = {}
        self.records: deque = deque(maxlen=keep_records)
        self.rounds = 0

    def _baseline(self, hist: deque) -> Optional[Tuple[float, float]]:
        if len(hist) < self.min_baseline:
            return None
        vals = sorted(hist)
        n = len(vals)
        med = (vals[n // 2] if n % 2
               else 0.5 * (vals[n // 2 - 1] + vals[n // 2]))
        devs = sorted(abs(v - med) for v in vals)
        mad = (devs[n // 2] if n % 2
               else 0.5 * (devs[n // 2 - 1] + devs[n // 2]))
        return med, max(1.4826 * mad, self.rel_floor * abs(med),
                        self.abs_floor)

    def observe_round(self, epoch: int,
                      compiles_by_family: Dict[str, float]
                      ) -> Dict[str, Any]:
        """Ingest one round's fresh-compile deltas (families absent
        this round count as zero — the zeros ARE the baseline) and
        return the round's storm record."""
        self.rounds += 1
        fams: Dict[str, Dict[str, Any]] = {}
        worst = 0
        for fam in sorted(set(self._hist) | set(compiles_by_family)):
            fresh = float(compiles_by_family.get(fam, 0.0))
            hist = self._hist.setdefault(
                fam, deque(maxlen=self.window))
            level = 0
            z = None
            judged = len(hist) >= self.warmup
            baseline = self._baseline(hist) if judged else None
            if baseline is not None:
                z = (fresh - baseline[0]) / baseline[1]
                tripping = abs(z) >= self.warn_z
                if tripping:
                    prev, last = self._streak.get(fam, (0, -10 ** 9))
                    streak = (prev + 1 if self.rounds - last
                              <= self.streak_gap else 1)
                    self._streak[fam] = (streak, self.rounds)
                    level = 2 if (abs(z) >= self.crit_z
                                  and streak >= self.crit_streak) \
                        or streak >= self.crit_streak else 1
                else:
                    self._streak.pop(fam, None)
            hist.append(fresh)          # update AFTER judging
            if level:
                _C_STORM.inc(family=fam, level=LEVELS[level])
            worst = max(worst, level)
            fams[fam] = {"fresh": fresh,
                         "z": round(z, 2) if z is not None else None,
                         "level": LEVELS[level]}
        record = {"type": "device_storm", "t": time.time(),
                  "role": self.role, "epoch": int(epoch),
                  "verdict": LEVELS[worst], "families": fams}
        self.records.append(record)
        _G_STORM.set(worst)
        if worst:
            obs_flight.FLIGHT.record(
                "event", "device_storm", level=LEVELS[worst].upper(),
                epoch=int(epoch), verdict=LEVELS[worst],
                families=[f for f, d in fams.items()
                          if d["level"] != "ok"])
        if worst >= 2:
            obs_flight.FLIGHT.flush("device_storm_crit")
            if XPROF is not None:
                XPROF.trigger_once("storm_crit")
        append_record(dict(record))
        return record


# -------------------------------------------- profiler capture window
class XprofWindow:
    """A ``jax.profiler`` capture window around rounds R..R+K-1
    (spec "R:K", K default 1), plus one-shot on-demand captures from a
    CRIT verdict (`trigger_once`).  Entirely inert when unarmed; every
    profiler call is failure-isolated and counted."""

    def __init__(self, spec: str = "", out_dir: str = ""):
        self.out_dir = out_dir
        self.start_round: Optional[int] = None
        self.count = 1
        self.active = False
        self._stop_after: Optional[int] = None
        self._pending_trigger: Optional[str] = None
        self._window_done = False
        self._dead = False
        spec = (spec or "").strip()
        if spec:
            try:
                r, _, k = spec.partition(":")
                self.start_round = int(r)
                self.count = max(int(k), 1) if k else 1
            except ValueError:
                self.start_round = None

    @property
    def armed(self) -> bool:
        return (not self._dead
                and (self.start_round is not None
                     or self._pending_trigger is not None
                     or self.active))

    def trigger_once(self, reason: str) -> None:
        """Arm a one-round capture starting at the next round boundary
        (no-op while a window is already open or after a profiler
        failure)."""
        if not self._dead and not self.active \
                and self._pending_trigger is None and self.out_dir:
            self._pending_trigger = reason

    def _start(self, epoch: int, trigger: str, rounds: int) -> None:
        try:
            import jax
            os.makedirs(self.out_dir, exist_ok=True)
            jax.profiler.start_trace(self.out_dir)
        except Exception:       # noqa: BLE001 — counted degrade
            self._dead = True
            obs_flight.FLIGHT.record(
                "event", "device_xprof_failed", level="WARN",
                trigger=trigger)
            return
        self.active = True
        self._stop_after = epoch + max(rounds, 1) - 1
        _C_XPROF.inc(trigger=trigger)
        obs_flight.FLIGHT.record(
            "event", "device_xprof_start", trigger=trigger,
            epoch=int(epoch), rounds=rounds, dir=self.out_dir)
        append_record({"type": "device_xprof", "t": time.time(),
                       "event": "start", "trigger": trigger,
                       "epoch": int(epoch), "rounds": rounds,
                       "dir": self.out_dir})

    def _stop(self, epoch: int) -> None:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:       # noqa: BLE001
            self._dead = True
        self.active = False
        self._stop_after = None
        obs_flight.FLIGHT.record(
            "event", "device_xprof_stop", epoch=int(epoch),
            dir=self.out_dir)
        append_record({"type": "device_xprof", "t": time.time(),
                       "event": "stop", "epoch": int(epoch),
                       "dir": self.out_dir})

    def on_round(self, epoch: int) -> None:
        """Drive the window from the round loop (driver-side): close a
        finished window, then open the configured or triggered one."""
        if self._dead:
            return
        epoch = int(epoch)
        if self.active and self._stop_after is not None \
                and epoch > self._stop_after:
            self._stop(epoch)
        if self.active or not self.out_dir:
            return
        if self.start_round is not None and not self._window_done \
                and epoch >= self.start_round:
            self._window_done = True
            self._start(epoch, "window", self.count)
        elif self._pending_trigger is not None:
            trigger, self._pending_trigger = self._pending_trigger, None
            self._start(epoch, trigger, 1)

    def close(self) -> None:
        if self.active:
            self._stop(self._stop_after or -1)


def arm_xprof(spec: str = "", out_dir: str = "") -> XprofWindow:
    """Build + publish the module-level capture window.  `spec` and
    `out_dir` default from ``BFLC_XPROF`` / ``BFLC_XPROF_DIR``."""
    global XPROF
    spec = spec or os.environ.get("BFLC_XPROF", "")
    out_dir = out_dir or os.environ.get("BFLC_XPROF_DIR", "")
    XPROF = XprofWindow(spec, out_dir)
    return XPROF


# --------------------------------------------------------- reporting
def report() -> Dict[str, Any]:
    """The bench-artifact `device` section: platform, per-family
    compile/cost attribution, memory watermark.  Plain dicts from the
    in-process mirrors — valid whether or not a registry scrape ever
    ran."""
    fams: Dict[str, Dict[str, Any]] = {}
    for fam in sorted(set().union(*(_STATE[k] for k in _STATE))):
        fams[fam] = {
            "compiles": int(_STATE["compiles"].get(fam, 0)),
            "compile_seconds": round(
                _STATE["compile_seconds"].get(fam, 0.0), 6),
            "flops": _STATE["flops"].get(fam, 0.0),
            "bytes": _STATE["bytes"].get(fam, 0.0),
            "cache_hits": int(_STATE["cache_hit"].get(fam, 0)),
            "cache_misses": int(_STATE["cache_miss"].get(fam, 0)),
            "execute_calls": int(_STATE["execute_calls"].get(fam, 0)),
        }
    return {
        "enabled": device_armed(),
        "legacy_pin": device_legacy(),
        "platform": _platform(),
        "families": fams,
        "memory": memory_sample(),
        "cost_analysis_unavailable": int(sum(
            _STATE["cost_unavailable"].values())),
        "aot_fallbacks": int(sum(_STATE["aot_fallback"].values())),
    }


def _platform() -> str:
    jax = sys.modules.get("jax")
    if jax is None:
        return "uninitialized"
    try:
        return str(jax.devices()[0].platform)
    except Exception:           # noqa: BLE001
        return "unknown"


def _terminal_flush() -> None:
    """Flight-recorder terminal path: the dying role's final memory
    watermark and per-family mirror, appended before the process
    goes away (fired from SIGTERM / excepthook / atexit)."""
    try:
        sample_memory(reason="terminal")
        if any(_STATE["compiles"].values()) \
                or any(_STATE["execute_calls"].values()):
            append_record({
                "type": "device_terminal", "t": time.time(),
                "families": report()["families"]})
    except Exception:           # noqa: BLE001 — dying anyway
        pass


def load_device_records(path: str) -> List[Dict[str, Any]]:
    """Every parseable device record under `path` (a dir is globbed
    for *.device.jsonl; torn trailing lines skipped).  The ONE loader
    obs_query, incident_bundle and chaos_soak's storm gate share."""
    files = []
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            if name.endswith(".device.jsonl"):
                files.append(os.path.join(path, name))
    else:
        files = [path]
    records: List[Dict[str, Any]] = []
    for fp in files:
        try:
            with open(fp) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue            # torn tail line
                    if isinstance(rec, dict) and str(
                            rec.get("type", "")).startswith("device_"):
                        rec.setdefault("role",
                                       os.path.basename(fp).split(
                                           ".device.jsonl")[0])
                        records.append(rec)
        except OSError:
            continue
    records.sort(key=lambda r: (r.get("t", 0.0), r.get("epoch", 0)))
    return records


def reset_state() -> None:
    """Clear the in-process mirrors (tests; never part of a run)."""
    for table in _STATE.values():
        table.clear()
    _LAST_PEAK["bytes"] = 0.0
