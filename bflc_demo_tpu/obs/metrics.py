"""Fleet metrics registry: Counter / Gauge / Histogram with label sets.

The reference observes itself through three print streams behind an
OUTPUT macro and meters cost with blockchain gas (SURVEY.md §5); PR 3
upgraded that to a per-process `Tracer` (utils.tracing.PROC) — but every
role still kept its telemetry private.  This registry is the fleet-wide
half: Monarch-style labeled metrics with BOUNDED cardinality (Adya et
al., VLDB 2020) that every role can expose over the `telemetry` wire RPC
(comm.ledger_service / comm.bft) or publish as a file snapshot
(obs.flight), scraped each round by obs.collector.FleetCollector.

Design rules:

- **near-zero cost when disabled** (the default): every mutate is one
  attribute check and a return — instrument hot paths freely;
- **bounded cardinality**: each metric holds at most
  `max_series_per_metric` label sets; overflow folds into a single
  ``{"overflow": "true"}`` series and bumps the registry's
  `series_dropped` counter instead of growing without bound (a hostile
  or buggy label value must not OOM the process);
- **tracer absorption**: `snapshot()` carries `utils.tracing.PROC.costs`
  verbatim under `trace_costs` — the gas-pricer categories
  (wire/crypto/validate/certify/aggregate) ride every scrape without
  re-plumbing the charge sites;
- snapshots are plain JSON-able dicts; `to_prometheus` renders the
  standard text exposition format.
"""

from __future__ import annotations

import os
import re
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, float("inf"))

_OVERFLOW_KEY = (("overflow", "true"),)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _label_key(labelnames: Tuple[str, ...],
               labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple((k, str(labels.get(k, ""))) for k in labelnames)


class _Metric:
    """Shared series storage: {label-tuple: value-or-hist-state}."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 labelnames: Tuple[str, ...]):
        self._reg = registry
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._series: Dict[Tuple[Tuple[str, str], ...], Any] = {}

    def _key_for(self, labels: Dict[str, str]):
        """The series key for `labels`, folding NEW series past the
        cardinality cap into the overflow series (caller holds the
        registry lock)."""
        key = _label_key(self.labelnames, labels)
        if key in self._series:
            return key
        if len(self._series) >= self._reg.max_series_per_metric:
            self._reg.series_dropped += 1
            return _OVERFLOW_KEY
        return key

    def samples(self) -> List[dict]:
        return [{"labels": dict(k), "value": v}
                for k, v in self._series.items()]


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        with reg._lock:
            key = self._key_for(labels)
            self._series[key] = self._series.get(key, 0.0) + amount


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        with reg._lock:
            self._series[self._key_for(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        with reg._lock:
            key = self._key_for(labels)
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


class _HistState:
    __slots__ = ("count", "sum", "buckets")

    def __init__(self, n_buckets: int):
        self.count = 0
        self.sum = 0.0
        self.buckets = [0] * n_buckets


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, registry, name, help, labelnames,
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        super().__init__(registry, name, help, labelnames)
        bs = sorted(float(b) for b in buckets)
        if not bs or bs[-1] != float("inf"):
            bs.append(float("inf"))
        self.buckets = tuple(bs)

    def observe(self, value: float, **labels) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        with reg._lock:
            key = self._key_for(labels)
            st = self._series.get(key)
            if st is None:
                st = self._series[key] = _HistState(len(self.buckets))
            st.count += 1
            st.sum += float(value)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    st.buckets[i] += 1
                    break

    def time(self, **labels) -> "_HistTimer":
        """Context manager observing the block's wall duration (a
        disabled registry pays two attribute checks, no clock read)."""
        return _HistTimer(self, labels)

    def samples(self) -> List[dict]:
        out = []
        for k, st in self._series.items():
            cum, buckets = 0, {}
            for b, n in zip(self.buckets, st.buckets):
                cum += n            # Prometheus buckets are cumulative
                buckets["+Inf" if b == float("inf") else repr(b)] = cum
            out.append({"labels": dict(k), "count": st.count,
                        "sum": st.sum, "buckets": buckets})
        return out


class _HistTimer:
    __slots__ = ("_h", "_labels", "_t0")

    def __init__(self, h: Histogram, labels: Dict[str, str]):
        self._h = h
        self._labels = labels
        self._t0 = 0.0

    def __enter__(self):
        if self._h._reg.enabled:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._h._reg.enabled and self._t0:
            self._h.observe(time.perf_counter() - self._t0,
                            **self._labels)
        return False


class MetricsRegistry:
    """Process-wide metric registry (one per role process).

    Metric constructors are idempotent by name — modules declare their
    metrics at import and re-imports get the same object; a name reused
    with a different kind or label set raises (silent divergence would
    corrupt every downstream consumer).
    """

    def __init__(self, enabled: bool = False, role: str = ""):
        self.enabled = enabled
        self.role = role
        self.max_series_per_metric = 64
        self.series_dropped = 0
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}

    # ------------------------------------------------------- constructors
    def _get_or_make(self, cls, name: str, help: str,
                     labelnames: Tuple[str, ...], **kw) -> _Metric:
        name = _NAME_RE.sub("_", name)
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) \
                        or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-declared as {cls.kind} "
                        f"labels={tuple(labelnames)} but exists as "
                        f"{m.kind} labels={m.labelnames}")
                return m
            m = cls(self, name, help, tuple(labelnames), **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Tuple[str, ...] = ()) -> Counter:
        return self._get_or_make(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Tuple[str, ...] = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Tuple[str, ...] = (),
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_make(Histogram, name, help, labelnames,
                                 buckets=buckets)

    # ---------------------------------------------------------- reporting
    def snapshot(self) -> Dict[str, Any]:
        """JSON-able point-in-time view: every series of every metric,
        plus the process tracer's cost counters (the Tracer.charge
        categories absorbed — one scrape carries both planes)."""
        from bflc_demo_tpu.utils import tracing
        with self._lock:
            metrics = {name: {"type": m.kind, "help": m.help,
                              "samples": m.samples()}
                       for name, m in self._metrics.items()}
        return {"t": time.time(), "role": self.role, "pid": os.getpid(),
                "enabled": self.enabled,
                "series_dropped": self.series_dropped,
                "metrics": metrics,
                "trace_costs": dict(tracing.PROC.costs)}

    def reset(self) -> None:
        """Zero every metric's series WITHOUT unregistering the metric
        objects: modules hold them from import time, so dropping them
        from the registry would orphan live instrumentation sites into
        series no snapshot ever reports."""
        with self._lock:
            for m in self._metrics.values():
                m._series.clear()
            self.series_dropped = 0


def hist_quantile(sample: Dict[str, Any], q: float) -> float:
    """Quantile estimate from one exported histogram sample (the
    cumulative-bucket dict `Histogram.samples` / a scrape snapshot
    carries).  Prometheus-style upper-bound estimate: the smallest
    bucket boundary whose cumulative count reaches q * count —
    conservative (never under-reports a tail), exact when observations
    sit on boundaries.  Returns +inf when the quantile lands in the
    overflow bucket and 0.0 on an empty sample.  This is the ONE
    quantile rule every renderer shares (tools/fleet_top.py p50/p95/
    p99) so two panels can never disagree about a tail."""
    count = sample.get("count", 0)
    if not count:
        return 0.0
    target = q * count
    buckets = sample.get("buckets", {})
    # buckets dicts preserve ascending boundary order as exported;
    # still sort defensively by numeric bound for foreign snapshots
    ordered = sorted(
        ((float("inf") if le == "+Inf" else float(le), cum)
         for le, cum in buckets.items()), key=lambda kv: kv[0])
    for bound, cum in ordered:
        if cum >= target:
            return bound
    return float("inf")


def merge_hist_samples(samples: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge exported histogram samples (same metric, different label
    sets) into one: counts/sums add, cumulative buckets add per
    boundary.  The merged dict feeds `hist_quantile` directly."""
    out: Dict[str, Any] = {"count": 0, "sum": 0.0, "buckets": {}}
    for s in samples:
        out["count"] += s.get("count", 0)
        out["sum"] += s.get("sum", 0.0)
        for le, cum in (s.get("buckets") or {}).items():
            out["buckets"][le] = out["buckets"].get(le, 0) + cum
    return out


def _escape(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _fmt_labels(labels: Dict[str, str],
                extra: Optional[dict] = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"'
                    for k, v in sorted(items.items()))
    return "{" + body + "}"


def to_prometheus(snapshots: List[Dict[str, Any]],
                  prefix: str = "bflc_") -> str:
    """Render one or many role snapshots as Prometheus text exposition.

    Each snapshot's role rides as a `role` label so a whole-fleet dump
    is one coherent page; tracer cost counters surface as
    `<prefix>trace_cost_total{category=...}`."""
    helps: Dict[str, Tuple[str, str]] = {}
    lines_by_name: Dict[str, List[str]] = {}

    def emit(name: str, kind: str, help: str, line: str) -> None:
        helps.setdefault(name, (kind, help))
        lines_by_name.setdefault(name, []).append(line)

    for snap in snapshots:
        role = {"role": snap.get("role", "")}
        for raw, m in sorted((snap.get("metrics") or {}).items()):
            name = prefix + raw
            for s in m.get("samples", []):
                if m["type"] == "histogram":
                    lab = s.get("labels", {})
                    for le, n in s.get("buckets", {}).items():
                        emit(name, "histogram", m.get("help", ""),
                             f"{name}_bucket"
                             f"{_fmt_labels(lab, {**role, 'le': le})}"
                             f" {n}")
                    emit(name, "histogram", m.get("help", ""),
                         f"{name}_sum{_fmt_labels(lab, role)}"
                         f" {s.get('sum', 0.0)}")
                    emit(name, "histogram", m.get("help", ""),
                         f"{name}_count{_fmt_labels(lab, role)}"
                         f" {s.get('count', 0)}")
                else:
                    emit(name, m["type"], m.get("help", ""),
                         f"{name}{_fmt_labels(s.get('labels', {}), role)}"
                         f" {s.get('value', 0.0)}")
        tname = prefix + "trace_cost_total"
        for cat, v in sorted((snap.get("trace_costs") or {}).items()):
            emit(tname, "counter",
                 "utils.tracing.PROC cost counters (gas-pricer "
                 "categories)",
                 f"{tname}{_fmt_labels({'category': cat}, role)} {v}")
    out: List[str] = []
    for name, lines in lines_by_name.items():
        kind, help = helps[name]
        if help:
            out.append(f"# HELP {name} {help}")
        out.append(f"# TYPE {name} {kind}")
        out.extend(lines)
    return "\n".join(out) + ("\n" if out else "")


#: the process registry every instrumentation site charges into.
#: Disabled by default (one attribute check per site); enabled at
#: interpreter start via BFLC_TELEMETRY=1 + BFLC_TELEMETRY_ROLE (the
#: process-federation spawner sets both), or in process by
#: obs.install_process_telemetry.  Access as `metrics.REGISTRY`
#: (module attribute), never `from ... import REGISTRY` — the same
#: aliasing rule as tracing.PROC.
REGISTRY = MetricsRegistry(
    enabled=bool(os.environ.get("BFLC_TELEMETRY")),
    role=os.environ.get("BFLC_TELEMETRY_ROLE", ""))
