"""Headline benchmark — one JSON line on the BASELINE.md axes.

Metric: FL round time (seconds) for the reference-equivalence workload
(config 1: softmax regression on UCI occupancy, 20 clients, committee 4,
top-6 sample-weighted FedAvg — SURVEY.md §6), full protocol per round
(10 local trainings + committee scoring + aggregation + sponsor eval) using
the device-resident mesh runtime.  Both execution paths are measured:

- per-round (rounds_per_dispatch=1): one XLA program per protocol round,
  host ledger audited synchronously — the latency-honest number;
- batched (rounds_per_dispatch=5): R rounds per dispatch with post-hoc
  ledger replay/audit.

On TPU the headline `value` is the batched warm **median** round time
(compile-bearing first dispatch excluded) — robust to scheduler outliers
on a contended host; mean, std, CV, min and per-round numbers ride in
`extra` so the spread is part of the artifact.  On **cpu-fallback** the
headline is the ACCURACY axis instead (metric `fl_test_acc_config1`,
`vs_baseline` = best_acc / the reference's 0.9214): round times on a
contended shared-CPU host have CV > 1 (VERDICT r5 weak #2) and comparing
them against the reference's sleep-bound 20 s floor misleads — both now
ride in `extra` with `_unstable` suffixes.  Set BFLC_BENCH_ENDURANCE=1 to
also run the DECLARED metric axis (BASELINE.json: test-acc @ round 50 —
VERDICT r5 missing #2) as a 50-round campaign with a monotone-epoch audit
(`eval.benchmarks.endurance_config1`; also tests/test_endurance.py).

Control-plane axes (PR 3): `extra.crypto_backend` records which Ed25519
implementation ran (numbers across hosts are incomparable without it);
`extra.certification` is ops-certified/sec for the BFT commit path —
batched vs sequential, plus a pre-PR legacy-mode baseline leg;
`extra.federation` runs the config-1 process federation (20 clients +
2 standbys + 4 validators + quorum + WAL) and reports round wall time,
ops-certified/sec and the writer's crypto-time share (utils.tracing).
The federation leg runs with the fleet telemetry plane armed (PR 4,
bflc_demo_tpu/obs): `extra.telemetry` records its scrape coverage
(roles answering / roles expected); the measured scrape-on-vs-off
overhead lives in TPU_RESULTS.md (eval.benchmarks.
telemetry_overhead_config1).  `extra.hier` (PR 6) is the
hierarchical-federation flatness axis: root egress and certified
ops/round ratios across a 10x thin-client growth at fixed cell count,
plus the single-tier leg's multiple (eval.benchmarks.hier_scaling; the
full 1k->10k artifact is TPU_RESULTS.md round 11).  `extra.rejoin`
(PR 7) is the certified-snapshot rejoin axis: cold replay-from-genesis
vs snapshot state-sync wall time for a joiner at a few-hundred-round
chain (eval.benchmarks.rejoin_config1).  `extra.async_agg` (PR 9) is
the async buffered-aggregation axis: sync vs async round throughput +
time-to-accuracy under the heavytail straggler chaos profile
(eval.benchmarks.async_agg_config1; the full config-1 artifact with
critical-path evidence is TPU_RESULTS.md round 14).  `extra.mesh_agg`
(ISSUE 11) is the on-mesh batched-aggregation axis: compiled-leg vs
host-loop merge latency at 64/256 stacked deltas with the certified-
hash-equality verdict, and `extra.platform_detail` records the jax
backend, device count/kind and whether the meshagg engine ran jitted —
device evidence every artifact now carries (eval.benchmarks.
mesh_agg_config1; full curve in TPU_RESULTS.md round 15).
`extra.blocked_agg` (ISSUE 18) is the REDUCTION SPEC v2 axis: the
blocked mesh leg vs the v1 mesh leg and host loop across a blocks x N
sweep with byte-equality asserted on every cell, plus the
sharded-model leg whose stacked delta matrix deliberately exceeds the
v1 single-buffer staging path (eval.benchmarks.blocked_agg_config1);
`extra.platform_detail.blocked_agg` records the block geometry.
`extra.sparse` (ISSUE 13) is the sparse-upload-delta axis: writer
egress/round dense vs the sparsest top-k leg (f32 and i8), the QSGD
composition ratio sparse x i8 vs i8 alone, the accuracy gaps and the
encode/decode wall shares (eval.benchmarks.sparse_config1; the full
density x dtype grid is TPU_RESULTS.md round 17).  `extra.rederive`
(ISSUE 15) is the validator re-derivation plane axis: off/shard/full
round-wall overhead, per-validator re-derivation cost, and the
lying-writer refusal drill (eval.benchmarks.rederive_config1).
`extra.closed_loop` (ISSUE 20) is the closed-loop compression axis:
the round-3 accuracy trail of stateless / error-feedback / adaptive
sparse legs vs fast dense, egress reduction vs the legacy dense plane,
EF's rounds-to-0.85 saved at the sparsest density, and the certified
adaptive-density leg's moved-knob / clean-honest-path verdicts
(eval.benchmarks.closed_loop_config1; the 8-round fat-MLP artifact of
record is TPU_RESULTS.md).  `extra.device` (ISSUE 19) is the device-plane self-attribution
section (obs.device): platform, per-program-family compile counts /
wall seconds / cost-analysis FLOPs+bytes / cache hits, peak memory
watermark, and the meshagg engine's program-cache report;
`extra.device_overhead` is the armed-vs-BFLC_DEVICE_OBS=0 federation
round-time ratio plus the steady-state recompile evidence
(post-warmup sync rounds must report zero fleet fresh compiles —
eval.benchmarks.device_overhead_config1).
BFLC_BENCH_NO_CONTROL_PLANE=1 skips all
of it; BFLC_BENCH_FED_BASELINE=1 re-runs the federation on the legacy
control plane for the ratio.

vs_baseline: the reference's round time is structurally bounded below by its
polling design — every protocol phase waits a uniform(10,30) s sleep per
client (python-sdk/main.py:62, 231-233), i.e. >= ~20 s/round in expectation
before any compute.  vs_baseline = 20.0 / measured_mean_round_time (higher
is better; >1 beats the reference).  That floor is sleep-bound, so `extra`
also carries accuracy parity (reference sponsor line: 0.9214,
imgs/runtime.jpg) and samples/sec/chip — the axes a compute-bound
comparison needs.

Robustness: measurements run in child processes under a watchdog.  The TPU
attempt is gated by a cheap PRE-FLIGHT probe child (jax.devices() + one
matmul under its own short timeout, retried once) so a wedged axon tunnel
costs ~2 probe timeouts, not the whole budget.  Every successful on-TPU run
also snapshots its JSON line to BENCH_LATEST.json; if at a later invocation
the chip is unreachable (the axon tunnel is intermittent), the benchmark
replays that snapshot — labelled with `captured_at` and `cached: true` so
the artifact is honest about when the chip was actually measured — before
resorting to the CPU fallback ("platform": "cpu-fallback").
"""

import json
import os
import subprocess
import sys
import time

REPO_DIR = os.path.dirname(os.path.abspath(__file__))
LATEST_PATH = os.path.join(REPO_DIR, "BENCH_LATEST.json")
PROBE_TIMEOUT_S = int(os.environ.get("BFLC_BENCH_PROBE_TIMEOUT", "150"))
PROBE_CODE = (
    "import jax, jax.numpy as jnp; "
    "x = jnp.ones((512, 512), jnp.bfloat16); "
    "(x @ x).block_until_ready(); "
    "print('PROBE_OK', jax.devices()[0].platform)"
)


def _probe_tpu() -> bool:
    """Can this host reach a working TPU quickly?  Two attempts.

    Parses the exact platform token printed by the probe child — only
    'tpu' counts (a cuda/rocm backend would be a misconfigured host for
    this benchmark, not a TPU).
    """
    for _ in range(2):
        try:
            r = subprocess.run([sys.executable, "-c", PROBE_CODE],
                               capture_output=True, text=True,
                               timeout=PROBE_TIMEOUT_S)
            if r.returncode == 0:
                for ln in r.stdout.splitlines():
                    if ln.startswith("PROBE_OK"):
                        toks = ln.split()
                        return len(toks) >= 2 and toks[1] == "tpu"
        except subprocess.TimeoutExpired:
            pass
    return False


def _child() -> None:
    if os.environ.get("BFLC_BENCH_FORCE_CPU"):
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=4")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax

    from bflc_demo_tpu.eval import bench_config1
    from bflc_demo_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    # arm the metrics registry in THIS process so the device plane
    # (obs.device) attributes the in-process mesh runs — compile
    # events, cost analysis and cache hits land in extra.device.
    # Observability only: certified bytes are byte-identical either
    # way (tests/test_device_obs.py)
    from bflc_demo_tpu.obs import metrics as obs_metrics
    obs_metrics.REGISTRY.enabled = True
    obs_metrics.REGISTRY.role = "bench"
    platform = jax.devices()[0].platform
    # batched path (20 rounds, 5 per dispatch); the headline is the WARM
    # mean — steady-state rounds after the compile-bearing first dispatch
    rb = bench_config1(rounds=20, runtime="mesh", rounds_per_dispatch=5)
    # per-round path: latency per protocol round with synchronous audit,
    # plus XLA cost-analysis FLOPs -> MFU when the chip peak is known
    rp = bench_config1(rounds=6, runtime="mesh", rounds_per_dispatch=1,
                       estimate_flops=True)
    # headline: the warm MEDIAN round time — robust to scheduler outliers
    # on a contended host (VERDICT r4: the mean swung 66x across rounds on
    # shared CPU with no code-path change); mean/std/CV ride in extra so
    # the spread is part of the artifact, not hidden behind one number
    round_time = rb["warm_median_round_time_s"]
    baseline_round_s = 20.0
    on_cpu = bool(os.environ.get("BFLC_BENCH_FORCE_CPU"))
    best_acc = round(max(rb["best_acc"], rp["best_acc"]), 4)
    extra = {
        "best_test_acc": best_acc,
        "reference_test_acc": 0.9214,
        "batched_warm_median_round_time_s": round(
            rb["warm_median_round_time_s"], 5),
        "batched_warm_mean_round_time_s": round(
            rb["warm_mean_round_time_s"], 5),
        "batched_warm_std_round_time_s": round(
            rb["warm_std_round_time_s"], 5),
        "batched_warm_cv": round(rb["warm_cv"], 3),
        "batched_mean_round_time_s_incl_compile": round(
            rb["mean_round_time_s"], 5),
        "batched_min_round_time_s": round(rb["min_round_time_s"], 5),
        "per_round_min_round_time_s": round(rp["min_round_time_s"], 5),
        "train_samples_per_sec_per_chip": round(
            rb["train_samples_per_sec_per_chip"], 1),
        "rounds": rb["rounds"] + rp["rounds"],
        "baseline_note": ("20 s/round is the reference's structural "
                          "polling floor (sleep-bound); accuracy parity "
                          "and samples/sec/chip are the compute axes"),
        "platform": "cpu-fallback" if on_cpu else platform,
        # the real accelerator story (ISSUE 11): jax backend + device
        # evidence + whether the meshagg engine actually ran jitted —
        # a "cpu-fallback" line with no device story is uninterpretable
        "platform_detail": {
            "jax_backend": jax.default_backend(),
            "device_count": jax.device_count(),
            "device_kind": jax.devices()[0].device_kind,
        },
    }
    if rp.get("flops_per_round"):
        extra["flops_per_round"] = round(rp["flops_per_round"])
        if rp.get("mfu") is not None:
            extra["mfu"] = round(rp["mfu"], 6)
    # device-plane self-attribution (ISSUE 19, obs.device): platform,
    # per-program-family compile counts / wall seconds / cost-analysis
    # FLOPs+bytes / cache hits, peak memory watermark and the meshagg
    # engine's program-cache report — every artifact now says what the
    # device actually compiled and ran, not just how long it took
    from bflc_demo_tpu.meshagg.engine import ENGINE
    from bflc_demo_tpu.obs import device as obs_device
    extra["device"] = obs_device.report()
    extra["device"]["engine"] = {
        "compile_total": ENGINE.report().get("compile_total"),
        "cached_programs": ENGINE.report().get("cached_programs"),
    }
    # control-plane axes (PR 3).  The active crypto backend is recorded
    # unconditionally: cross-host perf numbers are uninterpretable without
    # knowing whether Ed25519 ran on the `cryptography` wheel or the
    # pure-Python fallback.
    from bflc_demo_tpu.comm.identity import ED25519_BACKEND
    extra["crypto_backend"] = ED25519_BACKEND
    if not os.environ.get("BFLC_BENCH_NO_CONTROL_PLANE"):
        from bflc_demo_tpu.eval.benchmarks import (certification_throughput,
                                                   federation_config1)
        # ops-certified/sec with its own pre-PR baseline leg (a light
        # legacy-mode child), then the config-1 process federation —
        # round wall time + crypto share through the real socket path.
        # BFLC_BENCH_FED_BASELINE=1 additionally re-runs the federation
        # on the legacy control plane for the before/after ratio (slow;
        # the artifact of record lives in TPU_RESULTS.md).
        extra["certification"] = certification_throughput(n_ops=24)
        extra["federation"] = federation_config1(
            rounds=3,
            compare_sequential=bool(
                os.environ.get("BFLC_BENCH_FED_BASELINE")))
        # telemetry-plane health (PR 4): scrape coverage — roles
        # answering / roles expected across the federation run's
        # per-round scrapes (the federation leg runs telemetry-armed)
        extra["telemetry"] = extra["federation"]["fast"].get("telemetry")
        # causal-tracing overhead (obs.trace): every-op-traced vs
        # untraced round time at config-1 — the 5% bar tracked per
        # round, plus the reassembly evidence (traces spanning >= 4
        # roles, critical-path attribution fraction)
        from bflc_demo_tpu.eval.benchmarks import trace_overhead_config1
        # trials=2: the leg-order alternation only de-biases the
        # session-warmup artifact with an even number of trials
        # (TPU_RESULTS.md round 13)
        to = trace_overhead_config1(rounds=2, trials=2)
        extra["trace_overhead"] = {
            "overhead_frac": to.get("overhead_frac"),
            "round_wall_time_s_trace_on": to[
                "round_wall_time_s_trace_on"],
            "round_wall_time_s_trace_off": to[
                "round_wall_time_s_trace_off"],
            "trace": to.get("trace"),
        }
        # model-quality health plane (obs.health): armed vs
        # BFLC_HEALTH_LEGACY=1 round time at config-1 — the same 5%
        # bar / alternating-leg harness as trace_overhead (the full
        # artifact of record lives in TPU_RESULTS.md)
        from bflc_demo_tpu.eval.benchmarks import health_overhead_config1
        ho = health_overhead_config1(rounds=2, trials=2)
        extra["health_overhead"] = {
            "overhead_frac": ho.get("overhead_frac"),
            "round_wall_time_s_health_armed": ho[
                "round_wall_time_s_health_armed"],
            "round_wall_time_s_health_legacy": ho[
                "round_wall_time_s_health_legacy"],
        }
        # SLO/forensics plane (obs.timeline + obs.slo): armed vs
        # BFLC_SLO_LEGACY=1 round time at config-1 — the same 5% bar /
        # alternating-leg harness; the plane is driver-side, so this
        # charges the joiner + burn-rate judge per scrape tick
        from bflc_demo_tpu.eval.benchmarks import slo_overhead_config1
        so = slo_overhead_config1(rounds=2, trials=2)
        extra["slo_overhead"] = {
            "overhead_frac": so.get("overhead_frac"),
            "round_wall_time_s_slo_armed": so[
                "round_wall_time_s_slo_armed"],
            "round_wall_time_s_slo_legacy": so[
                "round_wall_time_s_slo_legacy"],
        }
        # device plane (obs.device): armed vs BFLC_DEVICE_OBS=0 round
        # time at config-1 — the 1% bar (compile/memory attribution is
        # cheaper than the other planes: it only fires on cache misses
        # and publisher ticks), plus the armed leg's steady-state
        # recompile evidence (post-warmup sync rounds must show ZERO
        # fleet fresh compiles — the recompile gate)
        from bflc_demo_tpu.eval.benchmarks import device_overhead_config1
        do = device_overhead_config1(rounds=2, trials=2)
        extra["device_overhead"] = {
            "overhead_frac": do.get("overhead_frac"),
            "round_wall_time_s_device_armed": do[
                "round_wall_time_s_device_armed"],
            "round_wall_time_s_device_legacy": do[
                "round_wall_time_s_device_legacy"],
            "steady_state_recompiles": (do.get("device") or {}).get(
                "steady_state_recompiles"),
            "worst_storm_verdict": (do.get("device") or {}).get(
                "worst_storm_verdict"),
        }
        # steady-state recompile gate (tools/check_reduction_spec):
        # a repeated identical reduction scenario must add zero fresh
        # XLA programs after its warmup pass — the in-process twin of
        # the fleet-level zero-recompile evidence above
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        from check_reduction_spec import run_steady_state_check
        extra["device"]["steady_state_gate"] = run_steady_state_check()
        # data-plane axes (PR 5): coordinator egress bytes/round,
        # read-source shares, cache hit ratio, compression ratio and
        # the quantized-delta accuracy gap, vs a
        # BFLC_DATA_PLANE_LEGACY=1 child fleet
        from bflc_demo_tpu.eval.benchmarks import data_plane_config1
        dp = data_plane_config1(rounds=2)
        extra["data_plane"] = {
            "egress_reduction_x": dp.get("egress_reduction_x"),
            "round_time_speedup": dp.get("round_time_speedup"),
            "wire_transparent": dp.get("wire_transparent"),
            "egress_bytes_per_round": dp["fast"][
                "writer_egress_bytes_per_round"],
            "legacy_egress_bytes_per_round": (
                dp.get("pre_pr_legacy", {}).get(
                    "writer_egress_bytes_per_round")),
            "read_source_share": dp["fast"]["read_source_share"],
            "cache_hit_ratio": dp["fast"]["cache_hit_ratio"],
            "compression_ratio": dp["fast"]["compression_ratio"],
            "quantized_acc_gap": dp.get("quantized_acc_gap"),
            "quantized_delta_dtype": dp.get("quantized_leg", {}).get(
                "delta_dtype"),
        }
        # sparse upload deltas (ISSUE 13): density-sweep egress at the
        # config-1 fleet — this is the bench-budget twin (dense vs the
        # sparsest leg, f32 and i8; the full {1.0,0.1,0.01} x {f32,i8}
        # grid lives in TPU_RESULTS.md round 17), with the QSGD
        # composition ratio (sparse x i8 vs i8 alone) and the
        # encode/decode wall shares that bound the CPU cost of the win
        from bflc_demo_tpu.eval.benchmarks import sparse_config1
        sp = sparse_config1(rounds=2, densities=(1.0, 0.01),
                            dtypes=("f32", "i8"))
        sp_sparsest = sp["legs"].get("d0.01_f32", {})
        extra["sparse"] = {
            # ratio vs the PR-5 LEGACY dense-f32 baseline (fan-out/
            # cache/compression off) — the headline; the fast-plane
            # internal ratio rides separately so the two wins are
            # never conflated
            "egress_vs_legacy_dense_f32_x": sp.get(
                "egress_vs_legacy_dense_f32_x", {}).get("d0.01_f32"),
            "egress_vs_fast_dense_f32_x": sp.get(
                "egress_vs_dense_f32_x", {}).get("d0.01_f32"),
            "sparse_i8_vs_i8_x": sp.get("sparse_i8_vs_i8_x"),
            "sparsest_egress_bytes_per_round": sp_sparsest.get(
                "writer_egress_bytes_per_round"),
            "dense_egress_bytes_per_round": sp["legs"].get(
                "d1_f32", {}).get("writer_egress_bytes_per_round"),
            "acc_gap_vs_dense_f32": sp.get("acc_gap_vs_dense_f32"),
            "encode_share_of_round_d001": sp_sparsest.get(
                "encode_share_of_round"),
            "decode_share_of_round_d001": sp_sparsest.get(
                "decode_share_of_round"),
        }
        # closed-loop compression (ISSUE 20): error-feedback catch-up +
        # the certified adaptive-density loop — this is the bench-budget
        # twin (2 rounds, thin fleet; the 3-round fat-MLP artifact of
        # record lives in TPU_RESULTS.md): EF-vs-stateless accuracy gap
        # at the sparsest density, the EF egress reduction vs dense, and
        # the adaptive leg's moved-knob + clean-honest-path verdicts
        from bflc_demo_tpu.eval.benchmarks import closed_loop_config1
        cl = closed_loop_config1(rounds=3, model_hidden=2048,
                                 validators=4, timeout_s=300.0)
        extra["closed_loop"] = {
            "egress_reduction_ef_x": cl.get("egress_reduction_ef_x"),
            "egress_reduction_adaptive_x": cl.get(
                "egress_reduction_adaptive_x"),
            "egress_reduction_at_matched_acc_x": cl.get(
                "egress_reduction_at_matched_acc_x"),
            "acc_gap_stateless": cl.get("acc_gap_stateless"),
            "acc_gap_ef": cl.get("acc_gap_ef"),
            "acc_gap_adaptive": cl.get("acc_gap_adaptive"),
            "acc_catch_up": cl.get("acc_catch_up"),
            "rounds_to_085_ef": cl.get("rounds_to_085_ef"),
            "ef_rounds_saved": cl.get("ef_rounds_saved"),
            "adaptive_density_moved": cl.get("adaptive_density_moved"),
            "adaptive_honest_path_clean": cl.get(
                "adaptive_honest_path_clean"),
            "adaptive_eff_density_final": cl["legs"]["adaptive"].get(
                "eff_density_final"),
            "geometry": cl["geometry"],
        }
        # hierarchical-federation axes (PR 6): root-coordinator cost vs
        # simulated thin-client count at fixed cell count — the headline
        # claim is the flatness ratios (~1.0 across a 10x client-count
        # increase; the full 1k->10k run is TPU_RESULTS.md round 11,
        # this is its scaled-down bench-budget twin), plus the
        # single-tier leg's multiple at the SAME client count
        from bflc_demo_tpu.eval.benchmarks import hier_scaling
        hs = hier_scaling(clients=(250, 2500), cells=8, rounds=2,
                          validators=4, single_tier=(250,))
        extra["hier"] = {
            "clients_growth_x": hs.get("clients_growth_x"),
            "egress_ratio_across_growth": hs.get("hier_egress_ratio"),
            "ops_ratio_across_growth": hs.get("hier_ops_ratio"),
            "certified_ops_ratio_across_growth": hs.get(
                "hier_certified_ops_ratio"),
            "single_vs_hier_egress_x": hs.get("single_vs_hier_egress_x"),
            "single_vs_hier_ops_x": hs.get("single_vs_hier_ops_x"),
            "root_egress_bytes_per_round": {
                n: leg["root_egress_bytes_per_round"]
                for n, leg in hs["hier"].items()},
            "root_certified_ops_per_round": {
                n: leg["root_certified_ops_per_round"]
                for n, leg in hs["hier"].items()},
            "geometry": hs["geometry"],
        }
        # rejoin axis (PR 7): cold replay-from-genesis vs certified
        # snapshot state-sync through the real serving surfaces, at a
        # few-hundred-round chain (eval.benchmarks.rejoin_config1)
        from bflc_demo_tpu.eval.benchmarks import rejoin_config1
        extra["rejoin"] = rejoin_config1(rounds=300)
        # on-mesh batched aggregation (ISSUE 11): compiled mesh leg vs
        # the pre-engine host loop at 64/256 stacked deltas (the bench-
        # budget twin — the full 64/256/1024 curve lives in
        # TPU_RESULTS.md round 15), with the certified-hash-equality
        # verdict, compile count, and the engine's which-leg-ran
        # evidence
        from bflc_demo_tpu.eval.benchmarks import mesh_agg_config1
        ma = mesh_agg_config1(batch_sizes=(64, 256), repeats=3)
        extra["mesh_agg"] = {
            "hashes_equal": ma["hashes_equal"],
            "legs": ma["legs"],
            "programs_compiled": ma["programs_compiled"],
            "engine": ma["engine"],
        }
        extra["platform_detail"]["mesh_agg"] = {
            "selfcheck": ma["engine"]["selfcheck"],
            # did the COMPILED leg actually execute in this process,
            # or did everything fall back to the host loop?
            "jitted": ma["engine"]["calls"].get("mesh", 0) > 0,
        }
        # blocked reduction (ISSUE 18, REDUCTION SPEC v2): blocks x N
        # sweep of the blocked mesh leg vs the v1 mesh leg and the
        # host loop (byte-equality asserted on every cell), plus the
        # sharded-model leg whose stacked (N, P) delta matrix is
        # deliberately larger than the v1 single-buffer staging path
        # wants (eval.benchmarks.blocked_agg_config1)
        from bflc_demo_tpu.eval.benchmarks import blocked_agg_config1
        ba = blocked_agg_config1(batch_sizes=(64, 256),
                                 blocks_sweep=(1, 4, 16), repeats=3)
        extra["blocked_agg"] = {
            "hashes_equal": ba["hashes_equal"],
            "agg_speedup_vs_v1_x": ba.get("agg_speedup_vs_v1_x"),
            "legs": ba["legs"],
            "sharded_model": ba["sharded_model"],
            "programs_compiled": ba["programs_compiled"],
        }
        extra["platform_detail"]["blocked_agg"] = {
            # the block geometry the sweep exercised + what the engine
            # last ran — device-count independence evidence rides the
            # same artifact as the device story
            "blocks_sweep": ba["geometry"]["blocks_sweep"],
            "spec_version": ba["geometry"]["spec_version"],
            "last_blocks": ba["engine"]["last_blocks"],
            "blocked_calls": ba["engine"]["calls"].get("blocked", 0),
        }
        # async buffered aggregation (PR 9): sync vs async legs under
        # the heavytail straggler chaos profile — this is the
        # bench-budget twin (8 clients, short legs); the full config-1
        # artifact with the trace evidence is TPU_RESULTS.md round 14
        from bflc_demo_tpu.eval.benchmarks import async_agg_config1
        aa = async_agg_config1(rounds=3, async_rounds=9, buffer_k=4,
                               clients=8, trace_sample=0.0,
                               timeout_s=420)
        extra["async_agg"] = {
            "round_throughput_speedup": aa.get(
                "round_throughput_speedup"),
            "time_to_acc_target": aa.get("time_to_acc_target"),
            "time_to_acc_speedup": aa.get("time_to_acc_speedup"),
            "sync_round_wall_time_s": aa["sync"]["round_wall_time_s"],
            "async_round_wall_time_s": aa["async"][
                "round_wall_time_s"],
            "sync_best_acc": aa["sync"]["best_acc"],
            "async_best_acc": aa["async"]["best_acc"],
            "chaos_violations": (aa["sync"]["chaos_violations"] or [])
            + (aa["async"]["chaos_violations"] or []),
            "geometry": aa["geometry"],
        }
        # validator re-derivation plane (bflc_demo_tpu.rederive):
        # off/shard/full round-wall overhead + per-validator cost over
        # one scripted fleet, and the refusal drill — a writer
        # committing a corrupted model hash under shard mode must fail
        # certification (eval.benchmarks.rederive_config1)
        from bflc_demo_tpu.eval.benchmarks import rederive_config1
        rd = rederive_config1(rounds=3, validators=4)
        extra["rederive"] = {
            "round_wall_overhead_shard_x":
                rd["round_wall_overhead_shard_x"],
            "round_wall_overhead_full_x":
                rd["round_wall_overhead_full_x"],
            "rederive_s_per_validator_round": {
                m: rd["legs"][m]["rederive_s_per_validator_round"]
                for m in ("shard", "full")},
            "refusal_drill": rd["refusal_drill"],
        }
    if os.environ.get("BFLC_BENCH_ENDURANCE"):
        # the declared metric axis (BASELINE.json: "test-acc @ round 50"),
        # measurable on CPU with no tunnel: one 50-round config-1 campaign
        # with monotone-epoch audit (eval.benchmarks.endurance_config1)
        from bflc_demo_tpu.eval.benchmarks import endurance_config1
        extra["endurance"] = endurance_config1(rounds=50)
    if os.environ.get("BFLC_BENCH_ENDURANCE_ASYNC"):
        # the multi-thousand-round async campaign: snapshot-armed,
        # replica-rederived buffered aggregation under composed
        # heavytail + churn with committee reseats throughout — the
        # bounded-WAL / bounded-memory / zero-false-page evidence
        # (eval.benchmarks.endurance_async_config1)
        from bflc_demo_tpu.eval.benchmarks import endurance_async_config1
        extra["endurance_async"] = endurance_async_config1()
    if on_cpu:
        # VERDICT r5 weak #2: on cpu-fallback the round-time axis has
        # CV > 1 on this contended host and vs_baseline divides the
        # reference's SLEEP-bound 20 s floor by scheduler noise — neither
        # deserves the headline.  Accuracy is the one stable axis: it
        # becomes `value`; every timing (and the sleep-floor ratio)
        # demotes to `extra` where the spread stats qualify it.
        extra["cpu_fallback_note"] = (
            "time axis measured on a contended shared-CPU host — trend "
            "best_test_acc (the headline here) and the warm_cv spread, "
            "not the absolute round time")
        extra["round_time_s_unstable"] = round(round_time, 5)
        extra["vs_baseline_sleep_floor_unstable"] = round(
            baseline_round_s / round_time, 2)
        print(json.dumps({
            "metric": "fl_test_acc_config1",
            "value": best_acc,
            "unit": "accuracy",
            "vs_baseline": round(best_acc / 0.9214, 4),
            "extra": extra,
        }))
        return
    print(json.dumps({
        "metric": "fl_round_time_s_config1",
        "value": round(round_time, 5),
        "unit": "s/round",
        "vs_baseline": round(baseline_round_s / round_time, 2),
        "extra": extra,
    }))


def _emit(line: str) -> None:
    """Print the result line; snapshot it if it was a FRESH on-TPU
    measurement (replayed cache lines must not refresh captured_at — that
    timestamp is the honesty anchor for when the chip was really hit)."""
    print(line)
    try:
        rec = json.loads(line)
        if (rec.get("extra", {}).get("platform") == "tpu"
                and not rec.get("extra", {}).get("cached")):
            rec["extra"]["captured_at"] = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
            with open(LATEST_PATH, "w") as f:
                json.dump(rec, f)
                f.write("\n")
    except (ValueError, OSError):
        pass


def _cached_tpu_line() -> "str | None":
    """A prior on-chip capture from this repo checkout, if one exists."""
    try:
        with open(LATEST_PATH) as f:
            rec = json.load(f)
        if rec.get("extra", {}).get("platform") == "tpu":
            rec["extra"]["cached"] = True
            rec["extra"]["cache_note"] = (
                "chip unreachable at invocation time; this is the most "
                "recent on-TPU capture from this round (see captured_at)")
            return json.dumps(rec)
    except (OSError, ValueError):
        pass
    return None


def main() -> None:
    if os.environ.get("BFLC_BENCH_CHILD"):
        _child()
        return
    budget = int(os.environ.get("BFLC_BENCH_TIMEOUT", "1500"))

    if os.environ.get("BFLC_BENCH_FORCE_CPU"):
        attempts = [({"BFLC_BENCH_FORCE_CPU": "1"}, budget)]
    elif _probe_tpu():
        attempts = [({}, budget), ({"BFLC_BENCH_FORCE_CPU": "1"}, budget)]
    else:
        cached = _cached_tpu_line()
        if cached is not None:
            _emit(cached)
            return
        attempts = [({"BFLC_BENCH_FORCE_CPU": "1"}, budget)]
    last_err = ""
    for extra_env, timeout_s in attempts:
        # each attempt gets its own full budget: if the TPU child wedges
        # after a passing probe, the CPU fallback must still have enough
        # room to produce the honest "cpu-fallback" number
        env = dict(os.environ, BFLC_BENCH_CHILD="1", **extra_env)
        try:
            t0 = time.time()
            proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                                  env=env, capture_output=True, text=True,
                                  timeout=timeout_s)
            lines = [ln for ln in proc.stdout.splitlines()
                     if ln.startswith("{")]
            if proc.returncode == 0 and lines:
                _emit(lines[-1])
                return
            last_err = (f"rc={proc.returncode} after "
                        f"{time.time() - t0:.0f}s: "
                        f"{proc.stderr.strip()[-400:]}")
        except subprocess.TimeoutExpired:
            last_err = f"timed out after {timeout_s}s (wedged backend?)"
    if not os.environ.get("BFLC_BENCH_FORCE_CPU"):
        # an explicit CPU-only request must never answer with a TPU line
        cached = _cached_tpu_line()
        if cached is not None:
            _emit(cached)
            return
    print(json.dumps({
        "metric": "fl_round_time_s_config1", "value": None, "unit": "s/round",
        "vs_baseline": None, "error": last_err}))
    sys.exit(1)


if __name__ == "__main__":
    main()
