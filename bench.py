"""Headline benchmark — one JSON line on the BASELINE.md axes.

Metric: FL round time (seconds) for the reference-equivalence workload
(config 1: softmax regression on UCI occupancy, 20 clients, committee 4,
top-6 sample-weighted FedAvg — SURVEY.md §6), full protocol per round
(10 local trainings + committee scoring + aggregation + sponsor eval) using
the device-resident mesh runtime.  Both execution paths are measured:

- per-round (rounds_per_dispatch=1): one XLA program per protocol round,
  host ledger audited synchronously — the latency-honest number;
- batched (rounds_per_dispatch=5): R rounds per dispatch with post-hoc
  ledger replay/audit — the amortised number (the headline `value`).

vs_baseline: the reference's round time is structurally bounded below by its
polling design — every protocol phase waits a uniform(10,30) s sleep per
client (python-sdk/main.py:62, 231-233), i.e. >= ~20 s/round in expectation
before any compute.  vs_baseline = 20.0 / measured_round_time (higher is
better; >1 beats the reference).  That floor is sleep-bound, so `extra`
also carries accuracy parity (reference sponsor line: 0.9214,
imgs/runtime.jpg) and samples/sec/chip — the axes a compute-bound
comparison needs.

Robustness: measurements run in child processes under a watchdog.  The TPU
attempt is gated by a cheap PRE-FLIGHT probe child (jax.devices() + one
matmul under its own short timeout, retried once) so a wedged axon tunnel
costs ~2 probe timeouts, not the whole budget (round-1 failure mode: the
full 1500 s burned before the CPU fallback).  If the probe never passes,
the benchmark reruns pinned to CPU, honestly labelled
"platform": "cpu-fallback".
"""

import json
import os
import subprocess
import sys
import time

PROBE_TIMEOUT_S = int(os.environ.get("BFLC_BENCH_PROBE_TIMEOUT", "150"))
PROBE_CODE = (
    "import jax, jax.numpy as jnp; "
    "x = jnp.ones((512, 512), jnp.bfloat16); "
    "(x @ x).block_until_ready(); "
    "print('PROBE_OK', jax.devices()[0].platform)"
)


def _probe_tpu() -> bool:
    """Can this host reach a working accelerator quickly?  Two attempts."""
    for _ in range(2):
        try:
            r = subprocess.run([sys.executable, "-c", PROBE_CODE],
                               capture_output=True, text=True,
                               timeout=PROBE_TIMEOUT_S)
            if r.returncode == 0 and "PROBE_OK" in r.stdout:
                return "PROBE_OK cpu" not in r.stdout
        except subprocess.TimeoutExpired:
            pass
    return False


def _child() -> None:
    if os.environ.get("BFLC_BENCH_FORCE_CPU"):
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=4")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax

    from bflc_demo_tpu.eval import bench_config1
    from bflc_demo_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    platform = jax.devices()[0].platform
    # batched path: the headline (20 rounds, 5 per dispatch; min round time
    # excludes the compile-bearing first dispatch)
    rb = bench_config1(rounds=20, runtime="mesh", rounds_per_dispatch=5)
    # per-round path: latency per protocol round with synchronous audit
    rp = bench_config1(rounds=6, runtime="mesh", rounds_per_dispatch=1)
    round_time = rb["min_round_time_s"]
    baseline_round_s = 20.0
    print(json.dumps({
        "metric": "fl_round_time_s_config1",
        "value": round(round_time, 5),
        "unit": "s/round",
        "vs_baseline": round(baseline_round_s / round_time, 2),
        "extra": {
            "best_test_acc": round(max(rb["best_acc"], rp["best_acc"]), 4),
            "reference_test_acc": 0.9214,
            "batched_min_round_time_s": round(rb["min_round_time_s"], 5),
            "batched_mean_round_time_s": round(rb["mean_round_time_s"], 5),
            "per_round_min_round_time_s": round(rp["min_round_time_s"], 5),
            "train_samples_per_sec_per_chip": round(
                rb["train_samples_per_sec_per_chip"], 1),
            "rounds": rb["rounds"] + rp["rounds"],
            "baseline_note": ("20 s/round is the reference's structural "
                              "polling floor (sleep-bound); accuracy parity "
                              "and samples/sec/chip are the compute axes"),
            "platform": ("cpu-fallback"
                         if os.environ.get("BFLC_BENCH_FORCE_CPU")
                         else platform),
        },
    }))


def main() -> None:
    if os.environ.get("BFLC_BENCH_CHILD"):
        _child()
        return
    budget = int(os.environ.get("BFLC_BENCH_TIMEOUT", "1500"))

    attempts = []
    if os.environ.get("BFLC_BENCH_FORCE_CPU"):
        attempts = [({"BFLC_BENCH_FORCE_CPU": "1"}, budget)]
    elif _probe_tpu():
        attempts = [({}, budget), ({"BFLC_BENCH_FORCE_CPU": "1"}, budget)]
    else:
        attempts = [({"BFLC_BENCH_FORCE_CPU": "1"}, budget)]
    last_err = ""
    for extra_env, timeout_s in attempts:
        # each attempt gets its own full budget: if the TPU child wedges
        # after a passing probe, the CPU fallback must still have enough
        # room to produce the honest "cpu-fallback" number
        env = dict(os.environ, BFLC_BENCH_CHILD="1", **extra_env)
        try:
            t0 = time.time()
            proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                                  env=env, capture_output=True, text=True,
                                  timeout=timeout_s)
            lines = [ln for ln in proc.stdout.splitlines()
                     if ln.startswith("{")]
            if proc.returncode == 0 and lines:
                print(lines[-1])
                return
            last_err = (f"rc={proc.returncode} after "
                        f"{time.time() - t0:.0f}s: "
                        f"{proc.stderr.strip()[-400:]}")
        except subprocess.TimeoutExpired:
            last_err = f"timed out after {timeout_s}s (wedged backend?)"
    print(json.dumps({
        "metric": "fl_round_time_s_config1", "value": None, "unit": "s/round",
        "vs_baseline": None, "error": last_err}))
    sys.exit(1)


if __name__ == "__main__":
    main()
