"""Headline benchmark — one JSON line on the BASELINE.md axes.

Metric: FL round time (seconds) for the reference-equivalence workload
(config 1: softmax regression on UCI occupancy, 20 clients, committee 4,
top-6 sample-weighted FedAvg — SURVEY.md §6), full protocol per round
(10 local trainings + committee scoring + aggregation + sponsor eval) using
the device-resident mesh runtime (one XLA program per round).

vs_baseline: the reference's round time is structurally bounded below by its
polling design — every protocol phase waits a uniform(10,30) s sleep per
client (python-sdk/main.py:62, 231-233), i.e. >= ~20 s/round in expectation
before any compute.  vs_baseline = 20.0 / measured_round_time (higher is
better; >1 beats the reference).

Robustness: the measurement runs in a child process with a watchdog.  If the
TPU backend wedges (observed: a stuck axon tunnel blocks jax.devices()
indefinitely), the child is killed and the benchmark reruns pinned to CPU,
honestly labelled "platform": "cpu-fallback" — a number with a caveat beats
a hung driver.
"""

import json
import os
import subprocess
import sys
import time


def _child() -> None:
    if os.environ.get("BFLC_BENCH_FORCE_CPU"):
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=4")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax

    from bflc_demo_tpu.eval import bench_config1

    platform = jax.devices()[0].platform
    r = bench_config1(rounds=10, runtime="mesh", rounds_per_dispatch=5)
    # min over rounds excludes the first (compile-bearing) round
    round_time = r["min_round_time_s"]
    baseline_round_s = 20.0
    print(json.dumps({
        "metric": "fl_round_time_s_config1",
        "value": round(round_time, 5),
        "unit": "s/round",
        "vs_baseline": round(baseline_round_s / round_time, 2),
        "extra": {
            "best_test_acc": round(r["best_acc"], 4),
            "reference_test_acc": 0.9214,
            "mean_round_time_s": round(r["mean_round_time_s"], 5),
            "train_samples_per_sec_per_chip": round(
                r["train_samples_per_sec_per_chip"], 1),
            "rounds": r["rounds"],
            "platform": ("cpu-fallback"
                         if os.environ.get("BFLC_BENCH_FORCE_CPU")
                         else platform),
        },
    }))


def main() -> None:
    if os.environ.get("BFLC_BENCH_CHILD"):
        _child()
        return
    budget = int(os.environ.get("BFLC_BENCH_TIMEOUT", "1500"))
    attempts = [({}, budget), ({"BFLC_BENCH_FORCE_CPU": "1"}, budget)]
    last_err = ""
    for extra_env, timeout_s in attempts:
        env = dict(os.environ, BFLC_BENCH_CHILD="1", **extra_env)
        try:
            t0 = time.time()
            proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                                  env=env, capture_output=True, text=True,
                                  timeout=timeout_s)
            lines = [ln for ln in proc.stdout.splitlines()
                     if ln.startswith("{")]
            if proc.returncode == 0 and lines:
                print(lines[-1])
                return
            last_err = (f"rc={proc.returncode} after "
                        f"{time.time() - t0:.0f}s: "
                        f"{proc.stderr.strip()[-400:]}")
        except subprocess.TimeoutExpired:
            last_err = f"timed out after {timeout_s}s (wedged backend?)"
    print(json.dumps({
        "metric": "fl_round_time_s_config1", "value": None, "unit": "s/round",
        "vs_baseline": None, "error": last_err}))
    sys.exit(1)


if __name__ == "__main__":
    main()
