"""Headline benchmark — one JSON line on the BASELINE.md axes.

Metric: FL round time (seconds) for the reference-equivalence workload
(config 1: softmax regression on UCI occupancy, 20 clients, committee 4,
top-6 sample-weighted FedAvg — SURVEY.md §6), full protocol per round
(10 local trainings + 4x10 committee scorings + aggregation + sponsor eval).

vs_baseline: the reference's round time is structurally bounded below by its
polling design — every protocol phase waits a uniform(10,30) s sleep per
client (python-sdk/main.py:62, 231-233), i.e. >= ~20 s/round in expectation
before any compute.  vs_baseline = 20.0 / measured_round_time (higher is
better; >1 beats the reference).
"""

import json
import time


def main() -> None:
    from bflc_demo_tpu.eval import bench_config1

    r = bench_config1(rounds=10, runtime="mesh")
    # min over rounds excludes the first (compile-bearing) round
    round_time = r["min_round_time_s"]
    baseline_round_s = 20.0
    print(json.dumps({
        "metric": "fl_round_time_s_config1",
        "value": round(round_time, 5),
        "unit": "s/round",
        "vs_baseline": round(baseline_round_s / round_time, 2),
        "extra": {
            "best_test_acc": round(r["best_acc"], 4),
            "reference_test_acc": 0.9214,
            "mean_round_time_s": round(r["mean_round_time_s"], 5),
            "train_samples_per_sec_per_chip": round(
                r["train_samples_per_sec_per_chip"], 1),
            "rounds": r["rounds"],
        },
    }))


if __name__ == "__main__":
    main()
