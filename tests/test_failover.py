"""Writer failover: hot-standby promotion + client fail-over.

The reference has no single point of failure — all 4 PBFT nodes execute
every op, so the chain serves through node loss (README.md:162-183).  These
tests prove the TPU-native equivalent: a Standby follows the writer's op
stream live, the writer dies mid-federation, the standby promotes over the
SAME hash chain, and clients (FailoverClient) finish the run against it.
"""

import hashlib
import struct
import threading
import time

import numpy as np
import pytest

from bflc_demo_tpu.comm.failover import FailoverClient, Standby
from bflc_demo_tpu.comm.identity import (Wallet, provision_wallets,
                                         _op_bytes)
from bflc_demo_tpu.comm.ledger_service import LedgerServer
from bflc_demo_tpu.protocol import ProtocolConfig
from bflc_demo_tpu.utils.serialization import pack_pytree

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3, learning_rate=0.05,
                     batch_size=16)


def _init_blob():
    return pack_pytree({"W": np.zeros((5, 2), np.float32),
                        "b": np.zeros((2,), np.float32)})


def _sign(w, kind, epoch, payload):
    return w.sign(_op_bytes(kind, w.address, epoch, payload)).hex()


def _delta_blob(v):
    return pack_pytree({"W": np.full((5, 2), v, np.float32),
                        "b": np.zeros((2,), np.float32)})


def _drive_round(client, wallets, epoch):
    """One full protocol round through signed requests: uploads by the
    first `needed_update_count` non-committee wallets, then committee
    scores (triggers aggregation + commit)."""
    committee = set(client.request("committee")["committee"])
    trainers = [w for w in wallets if w.address not in committee]
    ups = []
    for i, w in enumerate(trainers[: CFG.needed_update_count]):
        blob = _delta_blob(float(i + 1) * 0.1 + epoch)
        digest = hashlib.sha256(blob).digest()
        payload = digest + struct.pack("<qd", 10 + i, 1.0)
        r = client.request("upload", addr=w.address, blob=blob.hex(),
                           hash=digest.hex(), n=10 + i, cost=1.0,
                           epoch=epoch,
                           tag=_sign(w, "upload", epoch, payload))
        assert r["ok"] or r["status"] == "DUPLICATE", r
        ups.append(w.address)
    comm_wallets = [w for w in wallets if w.address in committee]
    n_up = CFG.needed_update_count
    for j, w in enumerate(comm_wallets):
        scores = [0.5 + 0.01 * (j + u) for u in range(n_up)]
        payload = struct.pack(f"<{n_up}d", *scores)
        r = client.request("scores", addr=w.address, epoch=epoch,
                           scores=scores,
                           tag=_sign(w, "scores", epoch, payload))
        assert r["ok"] or r["status"] in ("DUPLICATE", "WRONG_EPOCH"), r


class TestInThreadPromotion:
    def test_standby_promotes_and_continues_the_chain(self):
        wallets, directory = provision_wallets(CFG.client_num,
                                               b"failover-master-0001")
        srv = LedgerServer(CFG, _init_blob(), directory=directory,
                           stall_timeout_s=60.0, ledger_backend="python")
        srv.start()
        standby = Standby(CFG, [(srv.host, srv.port), ("127.0.0.1", 0)], 1,
                          heartbeat_s=0.3, stall_timeout_s=60.0,
                          ledger_backend="python")
        standby.endpoints[1] = (standby.host, standby.port)
        st = threading.Thread(target=standby.run, daemon=True)
        st.start()

        endpoints = [(srv.host, srv.port), (standby.host, standby.port)]
        client = FailoverClient(endpoints, timeout_s=15.0)
        try:
            for w in wallets:
                r = client.request("register", addr=w.address,
                                   pubkey=w.public_bytes.hex(),
                                   tag=_sign(w, "register", 0, b""))
                assert r["ok"], r
            _drive_round(client, wallets, epoch=0)
            info = client.request("info")
            assert info["epoch"] == 1
            head_before = info["log_head"]
            size_before = info["log_size"]

            # wait until the standby has mirrored everything, then KILL the
            # writer (socket close = every connection dies)
            deadline = time.monotonic() + 20
            while standby.ledger.log_size() < size_before:
                assert time.monotonic() < deadline, "standby lagging"
                time.sleep(0.05)
            srv.close()

            assert standby.promoted.wait(timeout=30), "no promotion"
            info2 = client.request("info")     # fails over automatically
            assert info2["epoch"] == 1
            assert info2["log_size"] >= size_before
            # same chain: the promoted writer's log extends the old head
            ops = client.request("log_range", start=0,
                                 end=size_before)["ops"]
            h = b""
            for op in ops:              # pyledger._append_log chaining
                hh = hashlib.sha256()
                if h:
                    hh.update(h)
                hh.update(bytes.fromhex(op))
                h = hh.digest()
            assert h.hex() == head_before

            # the fleet finishes the NEXT round against the promoted writer
            _drive_round(client, wallets, epoch=1)
            assert client.request("info")["epoch"] == 2
        finally:
            client.close()
            standby.stop()
            srv.close()

    def test_promoted_writer_wal_holds_full_chain(self, tmp_path):
        """A standby promoted with wal_path journals the COMPLETE chain
        (pre-promotion replayed ops + its own), replayable to head
        equality by a fresh ledger — checkpoint/resume parity survives
        failover."""
        from bflc_demo_tpu.ledger import make_ledger

        wallets, directory = provision_wallets(CFG.client_num,
                                               b"failover-master-0003")
        srv = LedgerServer(CFG, _init_blob(), directory=directory,
                           stall_timeout_s=60.0, ledger_backend="python")
        srv.start()
        wal = str(tmp_path / "promoted.wal")
        standby = Standby(CFG, [(srv.host, srv.port), ("127.0.0.1", 0)], 1,
                          heartbeat_s=0.3, stall_timeout_s=60.0,
                          ledger_backend="python", wal_path=wal)
        standby.endpoints[1] = (standby.host, standby.port)
        threading.Thread(target=standby.run, daemon=True).start()

        client = FailoverClient([(srv.host, srv.port),
                                 (standby.host, standby.port)],
                                timeout_s=15.0)
        try:
            for w in wallets:
                assert client.request(
                    "register", addr=w.address,
                    pubkey=w.public_bytes.hex(),
                    tag=_sign(w, "register", 0, b""))["ok"]
            _drive_round(client, wallets, epoch=0)
            size = client.request("info")["log_size"]
            deadline = time.monotonic() + 20
            while standby.ledger.log_size() < size:
                assert time.monotonic() < deadline
                time.sleep(0.05)
            srv.close()
            assert standby.promoted.wait(timeout=30)
            _drive_round(client, wallets, epoch=1)   # post-promotion ops
            info = client.request("info")
            fresh = make_ledger(CFG, backend="python")
            assert fresh.replay_wal(wal) == info["log_size"]
            assert fresh.log_head().hex() == info["log_head"]
        finally:
            client.close()
            standby.stop()
            srv.close()

    def test_two_standbys_promote_in_priority_order(self):
        """Kill the writer AND the first standby: the SECOND standby must
        observe both deaths (connect-refused) and promote — the
        deterministic lease-free election over the endpoint priority list.
        """
        wallets, directory = provision_wallets(CFG.client_num,
                                               b"failover-master-0002")
        srv = LedgerServer(CFG, _init_blob(), directory=directory,
                           stall_timeout_s=60.0, ledger_backend="python")
        srv.start()
        eps = [(srv.host, srv.port), ("127.0.0.1", 0), ("127.0.0.1", 0)]
        sb1 = Standby(CFG, list(eps), 1, heartbeat_s=0.3,
                      stall_timeout_s=60.0, ledger_backend="python")
        sb1.endpoints[1] = (sb1.host, sb1.port)
        eps[1] = (sb1.host, sb1.port)
        sb2 = Standby(CFG, list(eps), 2, heartbeat_s=0.3,
                      stall_timeout_s=60.0, ledger_backend="python")
        sb2.endpoints[2] = (sb2.host, sb2.port)
        eps[2] = (sb2.host, sb2.port)
        t1 = threading.Thread(target=sb1.run, daemon=True)
        t2 = threading.Thread(target=sb2.run, daemon=True)
        t1.start()
        t2.start()

        client = FailoverClient(eps, timeout_s=15.0)
        try:
            for w in wallets:
                r = client.request("register", addr=w.address,
                                   pubkey=w.public_bytes.hex(),
                                   tag=_sign(w, "register", 0, b""))
                assert r["ok"], r
            _drive_round(client, wallets, epoch=0)
            size = client.request("info")["log_size"]
            deadline = time.monotonic() + 20
            while (sb1.ledger.log_size() < size
                   or sb2.ledger.log_size() < size):
                assert time.monotonic() < deadline, "standby lagging"
                time.sleep(0.05)
            # kill writer AND the higher-priority standby
            sb1.stop()
            srv.close()
            assert sb2.promoted.wait(timeout=45), \
                "second standby did not promote"
            info = client.request("info")
            assert info["epoch"] == 1
            _drive_round(client, wallets, epoch=1)
            assert client.request("info")["epoch"] == 2
        finally:
            client.close()
            sb1.stop()
            sb2.stop()
            srv.close()

    def test_lower_priority_standby_refollows_promoted_winner(self):
        """Kill ONLY the writer: standby 1 promotes, standby 2 must detect
        that a higher-priority peer is alive, RE-FOLLOW the promoted
        writer's op stream, and stay current with post-failover rounds."""
        wallets, directory = provision_wallets(CFG.client_num,
                                               b"failover-master-0004")
        srv = LedgerServer(CFG, _init_blob(), directory=directory,
                           stall_timeout_s=60.0, ledger_backend="python")
        srv.start()
        eps = [(srv.host, srv.port), ("127.0.0.1", 0), ("127.0.0.1", 0)]
        sb1 = Standby(CFG, list(eps), 1, heartbeat_s=0.3,
                      stall_timeout_s=60.0, ledger_backend="python")
        sb1.endpoints[1] = (sb1.host, sb1.port)
        eps[1] = (sb1.host, sb1.port)
        sb2 = Standby(CFG, list(eps), 2, heartbeat_s=0.3,
                      stall_timeout_s=60.0, ledger_backend="python")
        sb2.endpoints[2] = (sb2.host, sb2.port)
        eps[2] = (sb2.host, sb2.port)
        threading.Thread(target=sb1.run, daemon=True).start()
        threading.Thread(target=sb2.run, daemon=True).start()

        client = FailoverClient(eps, timeout_s=15.0)
        try:
            for w in wallets:
                assert client.request(
                    "register", addr=w.address,
                    pubkey=w.public_bytes.hex(),
                    tag=_sign(w, "register", 0, b""))["ok"]
            _drive_round(client, wallets, epoch=0)
            size = client.request("info")["log_size"]
            deadline = time.monotonic() + 20
            while (sb1.ledger.log_size() < size
                   or sb2.ledger.log_size() < size):
                assert time.monotonic() < deadline
                time.sleep(0.05)
            srv.close()                      # writer dies; sb1 stays up
            assert sb1.promoted.wait(timeout=30)
            assert not sb2.promoted.is_set()
            # a round driven against the PROMOTED writer must reach sb2's
            # replica via its re-followed subscription
            _drive_round(client, wallets, epoch=1)
            size2 = client.request("info")["log_size"]
            deadline = time.monotonic() + 30
            while sb2.ledger.log_size() < size2:
                assert time.monotonic() < deadline, \
                    f"sb2 stalled at {sb2.ledger.log_size()}/{size2}"
                time.sleep(0.05)
            assert not sb2.promoted.is_set()   # still a follower
            assert sb2.ledger.log_head() == sb1.ledger.log_head()
        finally:
            client.close()
            sb1.stop()
            sb2.stop()
            srv.close()

    def test_standby_rejects_bad_index(self):
        with pytest.raises(ValueError):
            Standby(CFG, [("127.0.0.1", 1)], 1)


@pytest.mark.slow
class TestProcessFailoverDrill:
    def test_kill_coordinator_mid_federation(self):
        """The no-single-point-of-failure drill as real OS processes: the
        primary coordinator is SIGKILLed at epoch 2 of 4; the hot standby
        promotes over the same hash chain and the client fleet finishes the
        remaining rounds against it (reference parity: the chain keeps
        serving through node loss, README.md:162-183)."""
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        from bflc_demo_tpu.data import load_occupancy, iid_shards

        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(xtr[:1500], ytr[:1500], CFG.client_num)
        res = run_federated_processes(
            "make_softmax_regression", shards, (xte[:500], yte[:500]), CFG,
            rounds=4, standbys=1, kill_writer_at_epoch=2,
            stall_timeout_s=20.0, timeout_s=420.0, replicas=1)
        assert res.rounds_completed >= 4
        assert res.best_accuracy() > 0.80, res.accuracy_history
        # the end-of-run replica replays the PROMOTED writer's full log and
        # reproduces its head: one unbroken chain across the failover
        assert res.replica_report["ok"]
        assert res.replica_report["head"] == res.ledger_log_head


class TestFailoverClient:
    def test_rotates_to_live_endpoint(self):
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python")
        srv.start()
        # first endpoint is dead; client must rotate and succeed
        dead = ("127.0.0.1", 1)          # port 1: connection refused
        client = FailoverClient([dead, (srv.host, srv.port)], timeout_s=5.0)
        try:
            assert client.request("info")["ok"]
            assert client.current_endpoint == (srv.host, srv.port)
        finally:
            client.close()
            srv.close()

    def test_all_dead_raises(self):
        client = FailoverClient([("127.0.0.1", 1)], timeout_s=1.0,
                                max_cycles=2)
        with pytest.raises(ConnectionError):
            client.request("info")

    def test_keyless_multi_endpoint_warns_about_fence_poisoning(self):
        """ADVICE r5 (low): without provisioned standby keys, promotion
        evidence is accepted on STRUCTURAL match alone, so one hostile
        endpoint replying {gen: 999, gen_ev: {...}} poisons the fence and
        the client rejects the legitimate writer forever — a one-message
        DoS.  Anywhere failover is real (> 1 endpoint), constructing the
        forgeable configuration must warn loudly; provisioning keys or
        running single-endpoint must stay silent."""
        eps = [("127.0.0.1", 1), ("127.0.0.1", 2)]
        with pytest.warns(RuntimeWarning, match="standby_keys"):
            FailoverClient(eps, timeout_s=1.0)
        import warnings as _warnings
        with _warnings.catch_warnings():
            _warnings.simplefilter("error")     # any warning would raise
            # single endpoint: failover (and the DoS) cannot happen
            FailoverClient(eps[:1], timeout_s=1.0)
            # keys provisioned: evidence is signature-verified
            sb = Wallet.from_seed(b"keyless-warn-test")
            FailoverClient(eps, timeout_s=1.0,
                           standby_keys={1: sb.public_bytes})


class _Partition:
    """A killable TCP forwarder: the standby's only path to the writer.

    Closing it simulates an asymmetric partition — the standby loses the
    writer (probes refused at the proxy port) while direct clients keep
    talking to the still-alive writer on its real address.
    """

    def __init__(self, target):
        import socket as _socket
        self._target = target
        self._socks = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._lsock = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        self._lsock.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", 0))
        self._lsock.listen(16)
        self.host, self.port = self._lsock.getsockname()
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        import socket as _socket
        while not self._stop.is_set():
            try:
                a, _ = self._lsock.accept()
                b = _socket.create_connection(self._target, timeout=5.0)
            except OSError:
                return
            with self._lock:
                self._socks += [a, b]
            for src, dst in ((a, b), (b, a)):
                threading.Thread(target=self._pump, args=(src, dst),
                                 daemon=True).start()

    def _pump(self, src, dst):
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        for s in (src, dst):
            try:
                s.close()
            except OSError:
                pass

    def cut(self):
        self._stop.set()
        with self._lock:
            socks, self._socks = self._socks, []
        for s in [self._lsock] + socks:
            try:
                s.close()
            except OSError:
                pass


class TestPromotionEvidence:
    """The fence is now EVIDENCE, not a bare integer (ADVICE r4 medium:
    any client could demote any writer with one message).  Demotion
    requires a promotion record signed by a provisioned standby identity
    and hash-bound to the writer's own chain prefix."""

    def _ledgers(self):
        """A writer ledger with a few ops and a standby replica of it."""
        from bflc_demo_tpu.ledger import make_ledger
        writer = make_ledger(CFG, backend="python")
        for i in range(CFG.client_num):
            writer.register_node(f"0x{i:040x}")
        standby = make_ledger(CFG, backend="python")
        for i in range(writer.log_size()):
            assert standby.apply_op(writer.log_op(i)).name == "OK"
        return writer, standby

    def test_evidence_verifies_and_rejects_tampering(self):
        from bflc_demo_tpu.comm.identity import Wallet
        from bflc_demo_tpu.comm.ledger_service import (
            make_promotion_evidence, verify_promotion_evidence)
        writer, standby = self._ledgers()
        w = Wallet.from_seed(b"standby-ev-1")
        keys = {1: w.public_bytes}
        assert standby.promote_writer(1, 1).name == "OK"
        ev = make_promotion_evidence(standby, w, 1)
        assert verify_promotion_evidence(ev, writer, keys)
        # divergent suffix on the writer does not break prefix binding
        writer.close_round()
        assert verify_promotion_evidence(ev, writer, keys)
        # tampering: signature, generation, unknown signer, foreign chain
        bad = dict(ev, sig="00" * 64)
        assert not verify_promotion_evidence(bad, writer, keys)
        assert not verify_promotion_evidence(dict(ev, gen=0), writer, keys)
        assert not verify_promotion_evidence(ev, writer, {})
        assert not verify_promotion_evidence(
            ev, writer, {1: Wallet.from_seed(b"other").public_bytes})
        foreign, _ = self._ledgers()
        from bflc_demo_tpu.ledger import make_ledger
        other_chain = make_ledger(CFG, backend="python")
        other_chain.register_node("0x" + "9" * 40)
        assert not verify_promotion_evidence(ev, other_chain, keys)

    def test_bare_fence_no_longer_demotes(self):
        """The DoS is closed: fence=<huge int> with no evidence gets a
        normal reply and the writer keeps serving."""
        from bflc_demo_tpu.comm.identity import Wallet
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python",
                           standby_keys={1: Wallet.from_seed(
                               b"sb").public_bytes})
        srv.start()
        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        c = CoordinatorClient(srv.host, srv.port, timeout_s=10.0)
        try:
            r = c.request("info", fence=999)
            assert r["ok"] and r.get("status") != "STALE_WRITER"
            assert not srv.fenced.is_set()
            # server still alive for the next client
            c2 = CoordinatorClient(srv.host, srv.port, timeout_s=10.0)
            assert c2.request("info")["ok"]
            c2.close()
        finally:
            c.close()
            srv.close()

    def test_forged_evidence_rejected_at_the_socket(self):
        """Evidence signed by a NON-provisioned key must not demote."""
        from bflc_demo_tpu.comm.identity import Wallet
        from bflc_demo_tpu.comm.ledger_service import (
            CoordinatorClient, make_promotion_evidence)
        real = Wallet.from_seed(b"sb-real")
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python",
                           standby_keys={1: real.public_bytes})
        srv.start()
        c = CoordinatorClient(srv.host, srv.port, timeout_s=10.0)
        try:
            # attacker replays the server's own chain into a fake standby
            # ledger, "promotes" it, signs with its OWN key
            from bflc_demo_tpu.ledger import make_ledger
            attacker = Wallet.from_seed(b"attacker")
            fake = make_ledger(CFG, backend="python")
            assert fake.promote_writer(1, 1).name == "OK"
            ev = make_promotion_evidence(fake, attacker, 1)
            r = c.request("info", fence=1, fence_ev=ev)
            assert r["ok"] and not srv.fenced.is_set()
        finally:
            c.close()
            srv.close()


class TestSplitBrainDrill:
    """VERDICT r4 item 4: partition the writer from its standby, force an
    election, heal, and assert exactly ONE surviving committed history."""

    def test_partition_promote_heal_single_history(self):
        from bflc_demo_tpu.comm.identity import Wallet
        wallets, directory = provision_wallets(CFG.client_num,
                                               b"splitbrain-master-01")
        sb_wallet = Wallet.from_seed(b"splitbrain-standby-1")
        keys = {1: sb_wallet.public_bytes}
        srv = LedgerServer(CFG, _init_blob(), directory=directory,
                           stall_timeout_s=60.0, ledger_backend="python",
                           standby_keys=keys)
        srv.start()
        proxy = _Partition((srv.host, srv.port))
        standby = Standby(CFG, [(proxy.host, proxy.port),
                                ("127.0.0.1", 0)], 1,
                          heartbeat_s=0.3, stall_timeout_s=60.0,
                          ledger_backend="python", wallet=sb_wallet,
                          standby_keys=keys)
        standby.endpoints[1] = (standby.host, standby.port)
        threading.Thread(target=standby.run, daemon=True).start()

        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        direct = CoordinatorClient(srv.host, srv.port, timeout_s=10.0)
        try:
            for w in wallets[:-1]:
                r = direct.request("register", addr=w.address,
                                   pubkey=w.public_bytes.hex(),
                                   tag=_sign(w, "register", 0, b""))
                assert r["ok"], r
            size_before = srv.ledger.log_size()
            deadline = time.monotonic() + 20
            while standby.ledger.log_size() < size_before:
                assert time.monotonic() < deadline, "standby lagging"
                time.sleep(0.05)

            # PARTITION: the standby loses the writer; direct clients
            # don't.  The standby elects itself and promotes (gen 1).
            proxy.cut()
            assert standby.promoted.wait(timeout=30), "no promotion"

            # the isolated old writer accepts a DIVERGENT op meanwhile
            w_div = wallets[-1]
            r = direct.request("register", addr=w_div.address,
                               pubkey=w_div.public_bytes.hex(),
                               tag=_sign(w_div, "register", 0, b""))
            assert r["ok"], r
            assert srv.ledger.log_size() == size_before + 1
            assert standby.ledger.log_op(size_before) != \
                srv.ledger.log_op(size_before)      # genuine fork

            # HEAL, phase 1 — a fenced client WITHOUT evidence meets the
            # stale writer: client-side fencing rejects the reply and
            # rotates; the writer is NOT demoted (no DoS, no evidence)
            promoted_ep = (standby.host, standby.port)
            informed = FailoverClient([(srv.host, srv.port), promoted_ep],
                                      timeout_s=10.0)
            informed.gen = 1            # saw the promotion, lost the proof
            r = informed.request("info")
            assert r["gen"] == 1        # answered by the PROMOTED writer
            assert not srv.fenced.is_set()
            # ... and the reply carried the evidence, learned retroactively
            assert informed.gen_ev is not None

            # HEAL, phase 2 — the same client retries against the stale
            # writer, now WITH evidence: the writer verifies and demotes
            informed._cur = 0
            informed.close()
            r2 = informed.request("info")
            assert r2["gen"] == 1
            assert srv.fenced.wait(timeout=10), "stale writer not fenced"

            # exactly one surviving history: the promoted chain.  The
            # divergent client re-registers against it idempotently.
            r3 = informed.request("register", addr=w_div.address,
                                  pubkey=w_div.public_bytes.hex(),
                                  tag=_sign(w_div, "register", 0, b""))
            assert r3["ok"] or r3["status"] == "DUPLICATE"
            assert standby.ledger.verify_log()
            # old writer refuses all connections now (fenced is set just
            # before the socket closes — poll past that window)
            deadline = time.monotonic() + 10
            while True:
                try:
                    probe = CoordinatorClient(srv.host, srv.port,
                                              timeout_s=2.0)
                    probe.close()
                except (ConnectionError, OSError):
                    break
                assert time.monotonic() < deadline, \
                    "stale writer still accepting connections"
                time.sleep(0.05)
        finally:
            informed.close()
            direct.close()
            standby.stop()
            srv.close()


class TestQuorumAck:
    """Quorum-ack replication (the PBFT-commit analogue, CP flavor): with
    quorum=Q the writer acknowledges a storage mutation only after >= Q
    subscribers confirmed applying it — an acknowledged op provably
    survives the writer's death."""

    def test_acknowledged_op_is_on_the_standby(self):
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python",
                           quorum=1, quorum_timeout_s=10.0)
        srv.start()
        standby = Standby(CFG, [(srv.host, srv.port), ("127.0.0.1", 0)], 1,
                          heartbeat_s=0.3, stall_timeout_s=60.0,
                          require_auth=False, ledger_backend="python")
        standby.endpoints[1] = (standby.host, standby.port)
        threading.Thread(target=standby.run, daemon=True).start()
        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        c = CoordinatorClient(srv.host, srv.port, timeout_s=15.0)
        try:
            # wait for the standby's subscription to land
            deadline = time.monotonic() + 10
            while not srv._sub_acked:
                assert time.monotonic() < deadline, "standby never followed"
                time.sleep(0.05)
            for i in range(CFG.client_num):
                r = c.request("register", addr=f"0x{i:040x}")
                assert r["ok"], r
                # THE guarantee: the op is already applied on the standby
                # at the moment the client sees ok — no polling window
                assert standby.ledger.log_size() >= srv.ledger.log_size()
        finally:
            c.close()
            standby.stop()
            srv.close()

    def test_no_quorum_means_replication_timeout_then_retry_succeeds(self):
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python",
                           quorum=1, quorum_timeout_s=0.5)
        srv.start()
        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        c = CoordinatorClient(srv.host, srv.port, timeout_s=15.0)
        standby = None
        try:
            r = c.request("register", addr="0x" + "01" * 20)
            assert not r["ok"] and r["status"] == "REPLICATION_TIMEOUT", r
            # the op IS in the local chain (durability was withheld, not
            # the mutation) — a later follower replicates it, after which
            # the retry reports the op as present
            assert srv.ledger.num_registered == 1
            standby = Standby(CFG, [(srv.host, srv.port),
                                    ("127.0.0.1", 0)], 1,
                              heartbeat_s=0.3, stall_timeout_s=60.0,
                              require_auth=False, ledger_backend="python")
            standby.endpoints[1] = (standby.host, standby.port)
            threading.Thread(target=standby.run, daemon=True).start()
            deadline = time.monotonic() + 15
            while True:
                r2 = c.request("register", addr="0x" + "01" * 20)
                if r2["status"] == "ALREADY_REGISTERED":
                    break               # rejected-but-in == progress
                assert time.monotonic() < deadline, r2
                time.sleep(0.2)
            while standby.ledger.num_registered < 1:
                assert time.monotonic() < deadline, "standby never caught up"
                time.sleep(0.1)
        finally:
            c.close()
            if standby is not None:
                standby.stop()
            srv.close()

    def test_anonymous_acker_cannot_fake_quorum(self):
        """Round-5 review: quorum durability must not be voidable by an
        anonymous subscriber blasting inflated acks.  With standby keys
        provisioned, only SIGNED standby subscriptions count — and acks
        are clamped to ops actually streamed."""
        import struct as _struct

        from bflc_demo_tpu.comm.identity import Wallet
        from bflc_demo_tpu.comm.ledger_service import (CoordinatorClient,
                                                       LedgerServer)
        from bflc_demo_tpu.comm.wire import send_msg
        sb_wallet = Wallet.from_seed(b"quorum-sb-1")
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python",
                           quorum=1, quorum_timeout_s=1.0,
                           standby_keys={1: sb_wallet.public_bytes})
        srv.start()
        c = CoordinatorClient(srv.host, srv.port, timeout_s=15.0)
        liar = None
        standby = None
        try:
            # an anonymous subscriber that acks everything, instantly
            liar = CoordinatorClient(srv.host, srv.port, timeout_s=5.0)
            send_msg(liar.sock, {"method": "subscribe", "from": 0})
            send_msg(liar.sock, {"ack": 10 ** 18})
            time.sleep(0.3)
            r = c.request("register", addr="0x" + "aa" * 20)
            assert r["status"] == "REPLICATION_TIMEOUT", r

            # a REAL standby (signed subscription) satisfies the quorum
            standby = Standby(CFG, [(srv.host, srv.port),
                                    ("127.0.0.1", 0)], 1,
                              heartbeat_s=0.3, stall_timeout_s=60.0,
                              require_auth=False, ledger_backend="python",
                              wallet=sb_wallet)
            standby.endpoints[1] = (standby.host, standby.port)
            threading.Thread(target=standby.run, daemon=True).start()
            deadline = time.monotonic() + 15
            while True:
                r2 = c.request("register", addr="0x" + "aa" * 20)
                if r2["status"] == "ALREADY_REGISTERED":
                    break               # replicated: rejected-but-in
                assert time.monotonic() < deadline, r2
                time.sleep(0.3)
        finally:
            c.close()
            if liar is not None:
                liar.close()
            if standby is not None:
                standby.stop()
            srv.close()

    def test_quorum_two_standbys(self):
        """Q=2: both standbys must ack before a mutation acknowledges —
        and once both follow, mutations go through."""
        from bflc_demo_tpu.comm.identity import Wallet
        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        w1, w2 = Wallet.from_seed(b"q2-sb-1"), Wallet.from_seed(b"q2-sb-2")
        keys = {1: w1.public_bytes, 2: w2.public_bytes}
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python",
                           quorum=2, quorum_timeout_s=1.0,
                           standby_keys=keys)
        srv.start()
        eps = [(srv.host, srv.port), ("127.0.0.1", 0), ("127.0.0.1", 0)]
        sbs = []
        c = CoordinatorClient(srv.host, srv.port, timeout_s=15.0)
        try:
            sb1 = Standby(CFG, list(eps), 1, heartbeat_s=0.3,
                          stall_timeout_s=60.0, require_auth=False,
                          ledger_backend="python", wallet=w1,
                          standby_keys=keys)
            sbs.append(sb1)
            threading.Thread(target=sb1.run, daemon=True).start()
            deadline = time.monotonic() + 10
            while not srv._sub_acked:
                assert time.monotonic() < deadline
                time.sleep(0.05)
            # only ONE eligible follower: Q=2 not met
            r = c.request("register", addr="0x" + "bb" * 20)
            assert r["status"] == "REPLICATION_TIMEOUT", r
            sb2 = Standby(CFG, list(eps), 2, heartbeat_s=0.3,
                          stall_timeout_s=60.0, require_auth=False,
                          ledger_backend="python", wallet=w2,
                          standby_keys=keys)
            sbs.append(sb2)
            threading.Thread(target=sb2.run, daemon=True).start()
            deadline = time.monotonic() + 15
            while True:
                r2 = c.request("register", addr="0x" + "bb" * 20)
                if r2["status"] == "ALREADY_REGISTERED":
                    break
                assert time.monotonic() < deadline, r2
                time.sleep(0.3)
            for sb in sbs:
                while sb.ledger.num_registered < 1:
                    assert time.monotonic() < deadline
                    time.sleep(0.1)
        finally:
            c.close()
            for sb in sbs:
                sb.stop()
            srv.close()

    def test_skipped_blob_is_not_certified_by_a_later_ack(self):
        """ADVICE r5 (medium): acks are CUMULATIVE watermarks, so when the
        blob fetch for upload op i transiently fails, acking any later op
        j>i would silently certify op i as quorum-durable WITHOUT its
        payload.  The standby must clamp every outgoing ack below the
        lowest unmirrored upload index and retry the fetch — on the
        pre-fix code this test fails at the REPLICATION_TIMEOUT
        assertions (the later upload's ack covers the skipped one)."""
        import hashlib as hl

        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        from bflc_demo_tpu.ledger.tool import decode_op

        class _FlakyBlobStandby(Standby):
            """Injects transient blob-UNAVAILABILITY for chosen digests:
            both mirror paths — the fetch round-trip AND the op-stream
            piggyback (PR 3) — must fail, or the injected fault no
            longer models 'this blob cannot be obtained right now'."""

            def __init__(self, *a, **kw):
                self.fail_digests = set()       # payload-hash hex strings
                super().__init__(*a, **kw)

            def _failing(self, op_bytes) -> bool:
                if op_bytes and op_bytes[0] == self._UPLOAD_OPCODE:
                    try:
                        ph = decode_op(op_bytes).get("payload_hash")
                    except Exception:
                        ph = None
                    return ph in self.fail_digests
                return False

            def _mirror_upload_payload(self, op_bytes, ctl):
                if self._failing(op_bytes):
                    return False
                return super()._mirror_upload_payload(op_bytes, ctl)

            def _harvest_pushed_blob(self, msg, op_bytes):
                if self._failing(op_bytes):
                    return
                super()._harvest_pushed_blob(msg, op_bytes)

        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python",
                           quorum=1, quorum_timeout_s=1.5)
        srv.start()
        standby = _FlakyBlobStandby(
            CFG, [(srv.host, srv.port), ("127.0.0.1", 0)], 1,
            heartbeat_s=0.3, stall_timeout_s=60.0, require_auth=False,
            ledger_backend="python")
        standby.endpoints[1] = (standby.host, standby.port)
        threading.Thread(target=standby.run, daemon=True).start()
        c = CoordinatorClient(srv.host, srv.port, timeout_s=20.0)
        try:
            deadline = time.monotonic() + 10
            while not srv._sub_acked:
                assert time.monotonic() < deadline, "standby never followed"
                time.sleep(0.05)
            for i in range(CFG.client_num):
                assert c.request("register", addr=f"0x{i:040x}")["ok"]
            committee = set(c.request("committee")["committee"])
            trainers = [f"0x{i:040x}" for i in range(CFG.client_num)
                        if f"0x{i:040x}" not in committee]
            blob_a, blob_b = _delta_blob(1.0), _delta_blob(2.0)
            dig_a = hl.sha256(blob_a).digest()
            dig_b = hl.sha256(blob_b).digest()

            # upload A's blob fetch fails transiently on the standby
            standby.fail_digests.add(dig_a.hex())
            r = c.request("upload", addr=trainers[0], blob=blob_a.hex(),
                          hash=dig_a.hex(), n=10, cost=1.0, epoch=0)
            assert r["status"] == "REPLICATION_TIMEOUT", r

            # upload B mirrors fine; its ack must NOT cover A
            r = c.request("upload", addr=trainers[1], blob=blob_b.hex(),
                          hash=dig_b.hex(), n=11, cost=1.0, epoch=0)
            assert r["status"] == "REPLICATION_TIMEOUT", \
                f"later upload's ack leaked past the unmirrored blob: {r}"
            # the A retry must STILL not report durable (pre-fix it
            # answers DUPLICATE here because B's watermark covered it)
            r = c.request("upload", addr=trainers[0], blob=blob_a.hex(),
                          hash=dig_a.hex(), n=10, cost=1.0, epoch=0)
            assert r["status"] == "REPLICATION_TIMEOUT", \
                f"skipped upload certified without its payload: {r}"
            assert standby._blobs.get(dig_a) is None

            # the transient failure heals -> the standby retries the
            # fetch, the clamp lifts, and the acks catch up cumulatively
            standby.fail_digests.clear()
            deadline = time.monotonic() + 20
            while True:
                r = c.request("upload", addr=trainers[0],
                              blob=blob_a.hex(), hash=dig_a.hex(), n=10,
                              cost=1.0, epoch=0)
                if r["status"] == "DUPLICATE":
                    break               # durably replicated: rejected-but-in
                assert time.monotonic() < deadline, r
                time.sleep(0.3)
            assert standby._blobs.get(dig_a) == blob_a
            assert standby._blobs.get(dig_b) == blob_b
        finally:
            c.close()
            standby.stop()
            srv.close()

    def test_acknowledged_upload_payload_is_on_the_standby(self):
        """Round-5 review: the ack must cover the upload's PAYLOAD, not
        just the op — an acknowledged uploader never retries, so a
        promoted standby missing the blob would wedge the round.  At the
        moment the client sees ok, the standby holds the blob."""
        import hashlib as hl

        from bflc_demo_tpu.comm.ledger_service import CoordinatorClient
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python",
                           quorum=1, quorum_timeout_s=10.0)
        srv.start()
        standby = Standby(CFG, [(srv.host, srv.port), ("127.0.0.1", 0)], 1,
                          heartbeat_s=0.3, stall_timeout_s=60.0,
                          require_auth=False, ledger_backend="python")
        standby.endpoints[1] = (standby.host, standby.port)
        threading.Thread(target=standby.run, daemon=True).start()
        c = CoordinatorClient(srv.host, srv.port, timeout_s=20.0)
        try:
            deadline = time.monotonic() + 10
            while not srv._sub_acked:
                assert time.monotonic() < deadline, "standby never followed"
                time.sleep(0.05)
            for i in range(CFG.client_num):
                assert c.request("register", addr=f"0x{i:040x}")["ok"]
            committee = set(c.request("committee")["committee"])
            trainer = next(f"0x{i:040x}" for i in range(CFG.client_num)
                           if f"0x{i:040x}" not in committee)
            blob = _delta_blob(1.5)
            digest = hl.sha256(blob).digest()
            r = c.request("upload", addr=trainer, blob=blob.hex(),
                          hash=digest.hex(), n=10, cost=1.0, epoch=0)
            assert r["ok"], r
            # acknowledged => the payload is already mirrored
            assert standby._blobs.get(digest) == blob
        finally:
            c.close()
            standby.stop()
            srv.close()
