"""Causal op tracing (bflc_demo_tpu.obs.trace): recorder + context
semantics, span-file durability, offline reassembly (multi-trace links,
critical path, stragglers, fault attribution), and one end-to-end traced
federation where a chaos delay fault targeting ONE client must be
attributed to that client's segments by the report.
"""

import threading
import time

import numpy as np
import pytest

from bflc_demo_tpu.obs import trace as obs_trace
from bflc_demo_tpu.obs.trace import (SpanRecorder, assemble_traces,
                                     critical_path, format_traceparent,
                                     gather_spans, load_spans,
                                     parse_traceparent, round_reports,
                                     segment_stats, trace_role_classes)


@pytest.fixture
def rec():
    r = SpanRecorder()
    r.enabled = True
    r.sample = 1.0
    r.role = "tester"
    return r


class TestRecorder:
    def test_disabled_recorder_records_and_propagates_nothing(self):
        r = SpanRecorder()
        with r.start_trace("root") as sp:
            sp["k"] = 1                 # the sink accepts writes
            with r.span("child"):
                assert r.current_traceparent() is None
        assert list(r._ring) == []

    def test_null_span_is_a_shared_singleton(self):
        """Zero-allocation contract for the off path: every disabled
        entry point returns the SAME object."""
        r = SpanRecorder()
        assert r.span("a") is r.span("b") is r.start_trace("c") \
            is r.span_from(None, "d")

    def test_sample_zero_keeps_roots_unsampled(self, rec):
        rec.sample = 0.0
        with rec.start_trace("root"):
            assert rec.current_traceparent() is None
        assert list(rec._ring) == []

    def test_root_child_linkage_and_context_restore(self, rec):
        with rec.start_trace("root", epoch=3):
            tp_root = rec.current_traceparent()
            with rec.span("child"):
                tp_child = rec.current_traceparent()
            assert rec.current_traceparent() == tp_root
        assert rec.current_traceparent() is None
        spans = {s["name"]: s for s in rec._ring}
        root, child = spans["root"], spans["child"]
        assert root["parent"] is None and root["epoch"] == 3
        assert child["trace"] == root["trace"]
        assert child["parent"] == root["span"]
        assert parse_traceparent(tp_root) == (root["trace"],
                                              root["span"])
        assert parse_traceparent(tp_child) == (child["trace"],
                                               child["span"])
        assert root["t0"] <= child["t0"] <= child["t1"] <= root["t1"]

    def test_span_without_ambient_context_is_noop(self, rec):
        with rec.span("orphan"):
            pass
        assert list(rec._ring) == []

    def test_span_from_remote_parent_and_links(self, rec):
        tp = format_traceparent("ab" * 16, "cd" * 8)
        link = format_traceparent("11" * 16, "22" * 8)
        with rec.span_from(tp, "serve", links=[link, None, "garbage"],
                           method="upload"):
            pass
        s = list(rec._ring)[-1]
        assert s["trace"] == "ab" * 16 and s["parent"] == "cd" * 8
        assert s["links"] == ["11" * 16]
        assert s["method"] == "upload"

    def test_span_from_garbage_parent_without_links_is_noop(self, rec):
        assert rec.span_from("not-a-traceparent", "x") is \
            rec.span_from(None, "y")
        with rec.span_from(17, "z"):
            pass
        assert list(rec._ring) == []

    def test_span_from_links_only_roots_in_first_link(self, rec):
        """A monitor-sweep certify has no ambient parent but still
        belongs to the traces it served."""
        link = format_traceparent("33" * 16, "44" * 8)
        with rec.span_from(None, "bft.vote_rtt", links=[link]):
            pass
        s = list(rec._ring)[-1]
        assert s["trace"] == "33" * 16 and s["parent"] is None

    def test_contexts_are_thread_local(self, rec):
        seen = {}

        def other():
            seen["tp"] = rec.current_traceparent()

        with rec.start_trace("root"):
            t = threading.Thread(target=other)
            t.start()
            t.join()
        assert seen["tp"] is None

    def test_trace_legacy_env_pins_install_off(self, tmp_path,
                                               monkeypatch):
        monkeypatch.setenv("BFLC_TRACE_LEGACY", "1")
        r = SpanRecorder()
        r.install("w", str(tmp_path), sample=1.0)
        assert not r.enabled

    def test_install_flush_load_roundtrip_with_wall_anchor(
            self, tmp_path):
        r = SpanRecorder()
        r.install("w", str(tmp_path), sample=1.0)
        try:
            assert r.enabled
            with r.start_trace("root", epoch=1):
                time.sleep(0.01)
            assert r.flush("test")
            spans = load_spans(str(tmp_path / "w.spans.jsonl"))
        finally:
            r.close()
        assert len(spans) == 1
        s = spans[0]
        assert s["role"] == "w" and s["name"] == "root"
        # monotonic t0/t1 were re-anchored onto the wall clock
        assert abs(s["t0"] - time.time()) < 60.0
        assert s["t1"] - s["t0"] >= 0.008
        assert gather_spans(str(tmp_path)) == spans


def _mk(trace, role, name, t0, t1, parent=None, links=None, **attrs):
    s = {"trace": trace, "span": f"{role}-{name}-{t0}",
         "parent": parent, "role": role, "name": name,
         "t0": float(t0), "t1": float(t1), **attrs}
    if links:
        s["links"] = links
    return s


class TestReassembly:
    def test_linked_span_lands_in_every_trace(self):
        spans = [_mk("A", "client-0", "client.upload_op", 0, 5),
                 _mk("B", "client-1", "client.upload_op", 0, 6),
                 _mk("A", "validator-0", "vote_batch", 2, 3,
                     links=["A", "B"])]
        traces = assemble_traces(spans)
        assert {s["name"] for s in traces["A"]} == {"client.upload_op",
                                                    "vote_batch"}
        assert {s["name"] for s in traces["B"]} == {"client.upload_op",
                                                    "vote_batch"}
        assert trace_role_classes(traces["B"]) == ["client",
                                                   "validator"]

    def test_critical_path_partitions_the_interval_exactly(self):
        spans = [_mk("A", "client-0", "client.upload_op", 0, 10),
                 _mk("A", "client-0", "train", 1, 4),
                 _mk("A", "writer", "serve", 6, 8, method="upload")]
        segs = critical_path(spans, 0.0, 10.0)
        assert sum(d for _l, d in segs) == pytest.approx(10.0)
        labels = [l for l, _d in segs]
        assert labels == ["client-0:client.upload_op", "client-0:train",
                          "client-0:client.upload_op",
                          "writer:serve[upload]",
                          "client-0:client.upload_op"]
        by = dict(segs[1:2])
        assert by["client-0:train"] == pytest.approx(3.0)

    def test_uncovered_time_becomes_wait(self):
        spans = [_mk("A", "client-0", "train", 2, 4)]
        segs = critical_path(spans, 0.0, 6.0)
        assert segs == [("(wait)", pytest.approx(2.0)),
                        ("client-0:train", pytest.approx(2.0)),
                        ("(wait)", pytest.approx(2.0))]

    def _round_spans(self):
        # two upload traces in epoch 2: client-1 arrives 0.8s late
        return [
            _mk("A", "client-0", "client.upload_op", 0.0, 1.0, epoch=2),
            _mk("A", "client-0", "upload", 0.4, 0.6, parent="p"),
            _mk("A", "writer", "serve", 0.45, 0.55, method="upload"),
            _mk("B", "client-1", "client.upload_op", 0.0, 2.0, epoch=2),
            _mk("B", "client-1", "upload", 1.0, 1.4, parent="p"),
            _mk("B", "writer", "serve", 1.25, 1.35, method="upload"),
        ]

    def test_round_report_wall_stragglers_and_coverage(self):
        reps = round_reports(self._round_spans())
        assert len(reps) == 1
        rep = reps[0]
        assert rep["epoch"] == 2
        assert rep["wall_s"] == pytest.approx(2.0)
        # segment partition: totals sum to the wall exactly
        assert sum(rep["by_label"].values()) == pytest.approx(2.0)
        assert rep["covered_frac"] == pytest.approx(1.0)
        # straggler ranking off writer-admission arrival
        assert rep["stragglers"][0][0] == "client-1"
        assert rep["stragglers"][0][1] == pytest.approx(0.8)
        assert rep["stragglers"][1] == ("client-0", pytest.approx(0.0))

    def test_fault_attribution_names_the_active_segment(self):
        faults = [{"t": 1.3, "kind": "delay", "target": "client-1"}]
        rep = round_reports(self._round_spans(), faults=faults)[0]
        assert rep["faults"] == [{"kind": "delay", "target": "client-1",
                                  "landed_in": "writer:serve[upload]"}]
        txt = obs_trace.format_round_report(rep)
        assert "critical path" in txt and "delay client-1" in txt

    def test_segment_stats_aggregate_role_classes(self):
        reps = round_reports(self._round_spans())
        stats = segment_stats(reps)
        # client-0 and client-1 fold into one client: row
        assert "client:client.upload_op" in stats
        st = stats["client:client.upload_op"]
        assert st["rounds"] == 1 and st["p50_s"] > 0


class TestEndToEndTraced:
    """The acceptance drill: a traced config-1-shaped federation (scaled
    to tier-1 budget) with a chaos DELAY fault pinned on client-1's link
    to the writer.  Every committed upload op must reassemble into a
    trace spanning client + writer + validator + standby; the per-round
    critical path must partition the round wall time; and the straggler
    ranking must finger client-1."""

    def test_traced_federation_reassembles_and_attributes_delay(
            self, tmp_path):
        from bflc_demo_tpu.chaos.schedule import FaultSchedule, WireWindow
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        from bflc_demo_tpu.data import iid_shards, load_occupancy
        from bflc_demo_tpu.protocol.constants import ProtocolConfig

        cfg = ProtocolConfig(client_num=4, comm_count=2,
                             aggregate_count=2, needed_update_count=2,
                             learning_rate=0.05, batch_size=32,
                             local_epochs=2).validate()
        xtr, ytr, xte, yte = load_occupancy()
        shards = iid_shards(np.asarray(xtr), np.asarray(ytr),
                            cfg.client_num)
        sched = FaultSchedule(11, duration_s=150.0, n_clients=4,
                              n_standbys=1, n_validators=2,
                              profile="light")
        sched.events = []               # no kills: the fault under test
        sched.wire_windows = {          # is a pure targeted delay
            "client-1": [WireWindow(0.0, 300.0, "delay", ("writer",),
                                    p=1.0, delay_ms=250.0)],
        }
        tdir = str(tmp_path / "telemetry")
        res = run_federated_processes(
            "make_softmax_regression", shards,
            (np.asarray(xte), np.asarray(yte)), cfg,
            rounds=3, standbys=1, bft_validators=2,
            chaos_schedule=sched, telemetry_dir=tdir,
            trace_sample=1.0, timeout_s=300.0)
        assert res.rounds_completed >= 3
        assert res.chaos_report is not None
        assert res.chaos_report["violations"] == []
        tel = res.telemetry_report
        assert tel is not None and tel["spans"], tel

        spans = gather_spans(tdir)
        roles = {s["role"] for s in spans}
        assert any(r.startswith("client-") for r in roles), roles
        assert "writer" in roles
        assert any(r.startswith("validator-") for r in roles), roles
        assert any(r.startswith("standby-") for r in roles), roles

        # every committed upload op reassembles into a trace crossing
        # >= 4 role classes (client, writer, validator, standby)
        traces = assemble_traces(spans)
        upload_traces = {
            tid: ts for tid, ts in traces.items()
            if any(s["name"] == "client.upload_op" for s in ts)}
        assert upload_traces
        four_role = [tid for tid, ts in upload_traces.items()
                     if {"client", "writer", "validator", "standby"}
                     <= set(trace_role_classes(ts))]
        assert four_role, {
            tid: trace_role_classes(ts)
            for tid, ts in upload_traces.items()}

        # per-round critical path: the segment partition must account
        # for the round wall time (exact by construction; the 10%
        # acceptance bar with slack for float noise)
        reports = round_reports(spans)
        assert reports, "no rounds reassembled"
        for rep in reports:
            assert sum(d for _l, d in rep["segments"]) == \
                pytest.approx(rep["wall_s"], rel=0.10)
            assert rep["covered_frac"] > 0.5, rep

        # the chaos delay fault pinned on client-1 shows up as the
        # straggler: in at least one round client-1 tops the upload-lag
        # ranking with a lag the 250 ms/frame delay explains
        tops = [rep["stragglers"][0] for rep in reports
                if rep["stragglers"]]
        assert any(role == "client-1" and lag > 0.2
                   for role, lag in tops), tops

        # the report tooling renders end to end
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        try:
            import fleet_top
            import trace_report
        finally:
            sys.path.pop(0)
        report = trace_report.build_report(tdir)
        assert report["n_traces"] >= len(upload_traces)
        txt = trace_report.render(report)
        assert "critical path" in txt and "stragglers" in txt
        from bflc_demo_tpu.obs.collector import load_timeline
        tl = load_timeline(tel["jsonl"])
        timeline_txt = fleet_top.render_timeline(tl, spans_dir=tdir)
        assert "critical paths" in timeline_txt
