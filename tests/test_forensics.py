"""Round forensics & SLO plane (bflc_demo_tpu.obs.timeline /
obs.slo; ISSUE 14): burn-rate window math, the streaming joiner's
tolerance of shuffled/truncated/mixed-version artifact streams,
alerts.jsonl SIGKILL durability, per-leaf health naming, the
verdict-gated chaos_soak exits, and the end-to-end forensics drill —
a scripted heavytail-straggler + sign-flip campaign at config-1
geometry raises exactly the latency burn-rate alert and the
health-budget alert within 2 rounds of onset (zero false alerts on the
clean leg), obs_query reports a critical-path partition that sums to
round wall and names the faulted role, and committed model hashes are
byte-identical armed vs BFLC_SLO_LEGACY=1."""

import hashlib
import json
import os
import random
import signal
import subprocess
import sys
import tarfile
import time

import numpy as np
import pytest

from bflc_demo_tpu.meshagg.stats import per_leaf_stats
from bflc_demo_tpu.obs import health as obs_health
from bflc_demo_tpu.obs import metrics as obs_metrics
from bflc_demo_tpu.obs import slo as obs_slo
from bflc_demo_tpu.obs.collector import FleetCollector
from bflc_demo_tpu.obs.health import HealthMonitor
from bflc_demo_tpu.obs.slo import SLOEngine, SLOSpec, burn_rate
from bflc_demo_tpu.obs.timeline import (RoundForensics, RoundTimeline,
                                        hist_delta, load_round_timeline,
                                        round_of_scrape)
from bflc_demo_tpu.protocol.constants import DEFAULT_PROTOCOL
from bflc_demo_tpu.utils.serialization import pack_pytree

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


def _tool(name):
    sys.path.insert(0, TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


@pytest.fixture
def enabled_registry():
    saved_enabled = obs_metrics.REGISTRY.enabled
    saved_role = obs_metrics.REGISTRY.role
    obs_metrics.REGISTRY.enabled = True
    obs_metrics.REGISTRY.role = "writer"
    try:
        yield obs_metrics.REGISTRY
    finally:
        obs_metrics.REGISTRY.enabled = saved_enabled
        obs_metrics.REGISTRY.role = saved_role


# ------------------------------------------------------ burn-rate math
class TestBurnRateMath:
    def test_burn_rate_is_breach_fraction_over_budget(self):
        assert burn_rate(0, 5, 0.1) == 0.0
        assert burn_rate(1, 5, 0.1) == pytest.approx(2.0)
        assert burn_rate(2, 5, 0.1) == pytest.approx(4.0)
        assert burn_rate(5, 25, 0.1) == pytest.approx(2.0)
        # pure fraction/budget math; the ENGINE passes the configured
        # window length so young windows are padded with healthy
        # history (uniform onset sensitivity)
        assert burn_rate(2, 2, 0.1) == pytest.approx(10.0)
        # degenerate inputs never divide by zero
        assert burn_rate(3, 0, 0.1) == 0.0
        assert burn_rate(3, 5, 0.0) == 0.0

    def _engine(self, **kw):
        spec = SLOSpec("lat", "round_wall_s", 1.0, **kw)
        return SLOEngine([spec]), spec

    def test_single_isolated_breach_never_pages(self):
        eng, _ = self._engine()
        alerts = []
        for ep, wall in enumerate([0.5, 0.5, 9.0, 0.5, 0.5, 0.5]):
            alerts += eng.observe_round(
                {"epoch": ep, "round_wall_s": wall})
        assert alerts == []
        rep = eng.report()["slos"]["lat"]
        assert rep["breaches"] == 1 and rep["alerts"] == 0

    def test_two_consecutive_breaches_page_once(self):
        eng, _ = self._engine()
        alerts = []
        for ep, wall in enumerate([0.5, 0.5, 9.0, 9.0, 9.0, 9.0]):
            alerts += eng.observe_round(
                {"epoch": ep, "round_wall_s": wall})
        # pages at the SECOND breaching round, latches thereafter
        assert len(alerts) == 1
        assert alerts[0]["epoch"] == 3
        assert alerts[0]["slo"] == "lat"
        assert alerts[0]["burn_fast"] >= 3.0
        assert alerts[0]["burn_slow"] >= 0.6

    def test_unlatch_then_new_excursion_repages(self):
        eng, _ = self._engine()
        walls = ([0.5] * 3 + [9.0, 9.0]        # excursion 1 -> page
                 + [0.5] * 6                   # cool: fast burn -> 0
                 + [9.0, 9.0])                 # excursion 2 -> page
        alerts = []
        for ep, wall in enumerate(walls):
            alerts += eng.observe_round(
                {"epoch": ep, "round_wall_s": wall})
        assert [a["epoch"] for a in alerts] == [4, 12]

    def test_none_signal_is_skipped_not_breached(self):
        eng, _ = self._engine()
        for ep in range(10):
            assert eng.observe_round({"epoch": ep,
                                      "round_wall_s": None}) == []
        assert eng.report()["slos"]["lat"]["judged"] == 0

    def test_ge_objective_direction(self):
        spec = SLOSpec("cov", "scrape_coverage", 0.9, op=">=",
                       budget=0.1)
        eng = SLOEngine([spec])
        alerts = []
        for ep, cov in enumerate([1.0, 1.0, 0.5, 0.5, 1.0]):
            alerts += eng.observe_round(
                {"epoch": ep, "scrape_coverage": cov})
        assert len(alerts) == 1 and alerts[0]["epoch"] == 3

    def test_alert_embeds_round_context(self, tmp_path):
        path = str(tmp_path / "alerts.jsonl")
        eng = SLOEngine([SLOSpec("lat", "round_wall_s", 1.0)],
                        jsonl_path=path)
        ctx = {"epoch": 3, "faults": [{"kind": "delay"}],
               "health_verdict": "warn"}
        for ep, wall in enumerate([0.5, 0.5, 9.0, 9.0]):
            eng.observe_round({"epoch": ep, "round_wall_s": wall},
                              context=ctx if ep == 3 else None)
        alerts = obs_slo.load_alerts(path)
        assert len(alerts) == 1
        assert alerts[0]["context"]["faults"] == [{"kind": "delay"}]
        assert alerts[0]["windows"]["fast"][-2:] == [1, 1]


class TestNotifyCmd:
    """--notify-cmd alert routing (ISSUE 15 satellite): one operator
    command per alert with the alerts.jsonl record on stdin,
    failure-isolated and counted."""

    def _page_twice(self, eng):
        for ep, wall in enumerate([0.5, 0.5, 9.0, 9.0]):
            eng.observe_round({"epoch": ep, "round_wall_s": wall})

    def _wait(self, cond, timeout_s=10.0):
        import time as _t
        t0 = _t.monotonic()
        while not cond() and _t.monotonic() - t0 < timeout_s:
            _t.sleep(0.05)
        assert cond()

    def test_alert_record_reaches_command_stdin(self, tmp_path):
        import json as _json
        import sys
        out = tmp_path / "paged.json"
        cmd = (f"{sys.executable} -c \"import sys; "
               f"open({str(out)!r}, 'w').write(sys.stdin.read())\"")
        eng = SLOEngine([SLOSpec("lat", "round_wall_s", 1.0)],
                        notify_cmd=cmd)
        self._page_twice(eng)
        assert eng.notified == 1
        self._wait(lambda: out.exists() and out.read_text().strip())
        rec = _json.loads(out.read_text())
        assert rec["type"] == "slo_alert" and rec["slo"] == "lat"
        assert rec["epoch"] == 3
        self._wait(lambda: eng.notify_failures == 0 and
                   eng.report()["notified"] == 1)

    def test_broken_command_is_counted_never_raised(self):
        eng = SLOEngine([SLOSpec("lat", "round_wall_s", 1.0)],
                        notify_cmd="false")
        self._page_twice(eng)          # a failing pager must not kill
        self._wait(lambda: eng.notify_failures == 1)
        assert eng.report()["notify_failures"] == 1

    def test_env_arming(self, monkeypatch):
        monkeypatch.setenv("BFLC_SLO_NOTIFY_CMD", "true")
        eng = SLOEngine([SLOSpec("lat", "round_wall_s", 1.0)])
        assert eng.notify_cmd == "true"
        monkeypatch.delenv("BFLC_SLO_NOTIFY_CMD")
        assert SLOEngine([]).notify_cmd == ""


# ------------------------------------------------- alerts durability
class TestAlertsDurability:
    def test_sigkill_leaves_parseable_alerts_jsonl(self, tmp_path):
        """The flight recorder's durability contract for alerts.jsonl:
        tmp-then-rename per alert, so a SIGKILL mid-campaign leaves a
        complete, parseable artifact."""
        path = tmp_path / "alerts.jsonl"
        code = f"""
import itertools, time
from bflc_demo_tpu.obs import slo
eng = slo.SLOEngine(
    [slo.SLOSpec("lat", "round_wall_s", 1.0, budget=1.0,
                 fast_window=1, slow_window=1, burn_fast=1.0,
                 burn_slow=0.0)],
    jsonl_path={str(path)!r})
for ep, wall in enumerate(itertools.cycle([9.0, 0.0])):
    eng.observe_round({{"epoch": ep, "round_wall_s": wall}})
    time.sleep(0.01)
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        p = subprocess.Popen([sys.executable, "-c", code], env=env,
                             cwd=os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__))))
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if len(obs_slo.load_alerts(str(path))) >= 2:
                break
            time.sleep(0.05)
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=10)
        alerts = obs_slo.load_alerts(str(path))
        assert len(alerts) >= 2
        # every line is a complete record (no torn tail possible)
        with open(path) as fh:
            for line in fh:
                rec = json.loads(line)
                assert rec["type"] == "slo_alert"
                assert rec["slo"] == "lat"


# ----------------------------------------------------- timeline joiner
def _mk_stream(rounds=5, t0=1000.0, dt=2.0, stamp_epoch=True,
               tag=True):
    """A synthetic collector stream + health records: one commit note
    and one post-commit scrape per round, a fault inside round 2."""
    recs, health = [], []
    for r in range(rounds):
        t_commit = t0 + (r + 1) * dt
        recs.append({"type": "note", "t": t_commit,
                     "name": "round_commit", "epoch": r,
                     "acc": 0.8 + 0.01 * r})
        scrape = {"type": "scrape", "t": t_commit + 0.1,
                  "tag": (f"round-{r}" if tag else None),
                  "roles": {"writer": {"metrics": {
                      "health_verdict": {"type": "gauge", "samples": [
                          {"labels": {}, "value": 0.0}]}}}},
                  "coverage": {"answered": 3, "expected": 4,
                               "missing": ["client-1"]}}
        if stamp_epoch:
            scrape["epoch"] = r + 1
        recs.append(scrape)
        health.append({"type": "health_round", "t": t_commit - 0.01,
                       "role": "writer", "epoch": r, "verdict": "ok",
                       "n": 3, "flagged": 0, "senders": []})
    recs.append({"type": "fault", "t": t0 + 2 * dt + 0.7,
                 "kind": "delay", "target": "client-3",
                 "source": "chaos"})
    return recs, health

class TestTimelineJoiner:
    def test_round_of_scrape_semantics(self):
        # stamped epoch E describes round E-1; tag names the round
        assert round_of_scrape({"epoch": 5, "tag": "round-9"}) == 4
        assert round_of_scrape({"tag": "round-9"}) == 9
        assert round_of_scrape({"epoch": 0}) is None
        assert round_of_scrape({"tag": "fleet_up"}) is None
        assert round_of_scrape({}) is None

    def _build(self, recs, health, order=None):
        tl = RoundTimeline()
        idx = list(range(len(recs)))
        if order is not None:
            order.shuffle(idx)
        for i in idx:
            tl.observe(recs[i])
        for h in health:
            tl.observe_health(h)
        return tl

    def test_joined_round_record(self):
        recs, health = _mk_stream()
        tl = self._build(recs, health)
        assert tl.rounds() == [0, 1, 2, 3, 4]
        rec = tl.round_record(2)
        assert rec["epoch"] == 2
        assert rec["wall_s"] == pytest.approx(2.0, abs=1e-6)
        assert rec["health_verdict"] == "ok"
        # the fault at +0.7s into round 2's window is assigned to it
        assert [f["target"] for f in rec["faults"]] == ["client-3"]
        assert tl.round_record(1)["faults"] == []
        assert tl.round_record(3)["faults"] == []
        assert rec["scrape_coverage"] == pytest.approx(0.75)
        assert rec["epoch_stamped"] is True
        assert rec["commit"]["acc"] == pytest.approx(0.82)

    def test_shuffled_streams_join_identically(self):
        recs, health = _mk_stream()
        tl_a = self._build(recs, health)
        for seed in (1, 2, 3):
            tl_b = self._build(recs, health,
                               order=random.Random(seed))
            for r in tl_a.rounds():
                ra, rb = tl_a.round_record(r), tl_b.round_record(r)
                ra["faults"] = sorted(ra["faults"],
                                      key=lambda f: f.get("t", 0))
                rb["faults"] = sorted(rb["faults"],
                                      key=lambda f: f.get("t", 0))
                assert ra == rb, f"round {r} diverged under seed {seed}"

    def test_mixed_version_streams_degrade_gracefully(self):
        # pre-PR-13 artifacts: no epoch stamp -> tag fallback
        recs, health = _mk_stream(stamp_epoch=False)
        tl = self._build(recs, health)
        assert tl.rounds() == [0, 1, 2, 3, 4]
        assert tl.round_record(2)["wall_s"] == pytest.approx(2.0)
        assert tl.round_record(2)["epoch_stamped"] is None
        # neither stamp nor tag: scrapes unkeyed, commits still join
        recs2, health2 = _mk_stream(stamp_epoch=False, tag=False)
        tl2 = self._build(recs2, health2)
        assert tl2.round_record(2)["scrapes"] == 0
        assert tl2.round_record(2)["wall_s"] == pytest.approx(2.0)
        # unknown record types from the future are skipped, not raised
        tl2.observe({"type": "v99_hologram", "t": 1.0})
        tl2.observe({"not_even": "typed"})
        tl2.observe("garbage")          # type: ignore[arg-type]

    def test_truncated_and_garbled_artifacts_load(self, tmp_path):
        recs, health = _mk_stream(rounds=3)
        mpath = tmp_path / "metrics.jsonl"
        with open(mpath, "w") as fh:
            for rec in recs:
                fh.write(json.dumps(rec) + "\n")
        with open(tmp_path / "writer.health.jsonl", "w") as fh:
            for h in health:
                fh.write(json.dumps(h) + "\n")
            fh.write('{"type": "health_round", "epo')   # torn tail
        # tear metrics.jsonl mid-record too
        raw = mpath.read_bytes()
        mpath.write_bytes(raw[:-25])
        tl = load_round_timeline(str(tmp_path))
        assert tl.rounds() == [0, 1, 2]
        assert (tmp_path / "alerts.jsonl").exists() is False
        assert tl.round_record(1)["health_verdict"] == "ok"

    def test_hist_delta_brackets_one_round(self):
        prev = {"count": 10, "sum": 5.0,
                "buckets": {"0.1": 8, "+Inf": 10}}
        cur = {"count": 13, "sum": 9.5,
               "buckets": {"0.1": 9, "+Inf": 13}}
        d = hist_delta(cur, prev)
        assert d == {"count": 3, "sum": 4.5,
                     "buckets": {"0.1": 1, "+Inf": 3}}
        # a restarted role (counter reset) falls back to cur
        assert hist_delta(prev, cur) == prev
        assert hist_delta({}, prev) == {}
        assert hist_delta(cur, None) == cur

    def test_catchup_judging_never_uses_lookahead_accuracy(self):
        """Review regression: a catch-up pass (async burst / dark
        writer) judges earlier rounds AFTER later, better commits are
        known — the regression baseline must be the best accuracy
        strictly BEFORE each round, or a healthily improving run
        pages accuracy_progress falsely."""
        f = RoundForensics(SLOEngine())        # default objectives
        for r in range(7):
            f.observe({"type": "note", "t": 100.0 + r,
                       "name": "round_commit", "epoch": r,
                       "acc": 0.30 + 0.10 * r})
        # one late scrape triggers the catch-up over all 7 rounds
        f.observe({"type": "scrape", "t": 107.5, "epoch": 7,
                   "roles": {}, "coverage": {"answered": 1,
                                             "expected": 1,
                                             "missing": []}})
        rep = f.report()
        assert rep["slos"]["accuracy_progress"]["judged"] >= 6
        assert rep["slos"]["accuracy_progress"]["breaches"] == 0
        assert rep["alerts"] == 0
        # ...while a real regression still judges as a drop
        tl = f.timeline
        assert tl.slo_summary(3)["acc_drop_from_best"] < 0
        f.observe({"type": "note", "t": 108.0, "name": "round_commit",
                   "epoch": 7, "acc": 0.50})
        assert tl.slo_summary(7)["acc_drop_from_best"] == \
            pytest.approx(0.40)

    def test_darkened_writer_does_not_break_hist_deltas(self):
        """Review regression: a scrape the writer missed (chaos kill)
        must not clobber the previous answered snapshot — the next
        answered scrape's per-round histogram delta would otherwise
        silently fall back to the whole-run cumulative."""
        def _writer_snap(count):
            cum = {"0.1": count, "+Inf": count}
            return {"metrics": {"certify_latency_seconds": {
                "type": "histogram",
                "samples": [{"labels": {}, "count": count,
                             "sum": 0.05 * count, "buckets": cum}]}}}

        tl = RoundTimeline()
        for r, roles in enumerate([{"writer": _writer_snap(10)},
                                   {},                  # writer dark
                                   {"writer": _writer_snap(30)}]):
            tl.observe({"type": "note", "t": 100.0 + r,
                        "name": "round_commit", "epoch": r})
            tl.observe({"type": "scrape", "t": 100.1 + r,
                        "epoch": r + 1, "roles": roles,
                        "coverage": {"answered": len(roles),
                                     "expected": 2, "missing": []}})
        d = tl.scrapes[2][0]["certify_hist"]
        assert d["count"] == 20                 # 30 - 10, not 30
        assert d["buckets"]["+Inf"] == 20

    def test_gc_bounds_every_stream(self):
        """The keep_rounds bound holds for wall-clock streams too — a
        thousands-of-rounds soak must not grow driver memory linearly
        in notes/faults."""
        tl = RoundTimeline(keep_rounds=8)
        for r in range(50):
            tl.observe({"type": "note", "t": 100.0 + r,
                        "name": "round_commit", "epoch": r})
            tl.observe({"type": "fault", "t": 100.5 + r,
                        "kind": "delay", "target": "c1"})
        assert len(tl.commits) == 8
        assert min(tl.commits) == 42
        assert all(f["t"] >= tl.commits[42]["t"] for f in tl.faults)
        assert all(n["t"] >= tl.commits[42]["t"] for n in tl.notes
                   if isinstance(n.get("t"), (int, float)))
        # retained rounds still join their faults
        assert tl.round_record(45)["faults"]

    def test_flight_events_anchor_commits_offline(self, tmp_path):
        """A SIGKILLed driver leaves no metrics.jsonl notes — the
        writer's flight dump still anchors the rounds."""
        fpath = tmp_path / "writer.flight.jsonl"
        with open(fpath, "w") as fh:
            fh.write(json.dumps({"type": "flight_header",
                                 "role": "writer", "pid": 1,
                                 "reason": "test",
                                 "flushed_at": 0.0}) + "\n")
            for r in range(3):
                fh.write(json.dumps(
                    {"t": 100.0 + r, "kind": "event",
                     "name": "round_committed", "epoch": r,
                     "loss": 0.5 - 0.1 * r}) + "\n")
        tl = load_round_timeline(str(tmp_path))
        assert tl.rounds() == [0, 1, 2]
        assert tl.round_record(2)["wall_s"] == pytest.approx(1.0)
        assert tl.round_record(2)["commit"]["loss"] == pytest.approx(
            0.3)


# ------------------------------------------------- per-leaf satellite
class TestPerLeafHealth:
    def test_per_leaf_stats_match_hand_computation(self):
        layout = [("a", 0, 2), ("b", 2, 3)]
        mat = np.array([[3.0, 4.0, 1.0, 0.0, 0.0],
                        [0.0, 0.0, 2.0, 2.0, 1.0]], np.float32)
        ref = np.array([3.0, 4.0, 0.0, 0.0, 1.0], np.float32)
        s = per_leaf_stats(mat, layout, ref)
        assert s["a"]["l2"][0] == pytest.approx(5.0)
        assert s["a"]["cos"][0] == pytest.approx(1.0)
        assert s["a"]["l2"][1] == 0.0 and s["a"]["cos"][1] == 0.0
        assert s["b"]["l2"][1] == pytest.approx(3.0)

    def test_crit_names_the_flipped_leaf(self):
        """BFLC_HEALTH_PER_LEAF: a sender whose SINGLE layer is
        scaled/flipped gets that leaf ranked worst in its record."""
        rng = np.random.default_rng(7)
        dim_a, dim_b = 8, 8
        layout = [("layer_a", 0, dim_a), ("layer_b", dim_a, dim_b)]
        base = rng.standard_normal(dim_a + dim_b).astype(np.float32)
        hm = HealthMonitor(jsonl_path="", per_leaf=True)
        rec = None
        for ep in range(6):
            rows = [(base + 0.3 * rng.standard_normal(
                dim_a + dim_b)).astype(np.float32) for _ in range(10)]
            if ep >= 2:
                # only layer_b of sender 4 is attacked
                rows[4][dim_a:] = -40.0 * rows[4][dim_a:]
            rec = hm.on_round(
                epoch=ep, senders=[f"c{i}" for i in range(10)],
                rows=rows, weights=[10.0] * 10,
                selected=list(range(6)), leaf_layout=layout)
        by = {s["sender"]: s for s in rec["senders"]}
        assert by["c4"]["level"] == "crit"
        leaves = by["c4"]["leaves"]
        assert leaves and leaves[0]["key"] == "layer_b"
        assert leaves[0]["ratio"] > leaves[-1]["ratio"] \
            or len(leaves) == 1
        # honest senders carry no leaf breakdown (lazy: flagged only)
        assert "leaves" not in by["c0"]

    def test_off_by_default(self, monkeypatch):
        monkeypatch.delenv("BFLC_HEALTH_PER_LEAF", raising=False)
        hm = HealthMonitor(jsonl_path="")
        assert hm.per_leaf is False
        monkeypatch.setenv("BFLC_HEALTH_PER_LEAF", "1")
        assert HealthMonitor(jsonl_path="").per_leaf is True


# ----------------------------------------------------------- e2e drill
def _delta_for(client: int, epoch: int, base: np.ndarray,
               dim: int) -> np.ndarray:
    rng = np.random.default_rng([client, epoch, 1234])
    return (base + 0.3 * rng.standard_normal(dim)).astype(np.float32)


class _InProcCollector(FleetCollector):
    """The real FleetCollector against an in-process LedgerServer's
    dispatch surface (no sockets): the scrape tick, epoch stamping and
    forensics-observer wiring are all the production paths."""

    def __init__(self, server, **kw):
        super().__init__({"writer": ("127.0.0.1", 0)}, {}, **kw)
        self._server = server

    def _scrape_rpc(self, role, ep):
        r = self._server._dispatch("telemetry", {})
        snap = r.get("snapshot")
        rep_ep = r.get("epoch")
        return (snap if r.get("ok") and isinstance(snap, dict)
                else None,
                rep_ep if isinstance(rep_ep, int) else None)


def _write_drill_spans(tdir, windows):
    """Synthesized wall-anchored span artifacts shaped exactly like
    obs.trace's (the live recorder is drilled in tests/test_trace.py;
    here the offline joiner consumes the artifact format): per round,
    one upload-op trace per participating client — the straggler's
    upload stretched across its injected delay — plus the writer's
    aggregate span."""
    sid = [0]

    def _span(trace, name, role, t0, t1, parent=None, epoch=None):
        sid[0] += 1
        s = {"trace": trace, "span": f"s{sid[0]:04d}", "name": name,
             "role": role, "t0": t0, "t1": t1}
        if parent:
            s["parent"] = parent
        if epoch is not None:
            s["epoch"] = epoch
        return s

    spans = []
    for ep, w in enumerate(windows):
        t0, t1 = w["t0"], w["t1"]
        for i, (sender, t_up) in enumerate(w["uploads"]):
            tr = f"t{ep:03d}-{i}"
            root = _span(tr, "client.upload_op", sender, t0 + 1e-4 * i,
                         t_up, epoch=ep)
            spans.append(root)
            spans.append(_span(tr, "upload", sender,
                               root["t0"] + 1e-5, t_up,
                               parent=root["span"]))
        spans.append(_span(f"t{ep:03d}-agg", "aggregate", "writer",
                           max(tu for _s, tu in w["uploads"]), t1,
                           epoch=ep))
    with open(os.path.join(tdir, "fleet.spans.jsonl"), "w") as fh:
        for s in spans:
            fh.write(json.dumps(s) + "\n")


def _run_forensics_drill(tdir, *, rounds=9, attacker="c19",
                         attack_from=10 ** 9, straggle_from=10 ** 9,
                         straggler="c09", delay_s=0.25,
                         latency_bound_s=0.12):
    """The scripted campaign: config-1 geometry against a real
    LedgerServer dispatch surface, the real FleetCollector scrape tick
    feeding the real RoundForensics joiner + SLO engine.  From
    `attack_from` the attacker's delta is sign-flipped and scaled
    (the health half); from `straggle_from` the round carries an
    injected `delay_s` straggler window + a chaos fault record (the
    heavytail latency half).  Returns (hashes, forensics, windows)."""
    from bflc_demo_tpu.comm.ledger_service import LedgerServer
    cfg = DEFAULT_PROTOCOL
    dim = 12
    rng = np.random.default_rng(99)
    base = rng.standard_normal(dim).astype(np.float32)
    blob0 = pack_pytree({"W": np.zeros(dim, np.float32)})
    obs_metrics.REGISTRY.reset()
    server = LedgerServer(cfg, blob0, require_auth=False,
                          stall_timeout_s=3600.0)
    collector = _InProcCollector(
        server, jsonl_path=os.path.join(tdir, "metrics.jsonl"))
    forensics = None
    if obs_slo.slo_armed():
        engine = SLOEngine(
            obs_slo.default_slos(round_latency_s=latency_bound_s),
            jsonl_path=os.path.join(tdir, "alerts.jsonl"))
        forensics = RoundForensics(engine)
        collector.add_observer(forensics.observe)
    addrs = [f"c{i:02d}" for i in range(cfg.client_num)]
    for a in addrs:
        assert server._dispatch("register", {"addr": a})["ok"]
    collector.note("fleet_up", clients=len(addrs))
    collector.scrape(tag="fleet_up")
    hashes, windows = [], []
    try:
        for _ in range(rounds):
            ep = server.ledger.epoch
            t_r0 = time.time()
            committee = server._dispatch("committee", {})["committee"]
            trainers = sorted(a for a in addrs if a not in committee)
            # fixed slots: attacker at 8, straggler LAST at 9 — the
            # scripted slot-ordered scores below keep both out of the
            # rotating committee forever, and the straggler's upload
            # genuinely arrives last when its stall is injected
            honest = [a for a in trainers
                      if a not in (attacker, straggler)]
            uploaders = (honest[:cfg.needed_update_count - 2]
                         + [attacker, straggler])
            straggling = ep >= straggle_from
            uploads = []
            for a in uploaders:
                if straggling and a == straggler:
                    # the heavytail leg: this client's upload stalls —
                    # the chaos fault record lands at the stall start
                    collector.observe_fault(
                        {"kind": "delay", "target": straggler,
                         "t": ep})
                    time.sleep(delay_s)
                d = _delta_for(addrs.index(a), ep, base, dim)
                if a == attacker and ep >= attack_from:
                    d = (-20.0 * d).astype(np.float32)
                blob = pack_pytree({"W": d})
                r = server._dispatch("upload", {
                    "addr": a, "blob": blob,
                    "hash": hashlib.sha256(blob).hexdigest(),
                    "n": 10, "cost": 1.0, "epoch": ep})
                assert r["ok"], (a, r)
                uploads.append((a, time.time()))
            row = [1.0 - 0.05 * j
                   for j in range(cfg.needed_update_count)]
            for a in committee:
                r = server._dispatch("scores", {"addr": a, "epoch": ep,
                                                "scores": row})
                assert r["ok"], (a, r)
            assert server.ledger.epoch == ep + 1, "round did not commit"
            hashes.append(server._model_hash)
            windows.append({"t0": t_r0, "t1": time.time(),
                            "uploads": uploads})
            collector.note("round_commit", epoch=ep, acc=0.9)
            collector.scrape(tag=f"round-{ep}")
    finally:
        server.close()
    _write_drill_spans(tdir, windows)
    return hashes, forensics, windows


class TestForensicsDrill:
    """The acceptance drill (ISSUE 14): heavytail + sign-flip campaign
    at config-1 geometry -> exactly the latency burn-rate alert and
    the health-budget alert, each within 2 rounds of its onset, zero
    false alerts on the clean leg; obs_query's critical path partitions
    round wall and names the faulted role; hashes byte-identical armed
    vs BFLC_SLO_LEGACY=1."""

    ROUNDS = 9
    ATTACK_FROM = 3
    STRAGGLE_FROM = 5

    def _campaign(self, tdir):
        return _run_forensics_drill(
            tdir, rounds=self.ROUNDS, attack_from=self.ATTACK_FROM,
            straggle_from=self.STRAGGLE_FROM)

    def test_clean_leg_zero_alerts(self, tmp_path, enabled_registry,
                                   monkeypatch):
        monkeypatch.delenv("BFLC_SLO_LEGACY", raising=False)
        monkeypatch.delenv("BFLC_HEALTH_LEGACY", raising=False)
        obs_health.install(str(tmp_path))
        try:
            _, forensics, _ = _run_forensics_drill(str(tmp_path),
                                                   rounds=self.ROUNDS)
        finally:
            obs_health.install("")
        assert forensics is not None
        rep = forensics.report()
        assert rep["alerts"] == 0
        assert not os.path.exists(tmp_path / "alerts.jsonl")
        # the plane did judge: every round joined and scored
        assert rep["rounds_joined"] >= self.ROUNDS
        assert rep["slos"]["round_latency"]["judged"] >= \
            self.ROUNDS - 1
        assert rep["slos"]["health_budget"]["breaches"] == 0

    def test_campaign_raises_both_alerts_within_two_rounds(
            self, tmp_path, enabled_registry, monkeypatch):
        monkeypatch.delenv("BFLC_SLO_LEGACY", raising=False)
        monkeypatch.delenv("BFLC_HEALTH_LEGACY", raising=False)
        obs_health.install(str(tmp_path))
        try:
            _, forensics, _ = self._campaign(str(tmp_path))
        finally:
            obs_health.install("")
        alerts = forensics.engine.alerts
        by_slo = {}
        for a in alerts:
            by_slo.setdefault(a["slo"], []).append(a)
        # ONLY the two expected objectives paged
        assert set(by_slo) == {"round_latency", "health_budget"}, \
            alerts
        lat = by_slo["round_latency"][0]
        # latency onset at STRAGGLE_FROM; paged within 2 rounds
        assert self.STRAGGLE_FROM <= lat["epoch"] \
            <= self.STRAGGLE_FROM + 1
        # first CRIT verdict needs the 2-round streak: onset+1; the
        # health-budget page lands within 2 rounds of the attack
        hb = by_slo["health_budget"][0]
        assert self.ATTACK_FROM <= hb["epoch"] <= self.ATTACK_FROM + 2
        # each page carries its own evidence: the joined round context
        assert lat["context"]["epoch"] == lat["epoch"]
        assert lat["summary"]["round_wall_s"] > 0.12
        assert hb["summary"]["health_verdict"] == 2
        # the durable artifact matches the in-memory engine
        disk = obs_slo.load_alerts(str(tmp_path))
        assert [(a["slo"], a["epoch"]) for a in disk] == \
            [(a["slo"], a["epoch"]) for a in alerts]
        # fault records joined onto the breach round
        ctx_faults = lat["context"]["faults"]
        assert any(f.get("target") == "c09" for f in ctx_faults)

    def test_obs_query_critical_path_partitions_and_names_faulted_role(
            self, tmp_path, enabled_registry, monkeypatch, capsys):
        monkeypatch.delenv("BFLC_SLO_LEGACY", raising=False)
        monkeypatch.delenv("BFLC_HEALTH_LEGACY", raising=False)
        obs_health.install(str(tmp_path))
        try:
            _, forensics, _ = self._campaign(str(tmp_path))
        finally:
            obs_health.install("")
        breach = forensics.engine.alerts[0]["epoch"] \
            if forensics.engine.alerts else self.STRAGGLE_FROM
        breach = max(breach, self.STRAGGLE_FROM)
        tool = _tool("obs_query")
        out_json = str(tmp_path / "query.json")
        assert tool.main([str(tmp_path), "--round", str(breach),
                          "--out", out_json]) == 0
        md = capsys.readouterr().out
        assert "Critical path" in md
        rec = json.load(open(out_json))["rounds"][0]
        tr = rec["trace"]
        # the partition property: segments sum EXACTLY to trace wall
        assert sum(d for _l, d in tr["segments"]) == pytest.approx(
            tr["wall_s"], rel=1e-6)
        # ...and trace wall is the round wall (same commit window)
        assert tr["wall_s"] == pytest.approx(rec["wall_s"], abs=0.15)
        # the faulted role is named: top straggler AND fault segment
        assert tr["stragglers"][0][0] == "c09"
        assert any("c09" in f.get("landed_in", "")
                   for f in tr["fault_segments"])
        assert "c09" in md
        # summary mode renders the whole campaign
        assert tool.main([str(tmp_path)]) == 0
        summary_md = capsys.readouterr().out
        assert "round_latency" in summary_md
        # --slo mode shows the page with context
        assert tool.main([str(tmp_path), "--slo",
                          "health_budget"]) == 0
        slo_md = capsys.readouterr().out
        assert "health_budget" in slo_md and "round" in slo_md

    def test_model_hashes_byte_identical_armed_vs_legacy(
            self, tmp_path, enabled_registry, monkeypatch):
        monkeypatch.delenv("BFLC_SLO_LEGACY", raising=False)
        monkeypatch.delenv("BFLC_HEALTH_LEGACY", raising=False)
        d1 = tmp_path / "armed"
        d2 = tmp_path / "legacy"
        d1.mkdir(), d2.mkdir()
        armed, f1, _ = _run_forensics_drill(
            str(d1), rounds=6, attack_from=2, straggle_from=4,
            delay_s=0.15)
        assert f1 is not None and f1.engine.alerts
        monkeypatch.setenv("BFLC_SLO_LEGACY", "1")
        legacy, f2, _ = _run_forensics_drill(
            str(d2), rounds=6, attack_from=2, straggle_from=4,
            delay_s=0.15)
        assert f2 is None                   # plane never armed
        assert not os.path.exists(d2 / "alerts.jsonl")
        assert armed == legacy
        assert len(set(armed)) == 6         # the model really moved

    def test_chaos_soak_operator_gates(self, tmp_path,
                                       enabled_registry, monkeypatch):
        """The verdict-gated operations satellite: --fail-on-crit /
        --fail-on-slo turn the campaign's artifacts into exit-code
        evidence; a clean run passes both gates."""
        monkeypatch.delenv("BFLC_SLO_LEGACY", raising=False)
        monkeypatch.delenv("BFLC_HEALTH_LEGACY", raising=False)
        soak = _tool("chaos_soak")
        dirty = tmp_path / "dirty"
        clean = tmp_path / "clean"
        dirty.mkdir(), clean.mkdir()
        obs_health.install(str(dirty))
        try:
            self._campaign(str(dirty))
        finally:
            obs_health.install("")
        obs_health.install(str(clean))
        try:
            _run_forensics_drill(str(clean), rounds=5)
        finally:
            obs_health.install("")
        g = soak.operator_gates(str(dirty), fail_on_crit=True,
                                fail_on_slo=True)
        assert g["crit_rounds"] and g["slo_alerts"]
        assert any("c19" in cr["flagged"] for cr in g["crit_rounds"])
        assert len(g["failures"]) == 2
        # gates observed but unarmed: evidence without failure
        g2 = soak.operator_gates(str(dirty))
        assert g2["crit_rounds"] and not g2["failures"]
        g3 = soak.operator_gates(str(clean), fail_on_crit=True,
                                 fail_on_slo=True)
        assert g3 == {"crit_rounds": [], "slo_alerts": [],
                      "storm_rounds": [], "failures": []}
        # gating without telemetry is itself a failure, not a pass
        g4 = soak.operator_gates("", fail_on_crit=True)
        assert g4["failures"]

    def test_incident_bundle_carries_the_story(self, tmp_path,
                                               enabled_registry,
                                               monkeypatch):
        monkeypatch.delenv("BFLC_SLO_LEGACY", raising=False)
        monkeypatch.delenv("BFLC_HEALTH_LEGACY", raising=False)
        obs_health.install(str(tmp_path))
        try:
            self._campaign(str(tmp_path))
        finally:
            obs_health.install("")
        bundle = _tool("incident_bundle")
        out = str(tmp_path / "incident.tar")
        manifest = bundle.build_bundle(str(tmp_path), out,
                                       slo="round_latency", k=2)
        assert manifest["alert"]["slo"] == "round_latency"
        with tarfile.open(out) as tar:
            names = tar.getnames()
            assert "narrative.md" in names
            assert "manifest.json" in names
            assert "metrics.slice.jsonl" in names
            assert "slices/writer.health.jsonl" in names
            assert "slices/alerts.jsonl" in names
            assert "slices/fleet.spans.jsonl" in names
            narrative = tar.extractfile("narrative.md").read().decode()
            # the cross-pillar story: the page, the straggler, the
            # attacker's flagged record all in one document
            assert "round_latency" in narrative
            assert "c09" in narrative
            assert "c19" in narrative
            # the sliced metrics stream re-parses and stays in window
            sliced = tar.extractfile("metrics.slice.jsonl"
                                     ).read().decode()
            lo, hi = manifest["window_rounds"]
            for line in sliced.splitlines():
                rec = json.loads(line)
                r = (round_of_scrape(rec)
                     if rec.get("type") == "scrape"
                     else rec.get("epoch"))
                if isinstance(r, int):
                    assert lo <= r <= hi
        # no matching alert + no --round is a clean error
        empty = tmp_path / "empty"
        empty.mkdir()
        with pytest.raises(ValueError):
            bundle.build_bundle(str(empty),
                                str(tmp_path / "x.tar"))


class TestObsQueryTool:
    def test_empty_dir_is_a_clean_error(self, tmp_path, capsys):
        tool = _tool("obs_query")
        assert tool.main([str(tmp_path)]) == 2
        assert tool.main([str(tmp_path / "nope")]) == 2
