"""Networked control plane: wire framing, the coordinator server, live
replication across OS processes, and the multi-process client federation
with crash recovery.

This is the test the reference answers with its deployment topology — 4
chain nodes + 21 client processes on loopback (README.md:162-183,
main.py:343-358) — realised for the TPU-native stack: every byte crosses a
real socket, every client is a real process, and replication is proven by
chained head-digest equality (the identical-loss-lines check of
imgs/runtime.jpg, made exact).
"""

import hashlib
import socket
import struct

import numpy as np
import pytest

from bflc_demo_tpu.comm.identity import Wallet, provision_wallets, _op_bytes
from bflc_demo_tpu.comm.ledger_service import (LedgerServer,
                                               CoordinatorClient)
from bflc_demo_tpu.comm.wire import (blob_bytes, send_msg, recv_msg,
                                     WireError)
from bflc_demo_tpu.protocol import ProtocolConfig
from bflc_demo_tpu.utils.serialization import (pack_pytree, unpack_pytree,
                                               pack_entries)

CFG = ProtocolConfig(client_num=6, comm_count=2, aggregate_count=2,
                     needed_update_count=3, learning_rate=0.05,
                     batch_size=16)


def _init_blob():
    return pack_pytree({"W": np.zeros((5, 2), np.float32),
                        "b": np.zeros((2,), np.float32)})


def _sign(wallet, kind, epoch, payload):
    return wallet.sign(_op_bytes(kind, wallet.address, epoch, payload)).hex()


class TestWire:
    def test_roundtrip(self):
        a, b = socket.socketpair()
        send_msg(a, {"method": "x", "blob": "ab" * 100})
        assert recv_msg(b) == {"method": "x", "blob": "ab" * 100}
        a.close()
        assert recv_msg(b) is None      # clean EOF
        b.close()

    def test_oversized_frame_rejected(self):
        a, b = socket.socketpair()
        a.sendall(struct.pack(">I", (1 << 30)))
        with pytest.raises(WireError):
            recv_msg(b)
        a.close()
        b.close()

    def test_garbage_frame_rejected(self):
        a, b = socket.socketpair()
        a.sendall(struct.pack(">I", 4) + b"\xff\xfe\x00\x01")
        with pytest.raises(WireError):
            recv_msg(b)
        a.close()
        b.close()


@pytest.fixture
def server():
    srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                       stall_timeout_s=60.0, ledger_backend="python")
    srv.start()
    yield srv
    srv.close()


@pytest.fixture
def auth_server():
    srv = LedgerServer(CFG, _init_blob(), require_auth=True,
                       stall_timeout_s=60.0, ledger_backend="python")
    srv.start()
    yield srv
    srv.close()


def _register_all(client, n=CFG.client_num):
    addrs = [f"0x{i:040x}" for i in range(n)]
    for a in addrs:
        r = client.request("register", addr=a)
        assert r["ok"], r
    return addrs


class TestCoordinatorServer:
    def test_full_round_over_socket(self, server):
        """A complete protocol round where every interaction is a socket
        frame: register -> upload (blob+hash) -> scores -> server-side
        aggregation -> new model published under its content hash."""
        c = CoordinatorClient(server.host, server.port)
        addrs = _register_all(c)
        assert c.request("info")["epoch"] == 0

        committee = c.request("committee")["committee"]
        trainers = [a for a in addrs if a not in committee]
        blobs = {}
        for i, a in enumerate(trainers[:3]):
            delta = {"W": np.full((5, 2), float(i + 1), np.float32),
                     "b": np.zeros((2,), np.float32)}
            blob = pack_pytree(delta)
            digest = hashlib.sha256(blob).digest()
            blobs[a] = (delta, digest)
            r = c.request("upload", addr=a, blob=blob.hex(),
                          hash=digest.hex(), n=100, cost=1.0, epoch=0)
            assert r["ok"], r

        ups = c.request("updates")["updates"]
        assert len(ups) == 3
        # blob fetch round-trips bit-exactly
        got = blob_bytes(c.request("blob", hash=ups[0]["hash"])["blob"])
        assert hashlib.sha256(got).digest().hex() == ups[0]["hash"]

        for j, comm in enumerate(committee):
            scores = [0.9, 0.5, 0.1] if j == 0 else [0.8, 0.6, 0.2]
            r = c.request("scores", addr=comm, epoch=0, scores=scores)
            assert r["ok"], r

        info = c.request("info")
        assert info["epoch"] == 1               # aggregation fired
        mr = c.request("model")
        flat = unpack_pytree(blob_bytes(mr["blob"]))
        # top-2 by median are trainers 0 and 1 (equal weights): mean delta
        # W = 1.5 everywhere, so W = -lr * 1.5
        np.testing.assert_allclose(flat["['W']"],
                                   -CFG.learning_rate * 1.5, atol=1e-6)
        assert mr["hash"] == hashlib.sha256(
            blob_bytes(mr["blob"])).digest().hex()
        c.close()

    def test_wrong_hash_rejected(self, server):
        c = CoordinatorClient(server.host, server.port)
        _register_all(c)
        blob = pack_pytree({"W": np.ones((5, 2), np.float32),
                            "b": np.zeros((2,), np.float32)})
        r = c.request("upload", addr="0x" + "0" * 40, blob=blob.hex(),
                      hash="00" * 32, n=1, cost=0.0, epoch=0)
        assert not r["ok"] and r["status"] == "BAD_ARG"
        c.close()

    def test_structurally_mismatched_delta_rejected_at_upload(self, server):
        """A delta missing leaves / with wrong shapes must be refused at
        the upload boundary — never accepted and left to blow up inside
        aggregation on a committee member's scores call."""
        c = CoordinatorClient(server.host, server.port)
        _register_all(c)
        for bad in ({"W": np.ones((5, 2), np.float32)},         # missing b
                    {"W": np.ones((5, 3), np.float32),          # bad shape
                     "b": np.zeros((2,), np.float32)},
                    {"W": np.ones((5, 2), np.float32),          # extra leaf
                     "b": np.zeros((2,), np.float32),
                     "c": np.zeros((1,), np.float32)},
                    {"W": np.full((5, 2), "x"),                 # bad dtype:
                     "b": np.zeros((2,), np.float32)}):         # U1 strings
            blob = pack_pytree(bad)
            digest = hashlib.sha256(blob).digest()
            r = c.request("upload", addr="0x" + "0" * 40, blob=blob.hex(),
                          hash=digest.hex(), n=1, cost=0.0, epoch=0)
            assert not r["ok"] and r["status"] == "BAD_ARG", r
        assert c.request("info")["update_count"] == 0
        c.close()

    def test_wait_blocks_until_log_grows(self, server):
        c = CoordinatorClient(server.host, server.port)
        base = c.request("info")["log_size"]
        import threading, time
        t0 = time.monotonic()

        def later():
            time.sleep(0.3)
            c2 = CoordinatorClient(server.host, server.port)
            c2.request("register", addr="0x" + "1" * 40)
            c2.close()

        threading.Thread(target=later, daemon=True).start()
        r = c.request("wait", log_size=base, timeout_s=10.0)
        assert r["log_size"] == base + 1
        assert time.monotonic() - t0 >= 0.25
        c.close()

    def test_unknown_method(self, server):
        c = CoordinatorClient(server.host, server.port)
        assert not c.request("frobnicate")["ok"]
        c.close()


class TestAuthenticatedServer:
    def test_signed_round_trip_and_forgeries(self, auth_server):
        srv = auth_server
        wallets, _ = provision_wallets(CFG.client_num, b"net-master-000001")
        c = CoordinatorClient(srv.host, srv.port)
        for w in wallets:
            r = c.request("register", addr=w.address,
                          pubkey=w.public_bytes.hex(),
                          tag=_sign(w, "register", 0, b""))
            assert r["ok"], r
        # address/pubkey mismatch
        x = Wallet.from_seed(b"intruder")
        r = c.request("register", addr=wallets[0].address,
                      pubkey=x.public_bytes.hex(),
                      tag=_sign(x, "register", 0, b""))
        assert not r["ok"]
        # unsigned upload
        by_addr = {w.address: w for w in wallets}
        committee = set(c.request("committee")["committee"])
        trainer = next(w for w in wallets if w.address not in committee)
        blob = pack_pytree({"W": np.ones((5, 2), np.float32),
                            "b": np.zeros((2,), np.float32)})
        digest = hashlib.sha256(blob).digest()
        r = c.request("upload", addr=trainer.address, blob=blob.hex(),
                      hash=digest.hex(), n=10, cost=1.0, epoch=0, tag="")
        assert not r["ok"]
        # properly signed upload
        payload = digest + struct.pack("<qd", 10, 1.0)
        r = c.request("upload", addr=trainer.address, blob=blob.hex(),
                      hash=digest.hex(), n=10, cost=1.0, epoch=0,
                      tag=_sign(trainer, "upload", 0, payload))
        assert r["ok"], r
        # another wallet signing for the trainer's address
        other = next(w for w in wallets
                     if w.address not in committee and w is not trainer)
        blob2 = pack_pytree({"W": np.full((5, 2), 2.0, np.float32),
                             "b": np.zeros((2,), np.float32)})
        d2 = hashlib.sha256(blob2).digest()
        p2 = d2 + struct.pack("<qd", 10, 1.0)
        forged = other.sign(_op_bytes("upload", trainer.address, 0, p2)).hex()
        r = c.request("upload", addr=trainer.address, blob=blob2.hex(),
                      hash=d2.hex(), n=10, cost=1.0, epoch=0, tag=forged)
        assert not r["ok"]
        # verbatim replay of the accepted upload: the server's seen-tag set
        # must reject it at the AUTH layer with DUPLICATE ("already in",
        # the retry-safe signal) before the ledger is even consulted — the
        # same tri-state AuthenticatedLedger enforces, so the two
        # boundaries can't drift
        r = c.request("upload", addr=trainer.address, blob=blob.hex(),
                      hash=digest.hex(), n=10, cost=1.0, epoch=0,
                      tag=_sign(trainer, "upload", 0, payload))
        assert not r["ok"] and r["status"] == "DUPLICATE", r
        c.close()


class TestSocketDifferential:
    def test_socket_and_inprocess_ledgers_agree(self, server):
        """Driving the same protocol sequence through the socket dispatch
        and through a direct in-process ledger must produce byte-identical
        chained heads — the server's framing/auth layers may never perturb
        state-machine semantics."""
        from bflc_demo_tpu.ledger import make_ledger, LedgerStatus
        direct = make_ledger(CFG, backend="python")
        c = CoordinatorClient(server.host, server.port)
        addrs = _register_all(c)
        for a in addrs:
            assert direct.register_node(a) == LedgerStatus.OK
        committee = c.request("committee")["committee"]
        trainers = [a for a in addrs if a not in committee]
        for i, a in enumerate(trainers[:3]):
            blob = pack_pytree({"W": np.full((5, 2), float(i), np.float32),
                                "b": np.zeros((2,), np.float32)})
            digest = hashlib.sha256(blob).digest()
            assert c.request("upload", addr=a, blob=blob.hex(),
                             hash=digest.hex(), n=50 + i, cost=0.5,
                             epoch=0)["ok"]
            assert direct.upload_local_update(a, digest, 50 + i, 0.5,
                                              0) == LedgerStatus.OK
        for j, comm in enumerate(committee):
            scores = [0.9 - j * 0.1, 0.5, 0.3]
            assert c.request("scores", addr=comm, epoch=0,
                             scores=scores)["ok"]
            assert direct.upload_scores(comm, 0,
                                        scores) == LedgerStatus.OK
        # the server aggregated+committed on the last score; mirror it with
        # the server's own model hash so the commit ops are byte-identical
        info = c.request("info")
        assert info["epoch"] == 1
        mr = c.request("model")
        assert direct.commit_model(bytes.fromhex(mr["hash"]),
                                   0) == LedgerStatus.OK
        assert direct.log_size() == info["log_size"]
        assert direct.log_head().hex() == info["log_head"]
        c.close()


class TestReplication:
    def test_in_thread_replica_head_equality(self, server):
        """Subscribe from op 0, replay, compare chained heads."""
        from bflc_demo_tpu.comm.ledger_service import replicate
        c = CoordinatorClient(server.host, server.port)
        _register_all(c)
        size = c.request("info")["log_size"]
        replica = replicate(server.host, server.port, CFG,
                            ledger_backend="python", until_ops=size,
                            timeout_s=30.0)
        assert replica.log_head().hex() == c.request("info")["log_head"]
        assert replica.num_registered == CFG.client_num
        c.close()


def _occupancy_shards(n_clients, per_shard=250):
    from bflc_demo_tpu.data import load_occupancy, iid_shards
    xtr, ytr, xte, yte = load_occupancy()
    shards = iid_shards(xtr[: n_clients * per_shard],
                        ytr[: n_clients * per_shard], n_clients)
    return shards, (xte[:500], yte[:500])


@pytest.mark.slow
class TestProcessFederation:
    """Real OS processes end to end (coordinator + clients + replica)."""

    def test_converges_across_process_boundaries(self):
        """1 writer + 3 live replicas — the reference's 4-node topology
        (README.md:162-183), every replica reproducing the writer's head."""
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        shards, test_set = _occupancy_shards(CFG.client_num)
        res = run_federated_processes(
            "make_softmax_regression", shards, test_set, CFG,
            rounds=4, stall_timeout_s=20.0, timeout_s=420.0, replicas=3)
        assert res.rounds_completed >= 4
        assert res.best_accuracy() > 0.85, res.accuracy_history
        assert res.replica_report["ok"]
        assert res.replica_report["head"] == res.ledger_log_head

    def test_crash_recovery_across_processes(self):
        """Kill a trainer AND a committee member (real process exits) at
        epoch 1; the coordinator's failure detector must close/reseat/force
        so later rounds still complete — the reference deadlocks here."""
        from bflc_demo_tpu.client.process_runtime import \
            run_federated_processes
        shards, test_set = _occupancy_shards(CFG.client_num)
        # client 0 is in the bootstrap committee (first comm_count ids);
        # client 5 is a trainer
        res = run_federated_processes(
            "make_softmax_regression", shards, test_set, CFG,
            rounds=3, crash_at={0: 1, 5: 1},
            stall_timeout_s=4.0, timeout_s=420.0)
        assert res.rounds_completed >= 3
        assert sorted(res.recovered_clients) == [0, 5]
        assert res.replica_report["ok"]


class TestGasMetering:
    """Admission-control cost metering (reference parity: every storage op
    is gas-metered, CommitteePrecompiled.cpp:143,151,468-469).  Storage
    ops debit a per-sender, per-epoch budget at the socket boundary,
    AFTER signature verification (gas binds to a proven identity — a
    spoofed address must not drain a victim's budget) and BEFORE any
    state mutation, so one identity cannot make the coordinator store
    unbounded traffic; queries stay free."""

    def test_budget_exhaustion_rejects_storage_ops(self):
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python",
                           gas_budget_per_epoch=2_500)
        srv.start()
        from bflc_demo_tpu.comm.ledger_service import GAS_REGISTER
        assert 2 * GAS_REGISTER <= 2_500 < 3 * GAS_REGISTER
        c = CoordinatorClient(srv.host, srv.port, timeout_s=10.0)
        try:
            addr = "0x" + "ab" * 20
            r1 = c.request("register", addr=addr)
            assert r1["ok"]
            r2 = c.request("register", addr=addr)       # rejected: still
            assert r2["status"] == "ALREADY_REGISTERED"  # costs gas
            r3 = c.request("register", addr=addr)
            assert r3["status"] == "OUT_OF_GAS" and not r3["ok"]
            # queries remain free — the server still answers
            assert c.request("info")["ok"]
            # and an unmetered sender is unaffected
            assert c.request("register", addr="0x" + "cd" * 20)["ok"]
        finally:
            c.close()
            srv.close()

    def test_upload_gas_scales_with_blob_bytes(self):
        """A giant blob from one sender exhausts its own budget without
        touching the ledger or the blob store."""
        srv = LedgerServer(CFG, _init_blob(), require_auth=False,
                           stall_timeout_s=60.0, ledger_backend="python",
                           gas_budget_per_epoch=10_000)
        srv.start()
        c = CoordinatorClient(srv.host, srv.port, timeout_s=10.0)
        try:
            for i in range(CFG.client_num):
                assert c.request("register", addr=f"0x{i:040x}")["ok"]
            committee = set(c.request("committee")["committee"])
            trainer = next(f"0x{i:040x}" for i in range(CFG.client_num)
                           if f"0x{i:040x}" not in committee)
            big = bytes(64 * 1024)                     # 64 KiB >> budget
            digest = hashlib.sha256(big).digest()
            r = c.request("upload", addr=trainer, blob=big.hex(),
                          hash=digest.hex(), n=10, cost=1.0, epoch=0)
            assert r["status"] == "OUT_OF_GAS"
            assert srv.ledger.update_count == 0
            # the sender's legitimate-sized retry this epoch is also out
            # of gas (the budget is spent) — but a DIFFERENT sender works
            blob = pack_pytree({"W": np.ones((5, 2), np.float32),
                                "b": np.zeros((2,), np.float32)})
            other = next(a for i in range(CFG.client_num)
                         if (a := f"0x{i:040x}") not in committee
                         and a != trainer)
            d2 = hashlib.sha256(blob).digest()
            r2 = c.request("upload", addr=other, blob=blob.hex(),
                           hash=d2.hex(), n=10, cost=1.0, epoch=0)
            assert r2["ok"], r2
        finally:
            c.close()
            srv.close()

    def test_spoofed_address_cannot_drain_victim_budget(self):
        """Gas binds to a PROVEN identity: a forged-signature request
        naming a victim's address is rejected before any charge, so the
        victim's own ops still fit their budget."""
        wallets, directory = provision_wallets(2, b"gas-auth-master-01")
        victim, attacker = wallets
        srv = LedgerServer(CFG, _init_blob(), directory=directory,
                           stall_timeout_s=60.0, ledger_backend="python",
                           gas_budget_per_epoch=1_500)   # one register
        srv.start()
        c = CoordinatorClient(srv.host, srv.port, timeout_s=10.0)
        try:
            # attacker spams registers AS the victim with its own key
            for _ in range(5):
                r = c.request(
                    "register", addr=victim.address,
                    pubkey=victim.public_bytes.hex(),
                    tag=attacker.sign(_op_bytes(
                        "register", victim.address, 0, b"")).hex())
                assert not r["ok"] and r["status"] == "BAD_ARG"
            # the victim's genuine register still has budget
            r = c.request("register", addr=victim.address,
                          pubkey=victim.public_bytes.hex(),
                          tag=victim.sign(_op_bytes(
                              "register", victim.address, 0, b"")).hex())
            assert r["ok"], r
        finally:
            c.close()
            srv.close()
